"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py): applies an Optimizer
to a ParameterDict, syncing gradients through a KVStore.

step(batch_size) = allreduce_grads() + update() — identical contract to the
reference (CS2 in SURVEY.md).  On a sharded mesh the allreduce is in-graph
(psum inserted by XLA via the parallel module); here the KVStore handles
replica reduction + optional DCN sync.

The hot path is FUSED by default (``fuse_step``): the gradient allreduce
runs through ``KVStore.pushpull_fused`` (one collective per ~4 MB bucket
instead of one per key) and the optimizer update through
``optimizer.FusedUpdater`` (the whole parameter pytree in one donated
jit dispatch, see optimizer/fused.py).  Anything the fused path cannot
express — kvstore-side updates, gradient compression, sparse gradients —
falls back to the eager per-parameter loop transparently, per step.

Under ``MXNET_SPMD=1`` (or ``Trainer(spmd=True)``) the whole step tail
unifies further: gradient reduce AND optimizer update run as ONE jit
program over a named mesh spanning the replica devices (and, on dist
kvstores with a local update, every process), with optimizer states
sharded across the data axis (ZeRO-1) — see optimizer/spmd.py and
docs/sharding.md.  The same transparent fallbacks apply, and states
hand off losslessly when a step must take the per-replica path.
"""
from __future__ import annotations

import pickle
import warnings
import weakref
from typing import Dict, List, Optional, Union

from .. import kvstore as kvs_mod
from .. import optimizer as opt_mod
from ..base import MXNetError
from ..resilience import chaos as _chaos
from ..telemetry import instruments as _ins
from ..telemetry import mxgoodput as _goodput
from ..telemetry import mxprof as _mxprof
from ..telemetry import tracing as _tracing
from ..util import env as _env
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


def _phase_metric(phase: str):
    """Histogram child for a step phase — None when telemetry is off
    (a profiler-only capture must not register zero-count phantom
    families in the scrape registry)."""
    return _ins.training_phase_seconds(phase) if _tracing._ENABLED \
        else None


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None, fuse_step=None, spmd=None):
        if isinstance(params, (dict, ParameterDict)):
            params = [params[k] for k in sorted(params.keys())] \
                if isinstance(params, dict) else list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a ParameterDict/dict/list")
        self._params: List[Parameter] = []
        self._param2idx: Dict[str, int] = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p!r}")
            self._param2idx[p.name] = i
            self._params.append(p)
            p._trainer = self
        optimizer_params = optimizer_params or {}
        self._scale = optimizer_params.get("rescale_grad", 1.0)
        self._init_optimizer(optimizer, optimizer_params)
        # normalized to None when falsy: _init_kvstore only configures
        # compression for truthy values, and the fused-path gate must
        # agree with it (a literal {} configures nothing)
        self._compression_params = compression_params or None
        self._kvstore_kind = kvstore
        self._kvstore: Optional[kvs_mod.KVStore] = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._states_to_load = None
        # None = auto: fuse when the optimizer has a fused path and
        # nothing forces key-level treatment (resolved after kv init)
        self._fuse_step = fuse_step
        self._fuse_active: Optional[bool] = None
        # None = follow MXNET_SPMD; True/False force.  When engaged,
        # step() runs gradient reduce + optimizer apply as ONE program
        # over the replica mesh with ZeRO-sharded states
        # (optimizer/spmd.py); anything it cannot express falls back to
        # the per-replica path below, states handed off losslessly.
        self._spmd_step = spmd
        self._spmd_active: Optional[bool] = None
        self._spmd_updater = None
        # separate latch for the UPDATE half only: an optimizer/dtype
        # combination the fused updater can't express must not forfeit
        # the (independent) bucketed gradient allreduce
        self._fuse_update_ok = True
        # resilience.AutoCheckpoint attaches itself here; None costs
        # one attribute check per step
        self._auto_ckpt = None

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params and set(optimizer_params) - {"rescale_grad"}:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer, param_dict=param_dict,
                                             **optimizer_params)
        # one updater per context replica (ref: Trainer._updaters) — each
        # replica must own its optimizer state; allocated lazily once the
        # context list is known.  FusedUpdater extends Updater (same state
        # dict, same serialized payload) and its inherited per-parameter
        # __call__ is the eager fallback path.
        self._updaters: List[opt_mod.FusedUpdater] = []

    def _new_updater(self) -> opt_mod.FusedUpdater:
        return opt_mod.FusedUpdater(self._optimizer)

    def _fuse_resolved(self) -> bool:
        """Whether the fused step path is engaged (decided once, after
        the kvstore mode is known).  Explicit ``fuse_step=True`` against
        an unfusable configuration falls back with one warning — the
        fused path is a pure optimization, never a semantics change."""
        if self._fuse_active is None:
            allowed = (not self._update_on_kvstore
                       and self._compression_params is None
                       and self._optimizer.fused_static_key() is not None)
            if self._fuse_step is None:
                self._fuse_active = allowed
            elif self._fuse_step and not allowed:
                warnings.warn(
                    "Trainer(fuse_step=True) requires a local update "
                    "(no kvstore-side optimizer, no gradient "
                    "compression) and an optimizer with a fused path; "
                    "falling back to the eager per-parameter loop.",
                    UserWarning, stacklevel=3)
                self._fuse_active = False
            else:
                self._fuse_active = bool(self._fuse_step)
        return self._fuse_active

    def _init_kvstore(self):
        if self._kvstore_kind is None or self._kvstore_kind is False:
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            kind = self._kvstore_kind if isinstance(self._kvstore_kind, str) \
                else "device"
            self._kvstore = self._kvstore_kind \
                if isinstance(self._kvstore_kind, kvs_mod.KVStore) \
                else kvs_mod.create(kind)
            if self._compression_params:
                self._kvstore.set_gradient_compression(self._compression_params)
            if self._update_on_kvstore is None:
                # single-worker: local update is cheaper (no store copies)
                self._update_on_kvstore = self._kvstore.type.startswith("dist")
            if self._update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    self._kvstore.init(i, p.data())
        self._kv_initialized = True
        # mxprof HBM accounting pulls the optimizer-state share through
        # this provider at SAMPLE time (never per step); weakref so the
        # process-global recorder cannot pin a dead trainer.  Last
        # trainer to initialize wins — one training loop per process is
        # the accounting model.
        wself = weakref.ref(self)

        def _state_bytes_provider():
            t = wself()
            return (None, 1) if t is None else t.optimizer_state_bytes()

        _mxprof.set_state_bytes_provider(_state_bytes_provider)
        if self._states_to_load is not None:
            fname, allow_resize = self._states_to_load
            self.load_states(fname, allow_resize=allow_resize)
            self._states_to_load = None

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size: int, ignore_stale_grad: bool = False):
        """Forward through KVStore then optimizer (ref: Trainer.step).

        Resilience hooks: a chaos ``trainer.preempt`` plan sets the
        preemption flag at step entry (the stand-in for an async
        SIGTERM), and an attached AutoCheckpoint runs after the update
        — so a preemption observed during step N checkpoints AT step N
        and raises ``Preempted`` from the step-N boundary, never
        mid-update.  A ``trainer.numerics`` plan poisons one gradient
        bucket to NaN before the update — the mxhealth detection /
        skip_step fixture (backward has run by step(), so the
        gradients exist to corrupt)."""
        if _chaos._ACTIVE:
            _chaos.check("trainer.preempt")
            if _chaos.check("trainer.numerics") == "corrupt":
                self._corrupt_one_grad()
        if _goodput._ACTIVE:
            # goodput wiring: the FIRST step entry after a preemption
            # resume closes the recovery window — training is doing
            # useful work again (one falsy check when disabled)
            _goodput.on_step_entry()
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        if self._spmd_resolved() and self._step_spmd():
            if _tracing._ENABLED:
                _ins.training_steps_total().inc()
            if self._auto_ckpt is not None:
                self._auto_ckpt.on_step(self)
            return
        if not _tracing.active():  # disabled: one predicate check
            self._allreduce_grads()
            self._update(ignore_stale_grad)
        else:
            with _tracing.span("step", cat="training"):
                with _tracing.span("grad-allreduce", cat="training",
                                   metric=_phase_metric("grad-allreduce")):
                    self._allreduce_grads()
                with _tracing.span("optimizer-update", cat="training",
                                   metric=_phase_metric(
                                       "optimizer-update")):
                    self._update(ignore_stale_grad)
            if _tracing._ENABLED:
                _ins.training_steps_total().inc()
        if self._auto_ckpt is not None:
            self._auto_ckpt.on_step(self)

    def _corrupt_one_grad(self) -> bool:
        """Chaos ``trainer.numerics`` payload: NaN the first trainable
        parameter's gradient (every replica — a real numerics fault
        reduces into all of them).  Device-side multiply, no host
        sync."""
        for p in self._params:
            if p.grad_req == "null" or p._grad is None:
                continue
            for g in p.list_grad():
                g._data = g.data * float("nan")
            return True
        return False

    def _spmd_resolved(self) -> bool:
        """Whether the unified SPMD step path is engaged (decided once,
        after the kvstore mode is known).  Explicit ``spmd=True``
        against an incompatible configuration falls back with one
        warning — like the fused path, SPMD is a pure optimization,
        never a semantics change."""
        if self._spmd_active is None:
            want = self._spmd_step if self._spmd_step is not None \
                else _env.get_bool("MXNET_SPMD")
            allowed = (not self._update_on_kvstore
                       and self._compression_params is None
                       and self._optimizer.fused_static_key() is not None)
            if want and not allowed and self._spmd_step:
                warnings.warn(
                    "Trainer(spmd=True) requires a local update (no "
                    "kvstore-side optimizer, no gradient compression) "
                    "and an optimizer with a fused path; falling back "
                    "to the per-replica step.", UserWarning,
                    stacklevel=3)
            self._spmd_active = bool(want) and allowed
        return self._spmd_active

    def _dense_uniform_params(self):
        """Collect the (idxs, plist, nrep) that both single-dispatch
        update paths (SPMD mesh step, per-replica fused) require:
        every gradient dense, every param on the same replica count,
        and one shared ctx list.  Returns None when any of that fails —
        the caller falls back to its eager/per-replica route."""
        from ..ndarray.sparse import BaseSparseNDArray

        idxs: List[int] = []
        plist: List[Parameter] = []
        nrep = None
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            grads = p.list_grad()
            if any(isinstance(g, BaseSparseNDArray) for g in grads):
                return None
            if nrep is None:
                nrep = len(grads)
            elif len(grads) != nrep:
                return None  # ragged replica layout
            idxs.append(i)
            plist.append(p)
        if plist:
            ctxs = plist[0].list_ctx()
            if any(p.list_ctx() != ctxs for p in plist[1:]):
                return None  # mixed placement
        return idxs, plist, nrep

    def _step_spmd(self) -> bool:
        """One-program step over the replica mesh: gradient reduce +
        sharded optimizer apply in a single dispatch (optimizer/spmd).
        Returns False (caller runs the per-replica path) when this
        step's gradients are sparse, the layout is ragged/mixed, or the
        optimizer/dtype combination cannot take the mesh program — the
        latter disengages the path and hands the sharded states off to
        the per-replica updaters losslessly."""
        def bail() -> bool:
            """Structural fallback.  Before the mesh ever engaged this
            is a free per-step retry; once the SPMD updater owns the
            (sharded) optimizer states, a fallback step would silently
            run the per-replica path on FRESH zero states — so the
            path disengages permanently, handing the states off."""
            if self._spmd_updater is not None:
                self._spmd_disengage()
            return False

        collected = self._dense_uniform_params()
        if collected is None:
            return bail()
        idxs, plist, nrep = collected
        if not plist:
            return True
        if nrep > 1 and self._kvstore is None:
            # kvstore=None with replicas means the caller does NOT want
            # cross-replica reduction; the mesh program always reduces
            return bail()
        dist = self._kvstore is not None \
            and self._kvstore.type.startswith("dist")
        if self._spmd_updater is None:
            updater = opt_mod.SpmdUpdater(self._optimizer)
            if not updater.supports(
                    idxs, [p.list_data()[0] for p in plist]):
                self._spmd_active = False
                return False
            if any(u.states for u in self._updaters):
                # states accumulated on the per-replica path (a
                # load_states, or steps before SPMD engaged): replica 0
                # is canonical — re-shard it under the mesh
                updater.set_states(
                    self._updaters[0].get_states(dump_optimizer=False))
            self._spmd_updater = updater

        def run():
            self._spmd_updater.update_all_mesh(
                idxs, [p.list_grad() for p in plist],
                [p.list_data() for p in plist], dist=dist)

        try:
            if not _tracing.active():
                run()
                return True
            with _tracing.span("step", cat="training"):
                with _tracing.span("spmd-step", cat="training",
                                   metric=_phase_metric("spmd-step")):
                    run()
        except opt_mod.FusedUnsupported:
            self._spmd_disengage()
            return False
        if _tracing._ENABLED:
            _ins.spmd_step_total().inc()
        return True

    def _spmd_disengage(self):
        """Leave the SPMD path permanently (for this trainer), handing
        the sharded optimizer states off to the per-replica updaters so
        the fallback resumes exactly where the mesh program stopped."""
        updater, self._spmd_updater = self._spmd_updater, None
        self._spmd_active = False
        if updater is None or (not updater._bstate
                               and not updater._pstate
                               and not updater._pending):
            return
        payload = updater.get_states(dump_optimizer=False)
        ctxs = self._replica_ctxs()
        nrep = len(ctxs) if ctxs else 1
        while len(self._updaters) < nrep:
            self._updaters.append(self._new_updater())
        for r, u in enumerate(self._updaters):
            u.set_states(payload, ctx=ctxs[r] if ctxs else None)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        if not _tracing.active():
            self._allreduce_grads()
            return
        with _tracing.span("grad-allreduce", cat="training",
                           metric=_phase_metric("grad-allreduce")):
            self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        if self._fuse_resolved() and self._allreduce_grads_fused():
            return
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            grads = p.list_grad()
            if self._update_on_kvstore:
                # server-side update: push grads, pull fresh weights
                self._kvstore.pushpull(i, grads, out=p.list_data())
            elif len(grads) > 1 or self._kvstore.type.startswith("dist"):
                self._kvstore.push(i, grads)
                self._kvstore.pull(i, out=grads)

    def _allreduce_grads_fused(self) -> bool:
        """One bucketed pushpull over every dense gradient; returns
        False (caller runs the eager per-key loop) when a sparse
        gradient needs key-level treatment this step."""
        from ..ndarray.sparse import BaseSparseNDArray

        dist = self._kvstore.type.startswith("dist")
        keys, grads = [], []
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            g = p.list_grad()
            if len(g) > 1 or dist:
                if any(isinstance(x, BaseSparseNDArray) for x in g):
                    return False
                keys.append(i)
                grads.append(g)
        if keys:
            self._kvstore.pushpull_fused(keys, grads, out=grads)
        return True

    def update(self, batch_size: int, ignore_stale_grad: bool = False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        if not _tracing.active():
            self._update(ignore_stale_grad)
            return
        with _tracing.span("optimizer-update", cat="training",
                           metric=_phase_metric("optimizer-update")):
            self._update(ignore_stale_grad)
        if _tracing._ENABLED:
            _ins.training_steps_total().inc()

    def _update(self, ignore_stale_grad: bool = False):
        if self._update_on_kvstore:
            return  # weights already refreshed by pushpull
        if self._spmd_updater is not None:
            # manual allreduce_grads()+update() flow while the mesh
            # path holds the (sharded) optimizer states: the mesh
            # program would reduce the already-reduced grads again, and
            # the per-replica updaters below would start from fresh
            # zero states — hand the states off and stay per-replica
            self._spmd_disengage()
        if self._fuse_resolved() and self._fuse_update_ok \
                and self._update_fused():
            return
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            for r, (data, grad) in enumerate(zip(p.list_data(),
                                                 p.list_grad())):
                while len(self._updaters) <= r:
                    self._updaters.append(self._new_updater())
                self._updaters[r](i, grad, data)

    def _update_fused(self) -> bool:
        """Single-dispatch update: one FusedUpdater.update_all per
        replica.  Returns False (caller runs the eager loop) when this
        step's gradients are sparse, the replica layout is ragged, or
        placement is mixed (one program per device would be needed)."""
        collected = self._dense_uniform_params()
        if collected is None:
            return False
        idxs, plist, nrep = collected
        if not plist:
            return True
        while len(self._updaters) < nrep:
            self._updaters.append(self._new_updater())
        if not self._updaters[0].supports(
                idxs, [p.list_data()[0] for p in plist]):
            # static for the run (optimizer class + weight dtypes):
            # latch the UPDATE half to eager — no per-step probe, no
            # phantom fused-update span, no doomed retry — while the
            # bucketed allreduce keeps running
            self._fuse_update_ok = False
            return False

        def run():
            for r in range(nrep):
                u = self._updaters[r]
                # mxprof counts the program cost ONCE per step: every
                # replica runs the same executable, and the MFU
                # denominator is a single device's peak
                u.mxprof_report_cost = r == 0
                u.update_all(
                    idxs, [p.list_grad()[r] for p in plist],
                    [p.list_data()[r] for p in plist])

        try:
            if not _tracing.active():
                run()
                return True
            with _tracing.span("fused-update", cat="training",
                               metric=_phase_metric("fused-update")):
                run()
        except opt_mod.FusedUnsupported:
            # safety net (supports() should have caught it): replay
            # eagerly and stop taking the fused update path
            self._fuse_update_ok = False
            return False
        if _tracing._ENABLED:
            _ins.fused_step_total().inc()
        return True

    def optimizer_state_bytes(self):
        """(state_bytes, shard_factor) — the optimizer-state footprint
        one device carries is ``state_bytes / shard_factor``.  On the
        per-replica paths each replica holds a full copy (factor 1, the
        bytes are one updater's); under SPMD+ZeRO the global states
        split ``shard_factor`` ways.  mxprof's HBM sampling reads this
        through the provider registered in :meth:`_init_kvstore`."""
        def tree_bytes(s):
            if s is None:
                return 0
            if isinstance(s, (tuple, list)):
                return sum(tree_bytes(x) for x in s)
            try:
                return int(s.data.nbytes)  # NDArray leaf
            except AttributeError:
                return int(getattr(s, "nbytes", 0))

        if self._spmd_updater is not None:
            u = self._spmd_updater
            total = sum(tree_bytes(t) for t in u._bstate.values()) \
                + sum(tree_bytes(t) for t in u._pstate.values())
            return total, u.shard_factor()
        if not self._updaters:
            return 0, 1
        return sum(tree_bytes(s)
                   for s in self._updaters[0].states.values()), 1

    def _states_payload(self) -> bytes:
        """The serialized optimizer state for EVERY replica updater
        (the blob save_states writes and AutoCheckpoint snapshots).
        One replica keeps the reference single-payload format; multiple
        replicas wrap the per-replica payloads (each replica owns its
        own momentum/variance buffers — saving only replica 0 silently
        dropped the rest)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "optimizer state lives on the kvstore "
                "(update_on_kvstore); use save_states/"
                "kvstore.save_optimizer_states")
        if self._spmd_updater is not None:
            # gather-on-save: the SPMD updater emits the canonical
            # single-payload format (full-shape host tensors), loadable
            # onto ANY mesh shape or the per-replica paths
            return self._spmd_updater.get_states(dump_optimizer=False)
        if not self._updaters:
            self._updaters.append(self._new_updater())
        if len(self._updaters) == 1:
            return self._updaters[0].get_states(dump_optimizer=False)
        return pickle.dumps({"__mx_replica_states__": [
            u.get_states(dump_optimizer=False)
            for u in self._updaters]})

    def save_states(self, fname: str):
        """Persist optimizer state (see :meth:`_states_payload`)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=False)
            return
        with open(fname, "wb") as f:
            f.write(self._states_payload())

    def _replica_ctxs(self):
        """The context list the replica updaters map onto — the LONGEST
        ctx list across trainable parameters, because updater r serves
        replica r of every parameter that has one (ragged layouts run
        the eager loop but share the same updater list).  None when no
        trainable parameter is initialized yet."""
        best = None
        for p in self._params:
            if p.grad_req != "null" and p._data is not None:
                ctxs = p.list_ctx()
                if best is None or len(ctxs) > len(best):
                    best = ctxs
        return best

    def load_states(self, fname: str, allow_resize: bool = False):
        """Restore optimizer state.  ``allow_resize=True`` (the
        preemption-resume path) accepts a checkpoint whose replica
        count differs from this trainer's: sync data-parallel replicas
        hold identical state, so restoring onto FEWER replicas takes a
        prefix and onto more broadcasts replica 0.  The default stays
        strict — outside resume, a count mismatch is a wiring bug."""
        if not self._kv_initialized:
            self._states_to_load = (fname, allow_resize)
            return
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, "rb") as f:
            data = f.read()
        obj = pickle.loads(data)
        if self._spmd_updater is not None:
            # reshard-on-load: states re-shard lazily under whatever
            # mesh the next step runs on.  A per-replica wrapped
            # payload loads its replica 0 (replicas hold identical
            # state in sync training; the SPMD program keeps ONE copy)
            if isinstance(obj, dict) and "__mx_replica_states__" in obj:
                self._spmd_updater.set_states(
                    obj["__mx_replica_states__"][0])
            else:
                self._spmd_updater.set_states(data)
            return
        # size the updater list by the REPLICA count (knowable from the
        # parameters), not by how many updaters happen to exist — a
        # fresh trainer has none, and restoring fewer than the replica
        # count would leave later replicas stepping from zero state
        ctxs = self._replica_ctxs()
        nrep = len(ctxs) if ctxs else max(len(self._updaters), 1)
        while len(self._updaters) < nrep:
            self._updaters.append(self._new_updater())
        if isinstance(obj, dict) and "__mx_replica_states__" in obj:
            blobs = obj["__mx_replica_states__"]
            if len(blobs) != len(self._updaters):
                if not allow_resize:
                    raise MXNetError(
                        f"checkpoint {fname!r} holds {len(blobs)} "
                        f"replica states but this trainer runs "
                        f"{len(self._updaters)} replicas — a partial "
                        "restore would silently leave stale or zero "
                        "optimizer state on some replicas (pass "
                        "allow_resize=True on a preemption resume)")
                n = len(self._updaters)
                blobs = blobs[:n] if len(blobs) >= n \
                    else blobs + [blobs[0]] * (n - len(blobs))
            for r, (u, blob) in enumerate(zip(self._updaters, blobs)):
                u.set_states(blob, ctx=ctxs[r] if ctxs else None)
        else:
            # single-payload format: every replica gets the same state
            # (replicas hold identical state when training is in sync),
            # each placed on its own replica's device
            for r, u in enumerate(self._updaters):
                u.set_states(data, ctx=ctxs[r] if ctxs else None)
