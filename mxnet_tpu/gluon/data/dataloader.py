"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

The reference uses fork()ed worker processes with NDArrays in POSIX shm
(CPUSharedStorage) to parallelise decode/augment.  Forking a process that
holds a PjRt/TPU client is unsafe, so this loader offers two pools:

  * worker_pool="thread" (default): N worker threads + double-buffered
    prefetch.  Full speed when `__getitem__` releases the GIL
    (numpy/cv2/PIL decode); a PURE-python transform serializes on the
    GIL — measured crossover in docs/data.md.
  * worker_pool="process": persistent spawn()-based process pool (spawn,
    not fork, so no PjRt client is inherited; children run CPU-only
    jax).  Escapes the GIL for python-heavy `__getitem__` at the cost of
    one-time worker startup (a jax import per worker).  Batches travel
    through POSIX shared memory by default (worker_transport="shm", the
    reference's CPUSharedStorage role) — the worker writes arrays into
    a segment and ships only the descriptor; "pipe" selects plain
    pickling.

The C++ RecordIO pipeline (src/io, see native/) remains the
high-throughput path for ImageNet-style training.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

from ...analysis import sanitizer as _mxsan
from ...base import MXNetError
from ...ndarray.ndarray import NDArray, array as nd_array
from ...resilience import chaos as _chaos
from ...telemetry import instruments as _ins
from ...telemetry import tracing as _tracing
from ...util import env as _env
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "WorkerDied", "default_batchify_fn",
           "default_mp_batchify_fn"]


class WorkerDied(MXNetError):
    """A DataLoader worker (thread or spawned process) exited
    abnormally.  Raised in the CONSUMER with the worker's identity —
    never a silent short epoch, never a hang until the full batch
    timeout.  ``worker`` is the thread name or child pid."""

    def __init__(self, msg: str, worker=None):
        super().__init__(msg)
        self.worker = worker


def _observe_data_wait(t0: float) -> None:
    """Record one consumer-side wait-for-batch: the data-wait gauge +
    histogram (when telemetry is on) and a `data-wait` span in the
    trace (while the profiler captures).  A training step whose
    data-wait dominates is input-bound — the first thing step-time
    attribution must show."""
    dt = time.perf_counter() - t0
    if _tracing._ENABLED:
        _ins.data_wait_seconds().observe(dt)
        _ins.data_wait_last_seconds().set(dt)
    _tracing.record_complete("data-wait", "data", t0, dt)


def _stack_narrow(data):
    """Shared stacking + dtype narrowing (float64->float32,
    int64->int32) used by BOTH batchify variants — one policy."""
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    if arr.dtype == np.int64:
        arr = arr.astype(np.int32)
    return arr


def _numpy_batchify(data):
    """Child-process batchify: same stacking/dtype rules as
    default_batchify_fn but producing numpy (NDArray construction — and
    with it any jax device touch — stays in the parent)."""
    if isinstance(data[0], tuple):
        return tuple(_numpy_batchify(list(d)) for d in zip(*data))
    if isinstance(data[0], NDArray):
        data = [d.asnumpy() for d in data]
    return _stack_narrow(data)


# ---------------------------------------------------------------------------
# shared-memory batch transport (the CPUSharedStorage role, ref:
# src/storage/cpu_shared_storage_manager.cc): worker processes place the
# assembled batch in a POSIX shm segment and ship only its descriptor;
# the parent maps it with one explicit host copy (jax may alias numpy
# buffers, and the segment is unlinked right after).  vs the pipe this
# removes the serialize+pipe+deserialize copies (measured in
# DATALOADER_BENCH.json / docs/data.md).
# ---------------------------------------------------------------------------

def _shm_pack(out):
    """numpy tree -> (shm_name, spec); spec mirrors the tuple structure
    with ('a', shape, dtype_str, offset) leaves.  A segment is reclaimed
    immediately if packing fails partway — once the tracker registration
    is detached below, an abandoned segment would outlive the process."""
    from multiprocessing import shared_memory

    flat = []

    def walk(x):
        if isinstance(x, tuple):
            return ("t", tuple(walk(e) for e in x))
        a = np.ascontiguousarray(x)
        flat.append(a)
        return ("a", a.shape, a.dtype.str, 0)

    spec = walk(out)
    total = max(sum(a.nbytes for a in flat), 1)
    shm = shared_memory.SharedMemory(create=True, size=total)
    try:
        off = 0
        offs = []
        for a in flat:
            # write in place — tobytes() would add a full transient copy
            np.ndarray(a.shape, a.dtype, buffer=shm.buf,
                       offset=off)[...] = a
            offs.append(off)
            off += a.nbytes
    except Exception:
        try:
            shm.close()
            shm.unlink()
        except Exception:
            pass
        raise

    it = iter(offs)

    def fix(s):
        if s[0] == "t":
            return ("t", tuple(fix(e) for e in s[1]))
        return ("a", s[1], s[2], next(it))

    spec = fix(spec)
    name = shm.name
    # the parent owns the segment's lifetime: detach this process's
    # resource-tracker registration so the child's exit doesn't unlink
    # (nor warn about) a segment the parent is still reading
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass
    shm.close()
    return name, spec


def _shm_unpack(name, spec):
    """Attach, copy out into NDArrays (the jax device_put is the one
    unavoidable copy), then unlink the segment."""
    from multiprocessing import shared_memory

    shm = shared_memory.SharedMemory(name=name)
    try:
        def walk(s):
            if s[0] == "t":
                return tuple(walk(e) for e in s[1])
            _tag, shape, dt, off = s
            view = np.ndarray(shape, dtype=np.dtype(dt), buffer=shm.buf,
                              offset=off)
            # explicit host copy BEFORE unlink: jax may alias a numpy
            # buffer on the cpu backend, and the mapping dies below
            return nd_array(np.array(view))

        return walk(spec)
    finally:
        shm.close()
        shm.unlink()


def _drain_shm(pending, timeout=120):
    """Reclaim shm segments from unconsumed in-flight pool results.
    `timeout` is per result: callers pass the full loader timeout on a
    healthy teardown (a slow batch still packing must be waited out or
    its segment leaks) and a short cap on the post-error path (a dead
    worker must not stall the exit for timeout x window)."""
    from multiprocessing import shared_memory

    for res in pending:
        try:
            out = res.get(timeout)
        except Exception:
            continue  # failed batches packed nothing
        if isinstance(out, tuple) and len(out) == 3 \
                and out[0] == "__shm__":
            try:
                seg = shared_memory.SharedMemory(name=out[1])
                seg.close()
                seg.unlink()
            except Exception:
                pass


# spawn-child globals (one dataset/batchify per worker process)
_MP_STATE: dict = {}


def _mp_init(dataset, batchify_fn, transport="shm", chaos_specs=()):
    # Runs in EVERY worker — including ones the Pool maintenance thread
    # respawns later with the parent's normal env — so the TPU-safety
    # pinning must happen here, not around Pool construction.  jax is
    # already imported by the module bootstrap, but backends attach
    # lazily; the config override below is what the test conftest uses
    # for the same purpose and wins over plain env vars.
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    _MP_STATE["dataset"] = dataset
    _MP_STATE["batchify"] = batchify_fn
    _MP_STATE["transport"] = transport
    # chaos plans travel into the spawn child so worker-death injection
    # fires INSIDE the worker (each child runs its own call counters)
    _chaos.install_plans(list(chaos_specs))


def _mp_make_batch(indices):
    if _chaos._ACTIVE:
        action = _chaos.check("dataloader.worker")
        if action == "die":
            # simulated abnormal worker death: the parent must raise a
            # clear WorkerDied, not hang or return a short epoch
            os._exit(17)
    ds, bfn = _MP_STATE["dataset"], _MP_STATE["batchify"]
    out = bfn([ds[i] for i in indices])

    def dend(x):  # NDArray from a custom batchify -> plain numpy
        if isinstance(x, NDArray):
            return x.asnumpy()
        if isinstance(x, tuple):
            return tuple(dend(e) for e in x)
        return x

    out = dend(out)
    if _MP_STATE.get("transport") == "shm" and _all_arrays(out):
        try:
            return ("__shm__",) + _shm_pack(out)
        except Exception:
            pass  # fall back to pickling through the pool pipe
    return out


def _all_arrays(x):
    if isinstance(x, tuple):
        return all(_all_arrays(e) for e in x)
    return isinstance(x, np.ndarray)


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py::default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp

        return NDArray(jnp.stack([d.data for d in data]))
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(d)) for d in zip(*data))
    return nd_array(_stack_narrow(data))


default_mp_batchify_fn = default_batchify_fn


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False, timeout=120, worker_pool=None,
                 worker_transport="shm"):
        self._dataset = dataset
        self._timeout = timeout
        if worker_pool is None:
            worker_pool = "thread"  # docs/data.md: default rationale
        if thread_pool:
            worker_pool = "thread"  # reference-compat flag
        if worker_pool not in ("thread", "process"):
            raise MXNetError("worker_pool must be 'thread' or 'process'")
        if worker_transport not in ("shm", "pipe"):
            raise MXNetError("worker_transport must be 'shm' or 'pipe'")
        self._worker_pool = worker_pool
        self._worker_transport = worker_transport
        self._pool = None  # persistent spawn pool (created lazily)
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size is required when batch_sampler "
                                 "is not given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError("batch_size/shuffle/sampler/last_batch must not "
                             "be set with explicit batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        if prefetch is None:
            # tunable knob (mxtune sweep dimension); the explicit
            # prefetch= argument always wins, and the declared default
            # is dynamic: 2 * num_workers
            prefetch = _env.get_int("MXNET_PREFETCH_DEPTH")
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._resume_from = 0

    def resume_from(self, batch_idx: int) -> None:
        """Preemption-resume contract: the NEXT ``__iter__`` starts at
        `batch_idx` (0-based), skipping earlier batches without
        building them.  One-shot — the following epoch starts at 0.
        Determinism is the sampler's: with ``shuffle=True`` the caller
        must restore the RNG first (resilience.AutoCheckpoint does)."""
        self._resume_from = max(0, int(batch_idx))

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        start, self._resume_from = self._resume_from, 0
        if self._num_workers == 0:
            for bi, indices in enumerate(self._batch_sampler):
                if bi < start:
                    continue
                if not _tracing.active():
                    yield self._make_batch(indices)
                    continue
                t0 = time.perf_counter()
                batch = self._make_batch(indices)
                _observe_data_wait(t0)
                yield batch
            return
        if self._worker_pool == "process":
            yield from self._process_iter(start)
        else:
            yield from self._threaded_iter(start)

    # ---- spawn-based process pool ---------------------------------------
    def _get_pool(self):
        if self._pool is None:
            import multiprocessing as mp

            ctx = mp.get_context("spawn")
            bfn = self._batchify_fn
            if bfn is default_batchify_fn:
                bfn = _numpy_batchify  # NDArray assembly stays parent-side
            # children must never attach the (single-client) TPU:
            # _mp_init pins the CPU backend inside every worker (also
            # the ones the pool respawns later), so no parent-side env
            # juggling is needed here
            self._pool = ctx.Pool(
                self._num_workers, initializer=_mp_init,
                initargs=(self._dataset, bfn, self._worker_transport,
                          _chaos.export_plans("dataloader.worker")
                          if _chaos._ACTIVE else ()))
        return self._pool

    def _result_or_dead(self, res, pool, worker_pids):
        """``res.get`` sliced into short waits that watch worker
        liveness: a dead child (its pid reaped from, or respawned out
        of, ``pool._pool``) raises :class:`WorkerDied` NOW — its task
        is lost and the result would otherwise only surface as an
        opaque timeout a full ``self._timeout`` later."""
        import multiprocessing as mp

        deadline = time.monotonic() + self._timeout
        while True:
            remaining = deadline - time.monotonic()
            try:
                return res.get(min(0.5, max(remaining, 0.01)))
            except mp.TimeoutError:
                current = {w.pid for w in pool._pool}
                dead = sorted(
                    (worker_pids - current)
                    | {w.pid for w in pool._pool if not w.is_alive()})
                if dead:
                    raise WorkerDied(
                        f"DataLoader worker process(es) {dead} died "
                        f"abnormally; their in-flight batches are lost "
                        f"(the pool is discarded — recreate the "
                        f"iterator to continue)", worker=dead[0]) \
                        from None
                if remaining <= 0:
                    raise MXNetError(
                        f"DataLoader worker timed out after "
                        f"{self._timeout}s") from None

    def _process_iter(self, start: int = 0):
        """Strict-order prefetching over the persistent spawn pool;
        worker exceptions re-raise in the consumer (pickled through).
        In-flight shm results are reclaimed on ANY exit (early break,
        worker error, timeout) — the workers detach their shm
        registration, so an undrained descriptor would otherwise leak
        its /dev/shm segment until reboot."""
        from collections import deque

        pool = self._get_pool()
        worker_pids = {w.pid for w in pool._pool}
        batches = list(self._batch_sampler)[start:]
        window = max(self._prefetch, self._num_workers, 2)
        pending: deque = deque()
        it = iter(batches)
        timed_out = False
        died = False
        try:
            for _ in range(min(window, len(batches))):
                pending.append(pool.apply_async(_mp_make_batch,
                                                (next(it),)))
            while pending:
                res = pending.popleft()
                t0 = time.perf_counter() if _tracing.active() else None
                try:
                    out = self._result_or_dead(res, pool, worker_pids)
                except BaseException as e:
                    # the popped result may still arrive later and hold
                    # a shm segment — put it back so the drain sees it
                    pending.appendleft(res)
                    timed_out = True
                    if isinstance(e, WorkerDied):
                        # the respawned pool would re-lose the dead
                        # worker's task; start clean next iteration
                        died = True
                        try:
                            pool.terminate()
                        finally:
                            self._pool = None
                    raise
                if t0 is not None:
                    _observe_data_wait(t0)
                try:
                    pending.append(pool.apply_async(_mp_make_batch,
                                                    (next(it),)))
                except StopIteration:
                    pass
                yield self._wrap_np(out)
        finally:
            # healthy teardown (early break / epoch end) waits out slow
            # but live batches; after a worker timeout/crash, cap the
            # wait — those results mostly never arrive (and after a
            # terminated pool they NEVER arrive: shortest cap)
            _drain_shm(pending,
                       2 if died
                       else min(self._timeout, 15) if timed_out
                       else self._timeout)

    @staticmethod
    def _wrap_np(out):
        if isinstance(out, tuple):
            if len(out) == 3 and out[0] == "__shm__":
                return _shm_unpack(out[1], out[2])
            return tuple(DataLoader._wrap_np(o) for o in out)
        if isinstance(out, np.ndarray):
            return nd_array(out)
        return out

    def __del__(self):
        pool = getattr(self, "_pool", None)
        if pool is not None:
            try:
                pool.terminate()
            except Exception:
                pass

    def _threaded_iter(self, start: int = 0):
        """Prefetching iterator with N REAL worker threads (reference
        semantics: num_workers parallel batch producers).  Workers pull
        batch indices from a shared queue and publish into a reorder
        buffer keyed by batch position, so results stream strictly in
        sampler order; numpy/cv2/TF decode inside `__getitem__` releases
        the GIL, which is where the parallelism pays.

        A worker thread that dies without publishing (chaos-injected, or
        a C extension taking the thread down) surfaces as
        :class:`WorkerDied` at the consumer — the liveness check below —
        instead of a full-timeout hang for a batch that can never
        arrive."""
        batches = list(self._batch_sampler)[start:]
        n_workers = self._num_workers
        window = max(self._prefetch, n_workers, 2)  # in-flight bound
        task_q: "queue.Queue" = queue.Queue()
        # mxsan: the reorder buffer is shared by every worker and the
        # consumer; all access must hold done_cv (the tier-1 shutdown
        # regression test runs this loop under the sanitizer)
        done: dict = _mxsan.track(
            {}, "gluon.data.DataLoader._threaded_iter.done")
        done_cv = threading.Condition()
        stop = threading.Event()

        def worker():
            while True:
                item = task_q.get()
                if item is None or stop.is_set():  # sentinel: shut down
                    return
                pos, indices = item
                if _chaos._ACTIVE:
                    try:
                        if _chaos.check("dataloader.worker") == "die":
                            return  # abnormal exit: publish NOTHING
                    except BaseException as e:
                        with done_cv:
                            done[pos] = ("err", e)
                            done_cv.notify_all()
                        continue
                try:
                    result = ("ok", self._make_batch(indices))
                except BaseException as e:  # propagate to consumer
                    result = ("err", e)
                with done_cv:
                    done[pos] = result
                    done_cv.notify_all()

        next_submit = min(window, len(batches))
        for pos in range(next_submit):  # seed the prefetch window
            task_q.put((pos, batches[pos]))
        threads = [threading.Thread(target=worker, daemon=True,
                                    name=f"mx-dataloader-worker-{i}")
                   for i in range(n_workers)]
        for t in threads:
            t.start()
        try:
            for pos in range(len(batches)):
                t0 = time.perf_counter() if _tracing.active() else None
                deadline = time.monotonic() + self._timeout
                with done_cv:
                    while pos not in done:
                        dead = [t.name for t in threads
                                if not t.is_alive()]
                        if dead:
                            raise WorkerDied(
                                f"DataLoader worker thread(s) "
                                f"{dead} exited abnormally; batch "
                                f"{pos + start} will never arrive",
                                worker=dead[0])
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise MXNetError(
                                f"DataLoader worker timed out after "
                                f"{self._timeout}s (batch "
                                f"{pos + start})")
                        done_cv.wait(timeout=min(0.2, remaining))
                    kind, payload = done.pop(pos)
                if kind == "err":
                    raise payload
                if t0 is not None:
                    _observe_data_wait(t0)
                if next_submit < len(batches):  # top up the window
                    task_q.put((next_submit, batches[next_submit]))
                    next_submit += 1
                yield payload
        finally:
            stop.set()
            for _ in threads:
                task_q.put(None)

    def __len__(self):
        return len(self._batch_sampler)
