"""mxsan lock instrumentation: wrap ``threading.Lock`` / ``RLock`` /
``Condition`` construction so every acquire/release is recorded with
thread id and call site, feeding the lock-order graph.

Scope: only locks CONSTRUCTED from first-party code are wrapped.  The
patched factories inspect the caller's file and hand back the real
primitive for stdlib and site-packages callers (jax, queue,
concurrent.futures, ...) — instrumenting those would swamp the report
with third-party internals and blow the <3x overhead budget.

Conditions are real ``threading.Condition`` objects built over a
wrapped lock: the stdlib's ``_release_save`` / ``_acquire_restore`` /
``_is_owned`` protocol routes every ``wait()`` through our
bookkeeping, so a thread parked in ``cv.wait()`` correctly shows as
NOT holding the lock.
"""
from __future__ import annotations

import itertools
import os
import sys
import threading as _threading
from typing import Optional

from . import core

__all__ = ["patch", "unpatch", "patched", "SanLock", "SanRLock"]

# real factories, captured at import (before any patch can land)
_REAL_LOCK = _threading.Lock
_REAL_RLOCK = _threading.RLock
_REAL_CONDITION = _threading.Condition

_sid_counter = itertools.count(1)

# `/lib/python` covers both the stdlib and site-packages on every
# layout we run (system python, conda, venv); `<` covers eval/exec
# sources, which we DO instrument (test fixtures build locks there)
_FOREIGN = (f"{os.sep}lib{os.sep}python", "site-packages",
            f"{os.sep}importlib{os.sep}")

# the mxnet_tpu package itself is ALWAYS first-party, even when it is
# pip-installed under site-packages — otherwise an installed framework
# would get real locks while track() proxies stay active, and every
# correctly-locked access would read as an empty lockset
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))) + os.sep


def _first_party(filename: str) -> bool:
    if filename.startswith(_PKG_ROOT):
        return True
    return not any(f in filename for f in _FOREIGN)


# ---------------------------------------------------------------------------
# bookkeeping — held-list maintenance is UNCONDITIONAL (wrapped locks
# outlive sanitizer activation windows); recording checks the active
# instance at event time.
# ---------------------------------------------------------------------------

def _note_acquire(lock: "SanLock") -> None:
    entries = core.held_entries()
    for e in entries:
        if e[0] is lock:  # RLock reentry: no new edges
            e[1] += 1
            return
    san = core.get_active()
    if san is not None and entries and not core.in_sanitizer():
        with core._reentry_guard():
            san.note_order([e[0] for e in entries], lock)
    lock._holder = core.thread_token()
    entries.append([lock, 1])


def _note_release(lock: "SanLock") -> None:
    entries = core.held_entries()
    for i in range(len(entries) - 1, -1, -1):
        if entries[i][0] is lock:
            entries[i][1] -= 1
            if entries[i][1] == 0:
                del entries[i]
                lock._holder = None
            return
    # cross-thread release (a legal Lock handoff) or a lock acquired
    # before instrumentation: clear the holder so the OWNER's stale
    # held entry prunes on its next access instead of fabricating
    # order edges forever
    lock._holder = None


def _drop_all(lock: "SanLock") -> int:
    """Remove the lock from the held list entirely (Condition.wait on
    an RLock releases every recursion level at once); returns the
    count so the restore path can put it back."""
    entries = core.held_entries()
    for i in range(len(entries) - 1, -1, -1):
        if entries[i][0] is lock:
            n = entries[i][1]
            del entries[i]
            lock._holder = None
            return n
    return 0


def _restore_all(lock: "SanLock", count: int) -> None:
    entries = core.held_entries()
    san = core.get_active()
    if san is not None and entries and not core.in_sanitizer():
        # re-acquiring after a wait is a real acquisition order event
        with core._reentry_guard():
            san.note_order([e[0] for e in entries], lock)
    lock._holder = core.thread_token()
    entries.append([lock, max(count, 1)])


class SanLock:
    """Wrapper over a real lock: identical blocking semantics, plus
    held-list/order bookkeeping on successful acquires."""

    _KIND = "Lock"
    __slots__ = ("_inner", "sid", "name", "_holder")

    def __init__(self, inner=None, site: Optional[str] = None):
        self._inner = inner if inner is not None else _REAL_LOCK()
        self.sid = next(_sid_counter)
        self.name = f"{self._KIND}@{site or core.callsite()}"
        self._holder = None  # thread token of the current holder

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            _note_acquire(self)
        return got

    def release(self) -> None:
        _note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<mxsan {self.name} wrapping {self._inner!r}>"


class SanRLock(SanLock):
    _KIND = "RLock"
    __slots__ = ()

    def __init__(self, inner=None, site: Optional[str] = None):
        super().__init__(inner if inner is not None else _REAL_RLOCK(),
                         site)

    # Condition protocol: wait() releases ALL recursion levels.  The
    # saved count is PER-THREAD (several threads park in wait() on the
    # same condition at once), stashed in the thread-local alongside
    # the held list.
    def _release_save(self):
        saved = core._tls.__dict__.setdefault("saved_counts", {})
        saved[self.sid] = _drop_all(self)
        return self._inner._release_save()

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        saved = core._tls.__dict__.setdefault("saved_counts", {})
        _restore_all(self, saved.pop(self.sid, 1))

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


# ---------------------------------------------------------------------------
# patching
# ---------------------------------------------------------------------------

_patch_depth = 0
_patch_lock = _REAL_LOCK()


def _san_lock_factory():
    if not _first_party(sys._getframe(1).f_code.co_filename):
        return _REAL_LOCK()
    return SanLock(site=core.callsite())


def _san_rlock_factory():
    if not _first_party(sys._getframe(1).f_code.co_filename):
        return _REAL_RLOCK()
    return SanRLock(site=core.callsite())


def _san_condition_factory(lock=None):
    if lock is None:
        if _first_party(sys._getframe(1).f_code.co_filename):
            lock = SanRLock(site=core.callsite())
        else:
            return _REAL_CONDITION()
    # a real Condition over a San lock routes wait()'s release/
    # re-acquire through the wrapper's protocol methods
    return _REAL_CONDITION(lock)


def patch() -> None:
    """Replace the threading lock factories (refcounted: nested
    mxsan scopes under a session-wide enable are fine)."""
    global _patch_depth
    with _patch_lock:
        if _patch_depth == 0:
            _threading.Lock = _san_lock_factory
            _threading.RLock = _san_rlock_factory
            _threading.Condition = _san_condition_factory
        _patch_depth += 1


def unpatch() -> None:
    global _patch_depth
    with _patch_lock:
        if _patch_depth == 0:
            return
        _patch_depth -= 1
        if _patch_depth == 0:
            _threading.Lock = _REAL_LOCK
            _threading.RLock = _REAL_RLOCK
            _threading.Condition = _REAL_CONDITION


def patched() -> bool:
    return _patch_depth > 0
