"""mx.contrib.symbol — symbolic contrib op wrappers
(ref: python/mxnet/symbol/contrib.py generated namespace)."""
from __future__ import annotations

from ..ops.registry import OP_REGISTRY
from ..symbol.symbol import make_symbol_function

_CACHE = {}


def __getattr__(name):
    if name in _CACHE:
        return _CACHE[name]
    if name in OP_REGISTRY:
        fn = make_symbol_function(name)
        _CACHE[name] = fn
        return fn
    raise AttributeError(f"no contrib symbol op {name!r}")
