"""Native C++ layer tests: dependency engine + recordio.

Model: tests/cpp/engine/threaded_engine_test.cc (randomized dependency-
graph stress asserting serialization order) + dmlc recordio tests
(SURVEY.md §4).  Driven from Python through the ctypes C ABI — the same
binding path users exercise.
"""
import os
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import lib as native
from mxnet_tpu import recordio

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

def test_engine_basic_push_and_wait():
    eng = native.NativeEngine(num_workers=4)
    v = eng.new_variable()
    out = []
    eng.push(lambda: out.append(1), write=[v])
    eng.push(lambda: out.append(2), write=[v])
    eng.wait_for_var(v)
    assert out == [1, 2]  # writes on one var are FIFO
    assert eng.var_version(v) == 2
    eng.wait_for_all()
    assert eng.num_pending() == 0


def test_engine_writes_serialize_increments():
    """Unsynchronized += under engine write deps must not lose updates."""
    eng = native.NativeEngine(num_workers=8)
    v = eng.new_variable()
    state = {"x": 0}

    def bump():
        cur = state["x"]
        time.sleep(0.0002)  # widen the race window
        state["x"] = cur + 1

    n = 200
    for _ in range(n):
        eng.push(bump, write=[v])
    eng.wait_for_all()
    assert state["x"] == n


def test_engine_concurrent_reads_exclusive_writes():
    eng = native.NativeEngine(num_workers=8)
    v = eng.new_variable()
    lock = threading.Lock()
    active = {"r": 0, "w": 0, "max_r": 0}
    violations = []

    def reader():
        with lock:
            active["r"] += 1
            active["max_r"] = max(active["max_r"], active["r"])
            if active["w"]:
                violations.append("read during write")
        time.sleep(0.001)
        with lock:
            active["r"] -= 1

    def writer():
        with lock:
            if active["r"] or active["w"]:
                violations.append("write overlap")
            active["w"] += 1
        time.sleep(0.001)
        with lock:
            active["w"] -= 1

    for round_ in range(20):
        for _ in range(6):
            eng.push(reader, read=[v])
        eng.push(writer, write=[v])
    eng.wait_for_all()
    assert not violations
    assert active["max_r"] > 1  # reads actually ran concurrently


def test_engine_random_dag_stress():
    """Randomized read/write sets over many vars; per-var logs must show
    writes in push order with reads fenced between surrounding writes
    (the threaded_engine_test.cc invariant)."""
    rng = np.random.RandomState(0)
    eng = native.NativeEngine(num_workers=8)
    nvars, nops = 8, 300
    vars_ = [eng.new_variable() for _ in range(nvars)]
    logs = [[] for _ in range(nvars)]
    log_lock = threading.Lock()
    # schedule[i] = per-var sequence of ('r'|'w', op_id) in push order
    schedule = [[] for _ in range(nvars)]

    def make_op(op_id, reads, writes):
        def fn():
            with log_lock:
                for r in reads:
                    logs[r].append(("r", op_id))
                for w in writes:
                    logs[w].append(("w", op_id))
        return fn

    for op_id in range(nops):
        k = rng.randint(1, 4)
        chosen = rng.choice(nvars, size=k, replace=False)
        writes = [int(c) for c in chosen[:1]] if rng.rand() < 0.5 else []
        reads = [int(c) for c in chosen[len(writes):]]
        for r in reads:
            schedule[r].append(("r", op_id))
        for w in writes:
            schedule[w].append(("w", op_id))
        eng.push(make_op(op_id, reads, writes),
                 read=[vars_[r] for r in reads],
                 write=[vars_[w] for w in writes])
    eng.wait_for_all()

    for var in range(nvars):
        sched, log = schedule[var], logs[var]
        assert sorted(log) == sorted(sched)
        # writes in push order
        w_sched = [e for e in sched if e[0] == "w"]
        w_log = [e for e in log if e[0] == "w"]
        assert w_log == w_sched, f"var {var}: write order broken"
        # each read runs after its preceding write and before the next one
        prev_write = {}
        next_write = {}
        last_w = None
        for kind, op in sched:
            if kind == "w":
                last_w = op
            else:
                prev_write[op] = last_w
        last_w = None
        for kind, op in reversed(sched):
            if kind == "w":
                last_w = op
            else:
                next_write[op] = last_w
        pos = {e: i for i, e in enumerate(log)}
        for kind, op in sched:
            if kind != "r":
                continue
            if prev_write[op] is not None:
                assert pos[("r", op)] > pos[("w", prev_write[op])], \
                    f"var {var}: read {op} ran before its preceding write"
            if next_write[op] is not None:
                assert pos[("r", op)] < pos[("w", next_write[op])], \
                    f"var {var}: read {op} ran after the next write"


def test_engine_naive_mode_synchronous():
    eng = native.NativeEngine(num_workers=0)
    out = []
    v = eng.new_variable()
    eng.push(lambda: out.append(threading.get_ident()), write=[v])
    # naive engine runs inline on the pushing thread, already done here
    assert out == [threading.get_ident()]
    assert eng.num_pending() == 0


def test_engine_delete_variable():
    eng = native.NativeEngine(num_workers=2)
    v = eng.new_variable()
    out = []
    eng.push(lambda: out.append(1), write=[v])
    eng.delete_variable(v)
    eng.wait_for_all()
    assert out == [1]


def test_engine_cross_var_dependency_chain():
    """a writes v1; b reads v1, writes v2; c reads v2 — strict chain."""
    eng = native.NativeEngine(num_workers=4)
    v1, v2 = eng.new_variable(), eng.new_variable()
    order = []
    eng.push(lambda: (time.sleep(0.005), order.append("a")), write=[v1])
    eng.push(lambda: (time.sleep(0.003), order.append("b")), read=[v1],
             write=[v2])
    eng.push(lambda: order.append("c"), read=[v2])
    eng.wait_for_all()
    assert order == ["a", "b", "c"]


# ---------------------------------------------------------------------------
# recordio interop: python writer <-> native reader and vice versa
# ---------------------------------------------------------------------------

def _payloads(n=20, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.bytes(rng.randint(1, 2000)) for _ in range(n)]


def test_native_reader_reads_python_writer(tmp_path):
    path = str(tmp_path / "py.rec")
    rec = recordio.MXRecordIO(path, "w")
    data = _payloads()
    for p in data:
        rec.write(p)
    rec.close()
    reader = native.NativeRecordReader(path)
    got = []
    while True:
        buf = reader.read()
        if buf is None:
            break
        got.append(buf)
    assert got == data
    reader.reset()
    assert reader.read() == data[0]
    reader.close()


def test_python_reader_reads_native_writer(tmp_path):
    path = str(tmp_path / "native.rec")
    w = native.NativeRecordWriter(path)
    data = _payloads(seed=1)
    positions = [w.write(p) for p in data]
    w.close()
    rec = recordio.MXRecordIO(path, "r")
    got = []
    while True:
        buf = rec.read()
        if buf is None:
            break
        got.append(buf)
    assert got == data
    rec.close()
    # positions support random access via the native reader
    r = native.NativeRecordReader(path)
    r.seek(positions[5])
    assert r.read() == data[5]
    r.close()


def test_native_prefetch_reader(tmp_path):
    path = str(tmp_path / "pf.rec")
    rec = recordio.MXRecordIO(path, "w")
    data = _payloads(n=50, seed=2)
    for p in data:
        rec.write(p)
    rec.close()
    pf = native.NativePrefetchReader(path, capacity=8)
    got = []
    while True:
        buf = pf.read()
        if buf is None:
            break
        got.append(buf)
    assert got == data
    pf.reset()
    got2 = [pf.read() for _ in range(3)]
    assert got2 == data[:3]
    pf.close()


def test_image_record_iter_native_stream(tmp_path):
    """ImageRecordIter streams through the native prefetcher when not
    shuffling."""
    from mxnet_tpu import image as img_mod
    from mxnet_tpu.io import ImageRecordIter

    try:
        img_mod.imencode(np.zeros((8, 8, 3), np.uint8))
    except Exception:
        pytest.skip("no image encoder available")
    path = str(tmp_path / "imgs.rec")
    rec = recordio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    for i in range(7):
        arr = rng.randint(0, 255, size=(10, 10, 3), dtype=np.uint8)
        rec.write(recordio.pack_img(recordio.IRHeader(0, float(i), i, 0),
                                    arr, quality=90))
    rec.close()

    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                         batch_size=3)
    # native path active: the C++ decode pipeline when OpenCV is present,
    # else the C++ prefetch stream
    assert it._pipe is not None or it._stream is not None
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    assert labels[:7].tolist() == [0, 1, 2, 3, 4, 5, 6]
    it.reset()
    assert len(list(it)) == 3


def test_runtime_reports_native():
    from mxnet_tpu import runtime

    feats = runtime.Features()
    assert feats.is_enabled("NATIVE_ENGINE")


def test_native_writer_chunked_records(tmp_path):
    """Regression for the 29-bit length mask: the native writer chunk-chains
    oversized records (cflag 1/2/3); both readers rejoin them."""
    from mxnet_tpu import lib, recordio

    path = str(tmp_path / "native_chunked.rec")
    w = lib.NativeRecordWriter(path, max_chunk=32)
    magic = (0x3ED7230A).to_bytes(4, "little")
    payloads = [b"a" * 100, magic * 20, b"b" * 32 * 4, b"tiny"]
    for p in payloads:
        w.write(p)
    w.close()

    nr = lib.NativeRecordReader(path)
    for p in payloads:
        assert nr.read() == p
    assert nr.read() is None
    nr.close()

    pr = recordio.MXRecordIO(path, "r")
    for p in payloads:
        assert pr.read() == p
    assert pr.read() is None
    pr.close()


# ---------------------------------------------------------------------------
# N17: signal handlers + fork safety (mxnet_tpu/initialize.py, lib.py
# fork guards; ref role: src/initialize.cc)
# ---------------------------------------------------------------------------

def test_signal_handler_installed_on_import():
    """faulthandler is armed by package import (MXNET_USE_SIGNAL_HANDLER
    default on) and stays off when explicitly disabled."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    code = ("import mxnet_tpu, faulthandler;"
            "print(faulthandler.is_enabled())")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    assert r.stdout.strip() == "True", r.stdout

    env["MXNET_USE_SIGNAL_HANDLER"] = "0"
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    assert r.stdout.strip() == "False", r.stdout


def test_use_after_close_raises_not_crashes(tmp_path):
    """A closed native handle must raise MXNetError, not reach C++ as
    NULL (the old behavior was a hard crash)."""
    from mxnet_tpu import MXNetError

    path = str(tmp_path / "x.rec")
    w = native.NativeRecordWriter(path)
    w.write(b"payload")
    w.close()
    with pytest.raises(MXNetError, match="closed"):
        w.write(b"more")
    r = native.NativeRecordReader(path)
    assert r.read() == b"payload"
    r.close()
    with pytest.raises(MXNetError, match="closed"):
        r.read()


@pytest.mark.skipif(not hasattr(os, "fork"), reason="posix only")
def test_fork_safety_engine_and_reader(tmp_path):
    """Fork with a live engine + reader: the child gets a WORKING engine
    (rebuilt threads) and a loudly-invalid reader; the parent is
    untouched (ref: pthread_atfork engine shutdown, initialize.cc)."""
    from mxnet_tpu import MXNetError

    path = str(tmp_path / "f.rec")
    w = native.NativeRecordWriter(path)
    w.write(b"rec0")
    w.close()

    eng = native.NativeEngine(num_workers=2)
    v = eng.new_variable()
    hits = []
    for _ in range(8):
        eng.push(lambda: hits.append(1), write=[v])
    rd = native.NativeRecordReader(path)

    pid = os.fork()
    if pid == 0:  # child
        rc = 1
        try:
            # engine was rebuilt: usable with fresh worker threads
            cv = eng.new_variable()
            got = []
            eng.push(lambda: got.append(1), write=[cv])
            eng.wait_for_all()
            assert got == [1]
            # reader was invalidated: loud error, no crash
            try:
                rd.read()
            except MXNetError as e:
                assert "fork" in str(e)
                rc = 0
        except BaseException:
            import traceback

            traceback.print_exc()
        os._exit(rc)
    _, status = os.waitpid(pid, 0)
    assert os.waitstatus_to_exitcode(status) == 0
    # parent: pre-fork work all drained by the before-fork quiesce
    assert len(hits) == 8
    eng.wait_for_all()
    assert rd.read() == b"rec0"
    rd.close()


def test_cpp_engine_stress_binary(tmp_path):
    """The C++-native engine test tier (ref: tests/cpp/engine/
    threaded_engine_test.cc): compile src/engine_test.cc and run it —
    FIFO writes, reader/writer exclusion, randomized DAG vs a serial
    oracle, WaitForVar selectivity, all asserted in C++."""
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    src_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src")
    binary = str(tmp_path / "engine_test")
    build = subprocess.run(
        ["g++", "-std=c++17", "-O2", "-pthread",
         os.path.join(src_dir, "engine_test.cc"),
         os.path.join(src_dir, "engine.cc"), "-o", binary],
        capture_output=True, text=True, timeout=240)
    assert build.returncode == 0, build.stderr[-2000:]
    run = subprocess.run([binary], capture_output=True, text=True,
                         timeout=120)
    assert run.returncode == 0, run.stdout + run.stderr
    assert "ALL_OK" in run.stdout


def test_engine_rejects_read_write_overlap():
    """A var in both read and write sets must error loudly, not deadlock
    (ref: threaded_engine.cc duplicate-var CHECK)."""
    from mxnet_tpu import MXNetError

    eng = native.NativeEngine(num_workers=2)
    v = eng.new_variable()
    with pytest.raises(MXNetError, match="BOTH read and write"):
        eng.push(lambda: None, read=[v], write=[v])
    with pytest.raises(MXNetError, match="duplicate variable"):
        eng.push(lambda: None, write=[v, v])
    # engine still healthy afterwards
    done = []
    eng.push(lambda: done.append(1), write=[v])
    eng.wait_for_all()
    assert done == [1]
