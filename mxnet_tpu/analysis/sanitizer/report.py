"""mxsan output: human text + machine JSON (the MXSAN.json artifact).

Mirrors the mxlint reporter shape (counts first — the trajectory a
nightly tracks — then the full finding list) so the two artifacts read
the same way.
"""
from __future__ import annotations

import json
import time
from typing import List

from .core import Sanitizer, SanViolation

__all__ = ["render_json", "render_text", "write_report"]


def render_json(san: Sanitizer) -> dict:
    vs = san.violations()
    # snapshot the live detector state under the instance lock —
    # daemon threads (DataLoader workers, the serving batcher) may
    # still be recording while a session-finish hook renders
    with san._lock:
        n_locks = len(san.lock_names)
        n_edges = len(san.edges)
        sites = {site: (rec["count"], len(rec["keys"]), rec["seconds"],
                        rec.get("cache_loads", 0))
                 for site, rec in san.compile_sites.items()}
    per_kind = {}
    for v in vs:
        per_kind[v.kind] = per_kind.get(v.kind, 0) + 1
    return {
        "ok": not vs,
        "counts": {"violations": len(vs), **per_kind},
        "lock_graph": {
            "locks": n_locks,
            "edges": n_edges,
        },
        "compile_sites": {
            site: {"count": count,
                   "distinct_signatures": nkeys,
                   "seconds": round(secs, 4),
                   "cache_loads": loads}
            for site, (count, nkeys, secs, loads) in sorted(sites.items())
        },
        "violations": [{
            "kind": v.kind, "message": v.message, "site": v.site,
            "thread": v.thread, "fingerprint": v.fingerprint,
            "stacks": {role: list(stack)
                       for role, stack in v.stacks.items()},
        } for v in vs],
    }


def render_text(san: Sanitizer) -> str:
    vs: List[SanViolation] = san.violations()
    lines = [v.format() for v in vs]
    verdict = "FAIL" if vs else "OK"
    lines.append(f"mxsan: {verdict} — {len(vs)} violation(s), "
                 f"{len(san.lock_names)} instrumented lock(s), "
                 f"{len(san.edges)} order edge(s), "
                 f"{len(san.compile_sites)} compile site(s)")
    return "\n".join(lines)


def write_report(path: str, san: Sanitizer) -> dict:
    doc = render_json(san)
    doc["when"] = time.strftime("%Y-%m-%d %H:%M:%S")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc
