"""Fused (MXNET_FUSED_CONVBN=1) vs op-granular ResNet V1 blocks.

The fused path must be a pure optimization: same outputs, same gradients
for every parameter, same BatchNorm running-stat updates — train and
eval.  Runs on the CPU backend where FusedConvUnit takes its XLA
fallback; the Pallas kernel itself is covered by test_pallas_convbn.py
(interpret mode) and the on-chip lane.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.gluon.model_zoo.vision.resnet import (BasicBlockV1,
                                                     BottleneckV1)


def _snapshot(net):
    return {n: p.data().asnumpy().copy()
            for n, p in net.collect_params().items()}


def _restore(net, snap):
    for n, p in net.collect_params().items():
        p.set_data(mx.nd.array(snap[n]))


def _run_train_step(net, xnp):
    """One hybridized train forward+backward; returns out, grads, aux."""
    net.hybridize()
    x = mx.nd.array(xnp)
    with autograd.record():
        out = net(x)
        loss = (out * out).sum()
    loss.backward()
    grads = {n: p.grad().asnumpy().copy()
             for n, p in net.collect_params().items()
             if p.grad_req != "null"}
    aux = {n: p.data().asnumpy().copy()
           for n, p in net.collect_params().items()
           if "running" in n}
    return out.asnumpy(), grads, aux


def _block_case(block):
    xnp = np.random.RandomState(3).randn(2, 8, 8, 16).astype(np.float32)
    block.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    block(mx.nd.array(xnp))  # resolve deferred shapes eagerly
    snap = _snapshot(block)

    import os
    os.environ.pop("MXNET_FUSED_CONVBN", None)
    out_ref, g_ref, aux_ref = _run_train_step(block, xnp)

    _restore(block, snap)
    block.hybridize()  # drop the unfused CachedOp trace
    os.environ["MXNET_FUSED_CONVBN"] = "1"
    try:
        out_f, g_f, aux_f = _run_train_step(block, xnp)
    finally:
        os.environ.pop("MXNET_FUSED_CONVBN", None)

    np.testing.assert_allclose(out_f, out_ref, rtol=2e-4, atol=2e-4)
    assert set(g_f) == set(g_ref)
    for n in g_ref:
        np.testing.assert_allclose(g_f[n], g_ref[n], rtol=2e-3, atol=2e-3,
                                   err_msg=f"grad {n}")
    for n in aux_ref:
        np.testing.assert_allclose(aux_f[n], aux_ref[n], rtol=2e-4,
                                   atol=2e-4, err_msg=f"aux {n}")


def test_bottleneck_v1_fused_matches():
    _block_case(BottleneckV1(16, 1, downsample=False, in_channels=16,
                             layout="NHWC"))


def test_bottleneck_v1_stride2_downsample_fused_matches():
    _block_case(BottleneckV1(32, 2, downsample=True, in_channels=16,
                             layout="NHWC"))


def test_basic_block_v1_fused_matches():
    _block_case(BasicBlockV1(16, 1, downsample=False, in_channels=16,
                             layout="NHWC"))


def test_basic_block_v1_stride2_downsample_fused_matches():
    _block_case(BasicBlockV1(32, 2, downsample=True, in_channels=16,
                             layout="NHWC"))


def test_fused_eval_mode_matches():
    import os
    block = BottleneckV1(16, 1, downsample=False, in_channels=16,
                         layout="NHWC")
    xnp = np.random.RandomState(5).randn(2, 8, 8, 16).astype(np.float32)
    block.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    block(mx.nd.array(xnp))
    # warm the running stats so eval normalization is non-trivial
    block.hybridize()
    with autograd.record():
        block(mx.nd.array(xnp))
    out_ref = block(mx.nd.array(xnp)).asnumpy()  # eval (not recording)

    block.hybridize()
    os.environ["MXNET_FUSED_CONVBN"] = "1"
    try:
        out_f = block(mx.nd.array(xnp)).asnumpy()
    finally:
        os.environ.pop("MXNET_FUSED_CONVBN", None)
    np.testing.assert_allclose(out_f, out_ref, rtol=2e-4, atol=2e-4)


def test_fused_full_resnet_train_step():
    """Tiny resnet18_v1 NHWC end-to-end: fused trainer step ≈ unfused
    (BasicBlockV1 path; BottleneckV1 is covered block-level above)."""
    import os
    from mxnet_tpu.gluon.model_zoo import vision

    xnp = np.random.RandomState(7).rand(2, 32, 32, 3).astype(np.float32)
    ynp = np.array([1, 3], np.int32)

    def one_step(fused):
        np.random.seed(0)
        mx.random.seed(0)
        net = vision.resnet18_v1(classes=10, layout="NHWC")
        net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
        net(mx.nd.array(xnp))
        net.hybridize()
        if fused:
            os.environ["MXNET_FUSED_CONVBN"] = "1"
        try:
            with autograd.record():
                out = net(mx.nd.array(xnp))
                loss = mx.gluon.loss.SoftmaxCrossEntropyLoss()(
                    out, mx.nd.array(ynp)).sum()
            loss.backward()
        finally:
            os.environ.pop("MXNET_FUSED_CONVBN", None)
        # registration order is structural — stable across net instances
        # even though the global name counters differ between them
        grads = [(n, p.grad().asnumpy().copy())
                 for n, p in net.collect_params().items()
                 if p.grad_req != "null"]
        return out.asnumpy(), float(loss.asnumpy()), grads

    out_r, loss_r, g_r = one_step(False)
    out_f, loss_f, g_f = one_step(True)
    np.testing.assert_allclose(out_f, out_r, rtol=5e-4, atol=5e-4)
    assert abs(loss_f - loss_r) < 1e-3 * max(1.0, abs(loss_r))
    assert len(g_f) == len(g_r)
    for (nr, gr), (nf, gf) in zip(g_r, g_f):
        # atol scales with the tensor: deep-net fp32 grads reach ~1e3 and
        # summation-order noise scales with them
        atol = 5e-3 + 1e-5 * float(np.max(np.abs(gr)))
        np.testing.assert_allclose(gf, gr, rtol=5e-3, atol=atol,
                                   err_msg=f"grad {nr} / {nf}")


@pytest.mark.slow  # 19s: the multi-device-mesh twin of the tier-1
# single-device fused ResNet tests (same kernels, sharded); the sharded
# pallas parity tests keep mesh coverage in tier-1 — runs nightly
def test_fused_flag_works_under_multi_device_mesh():
    """MXNET_FUSED_CONVBN under a dp>1 SPMD mesh must compile and match
    the unfused trainer's loss.  (Since round 5 the kernel engages via
    the shard_map per-shard path on such meshes — interpret mode only
    on CPU; in this non-interpret test the XLA fallback serves, which
    is exactly the production behavior when Pallas is unavailable.)"""
    import os

    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    def one_loss(fused):
        np.random.seed(0)
        mx.random.seed(0)
        net = vision.resnet18_v1(classes=4, layout="NHWC")
        net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
        net(mx.nd.zeros((1, 32, 32, 3)))
        x = np.random.RandomState(2).rand(8, 32, 32, 3).astype("float32")
        y = (np.arange(8) % 4).astype("int32")
        if fused:
            os.environ["MXNET_FUSED_CONVBN"] = "1"
        try:
            with parallel.make_mesh(dp=2):
                tr = parallel.SPMDTrainer(
                    net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                    {"learning_rate": 0.1})
                lv = float(tr.step(tr._place(x, None),
                                   tr._place(y, None)).asnumpy())
        finally:
            os.environ.pop("MXNET_FUSED_CONVBN", None)
        return lv

    ref = one_loss(False)
    got = one_loss(True)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
