"""Transformer NMT tests (BASELINE config 5 plumbing)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import Trainer
from mxnet_tpu.gluon.model_zoo.transformer import (LabelSmoothedCELoss,
                                                   Transformer,
                                                   get_transformer_model,
                                                   transformer_base)
from mxnet_tpu.test_utils import assert_almost_equal


def test_causal_attention_masks_future():
    """Causal attention output at position t must not depend on tokens > t."""
    rng = np.random.RandomState(0)
    b, h, s, d = 1, 2, 6, 4
    q = rng.randn(b, h, s, d).astype("float32")
    k = rng.randn(b, h, s, d).astype("float32")
    v = rng.randn(b, h, s, d).astype("float32")
    out1 = nd.dot_product_attention(nd.array(q), nd.array(k), nd.array(v),
                                    causal=True).asnumpy()
    k2, v2 = k.copy(), v.copy()
    k2[:, :, 4:], v2[:, :, 4:] = 99.0, -99.0  # scramble the future
    out2 = nd.dot_product_attention(nd.array(q), nd.array(k2), nd.array(v2),
                                    causal=True).asnumpy()
    assert_almost_equal(out1[:, :, :4], out2[:, :, :4], rtol=1e-5, atol=1e-5)
    assert not np.allclose(out1[:, :, 5], out2[:, :, 5])


def test_causal_matches_explicit_mask():
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_attention as pa

    rng = np.random.RandomState(1)
    bh, s, d = 2, 8, 4
    q = rng.randn(bh, s, d).astype("float32")
    k = rng.randn(bh, s, d).astype("float32")
    v = rng.randn(bh, s, d).astype("float32")
    mask = np.ones((bh, s), "float32")
    got = np.asarray(pa.dot_product_attention_ref(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(mask),
        0.5, causal=True))
    s_mat = np.einsum("bqd,bkd->bqk", q, k) * 0.5
    tri = np.tril(np.ones((s, s)))
    s_mat = np.where(tri > 0, s_mat, -1e30)
    e = np.exp(s_mat - s_mat.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = np.einsum("bqk,bkd->bqd", p, v)
    assert_almost_equal(got, ref, rtol=1e-4, atol=1e-4)


def test_pallas_causal_interpret(monkeypatch):
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_attention as pa

    monkeypatch.setenv("MXNET_PALLAS_INTERPRET", "1")
    rng = np.random.RandomState(2)
    bh, s, d = 2, 20, 8
    q = rng.randn(bh, s, d).astype("float32")
    k = rng.randn(bh, s, d).astype("float32")
    v = rng.randn(bh, s, d).astype("float32")
    mask = (np.arange(s)[None, :] < np.array([20, 11])[:, None]).astype("float32")
    got = np.asarray(pa._attention_pallas(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(mask),
        0.3, True))
    ref = np.asarray(pa.dot_product_attention_ref(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(mask),
        0.3, causal=True))
    assert_almost_equal(got, ref, rtol=1e-4, atol=1e-4)


@pytest.fixture(scope="module")
def tiny_transformer():
    net = get_transformer_model("transformer_base", src_vocab_size=50,
                                units=32, hidden_size=64, num_layers=2,
                                num_heads=4, max_length=32, dropout=0.1)
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    return net


def test_transformer_forward_shapes(tiny_transformer):
    net = tiny_transformer
    b, ss, st = 2, 10, 7
    src = nd.array(np.random.randint(0, 50, (b, ss)).astype("float32"))
    tgt = nd.array(np.random.randint(0, 50, (b, st)).astype("float32"))
    logits = net(src, tgt, nd.array([10.0, 6.0]), nd.array([7.0, 5.0]))
    assert logits.shape == (b, st, 50)


def test_transformer_decoder_is_causal(tiny_transformer):
    """Changing future target tokens must not change earlier logits."""
    net = tiny_transformer
    b, ss, st = 1, 6, 8
    rng = np.random.RandomState(0)
    src = rng.randint(0, 50, (b, ss)).astype("float32")
    tgt1 = rng.randint(0, 50, (b, st)).astype("float32")
    tgt2 = tgt1.copy()
    tgt2[:, 5:] = 7
    sv, tv = nd.array([6.0]), nd.array([float(st)])
    l1 = net(nd.array(src), nd.array(tgt1), sv, tv).asnumpy()
    l2 = net(nd.array(src), nd.array(tgt2), sv, tv).asnumpy()
    assert_almost_equal(l1[:, :5], l2[:, :5], rtol=1e-4, atol=1e-4)


def test_transformer_trains_copy_task():
    """Overfit a tiny copy task: loss must drop substantially — the e2e
    sanity check that encoder/decoder/masking/loss wiring learns."""
    vocab = 20
    net = get_transformer_model("transformer_base", src_vocab_size=vocab,
                                units=32, hidden_size=64, num_layers=1,
                                num_heads=2, max_length=16, dropout=0.0)
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    loss_fn = LabelSmoothedCELoss(smoothing=0.0)
    trainer = Trainer(net.collect_params(), "adam", {"learning_rate": 3e-3})
    rng = np.random.RandomState(0)
    b, s = 8, 8
    src_np = rng.randint(3, vocab, (b, s)).astype("float32")
    # teacher forcing: tgt input = <bos>+copy[:-1], label = copy
    tgt_in = np.concatenate([np.ones((b, 1)), src_np[:, :-1]], 1).astype("float32")
    src, tgt = nd.array(src_np), nd.array(tgt_in)
    label = nd.array(src_np)
    sv = nd.array(np.full(b, s, "float32"))
    losses = []
    for _ in range(30):
        with autograd.record():
            logits = net(src, tgt, sv, sv)
            loss = loss_fn(logits, label).mean()
        loss.backward()
        trainer.step(b)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0] * 0.5, losses[::6]


def test_transformer_bucketing_jit_cache(tiny_transformer):
    """Different (src,tgt) length buckets each execute correctly — the
    XLA analogue of BucketingModule's executor-per-bucket (SURVEY §5)."""
    net = tiny_transformer
    rng = np.random.RandomState(1)
    for ss, st in [(10, 8), (6, 6), (10, 8), (12, 4)]:
        src = nd.array(rng.randint(0, 50, (2, ss)).astype("float32"))
        tgt = nd.array(rng.randint(0, 50, (2, st)).astype("float32"))
        out = net(src, tgt, nd.array([float(ss)] * 2),
                  nd.array([float(st)] * 2))
        assert out.shape == (2, st, 50)


def test_transformer_greedy_decode(tiny_transformer):
    net = tiny_transformer
    src = nd.array(np.random.randint(0, 50, (2, 6)).astype("float32"))
    out = net.greedy_decode(src, nd.array([6.0, 4.0]), max_len=5)
    assert out.shape == (2, 5)
    assert (out.asnumpy()[:, 0] == 1).all()  # starts with BOS


def test_label_smoothing_loss():
    pred = nd.array(np.random.randn(4, 10).astype("float32"))
    label = nd.array(np.array([1, 2, 3, 4], "float32"))
    l0 = LabelSmoothedCELoss(smoothing=0.0)(pred, label).asnumpy()
    logp = np.log(np.exp(pred.asnumpy() -
                         pred.asnumpy().max(-1, keepdims=True)) /
                  np.exp(pred.asnumpy() -
                         pred.asnumpy().max(-1, keepdims=True)).sum(
                             -1, keepdims=True))
    expect = -logp[np.arange(4), [1, 2, 3, 4]]
    assert_almost_equal(l0, expect, rtol=1e-4, atol=1e-5)
    ls = LabelSmoothedCELoss(smoothing=0.1)(pred, label).asnumpy()
    expect_s = 0.9 * expect + 0.1 * (-logp.mean(-1))
    assert_almost_equal(ls, expect_s, rtol=1e-4, atol=1e-5)


def test_transformer_tied_embeddings(tiny_transformer):
    net = tiny_transformer
    assert net.src_embed.weight is net.tgt_embed.weight
    assert net.tied_weight is net.src_embed.weight
    # one Parameter instance in collect_params
    names = [k for k in net.collect_params() if "src_embed" in k]
    assert len(names) == 1
