"""Successive-halving searcher over knob configs.

The budget schedule is the classic one (successive halving / ASHA
family, in the spirit of TVM's measured search, arXiv 1802.04799): start
many cheap trials, keep the better half, re-measure survivors at a
doubled budget, repeat.  Three properties matter more than the schedule
itself:

* **The default config is a pinned arm.**  ``{}`` (all declared
  defaults) enters rung 0 and is re-measured at EVERY rung regardless of
  rank, so the final rung always contains a fresh default measurement at
  the same budget as the winner.  "Tuned >= default" then holds by
  argmax construction — the gate can never be lost to a stale or
  smaller-budget default number.
* **A crashed trial is a pruned trial.**  The runner reporting a crash,
  timeout, or unparseable result scores ``-inf`` and is counted, never
  re-raised — a knob setting that OOMs the child must rank last, not
  kill the tune.
* **Objective, then tiebreak.**  The objective is the goodput ratio from
  the trial's embedded mxgoodput ledger; the tiebreak tuple (mxprof MFU,
  throughput) orders configs the ratio cannot separate.

The runner is injected (``runner(config, budget) -> result dict``), so
tests drive the searcher with synthetic runners and the CLI drives it
with bounded subprocess bench runs.
"""
from __future__ import annotations

import math
import random
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .space import Dimension, neighbor, sample

__all__ = ["successive_halving"]

Runner = Callable[[Dict[str, Any], int], Optional[Dict[str, Any]]]

_NEG_INF = float("-inf")


class _Arm:
    __slots__ = ("config", "objective", "tiebreak", "status", "pinned")

    def __init__(self, config: Dict[str, Any], pinned: bool = False):
        self.config = config
        self.objective = _NEG_INF
        self.tiebreak: Tuple[float, ...] = ()
        self.status = "pending"
        self.pinned = pinned

    def score(self) -> Tuple[float, Tuple[float, ...]]:
        return (self.objective, self.tiebreak)


def _measure(arm: _Arm, runner: Runner, budget: int,
             counters: Dict[str, int]) -> None:
    counters["trials"] += 1
    try:
        result = runner(arm.config, budget)
    except Exception:  # noqa: BLE001 — a crashed trial is a pruned trial
        result = None
    if not isinstance(result, dict) or not result.get("ok", True):
        arm.objective, arm.tiebreak = _NEG_INF, ()
        arm.status = "crashed"
        counters["crashed"] += 1
        return
    try:
        arm.objective = float(result["objective"])
        arm.tiebreak = tuple(float(x)
                             for x in result.get("tiebreak", ()))
        arm.status = "ok"
    except (KeyError, TypeError, ValueError):
        arm.objective, arm.tiebreak = _NEG_INF, ()
        arm.status = "crashed"
        counters["crashed"] += 1


def successive_halving(
        runner: Runner,
        dims: Sequence[Dimension],
        *,
        rng: random.Random,
        n_initial: int = 8,
        rungs: int = 3,
        keep: float = 0.5,
        base_budget: int = 4,
        budget_growth: int = 2,
        log: Optional[Callable[[str], None]] = None) -> Dict[str, Any]:
    """Run the search; returns the report dict (best/default/delta/
    trajectory/counters) ``tools/autotune.py`` embeds per scenario."""
    say = log or (lambda _msg: None)
    arms: List[_Arm] = [_Arm({}, pinned=True)]  # declared defaults
    # proposals: half uniform-random restarts, half neighborhood moves
    # off the default — the local moves find "default was nearly right"
    # winners fast, the restarts cover the rest of the space
    while len(arms) < max(2, n_initial):
        cfg = sample(rng, dims) if len(arms) % 2 else \
            neighbor(rng, {}, dims)
        arms.append(_Arm(cfg))

    counters = {"trials": 0, "crashed": 0, "pruned": 0}
    trajectory: List[Dict[str, Any]] = []
    for rung in range(max(1, rungs)):
        budget = base_budget * (budget_growth ** rung)
        for arm in arms:
            _measure(arm, runner, budget, counters)
        arms.sort(key=_Arm.score, reverse=True)
        best = arms[0]
        trajectory.append({
            "rung": rung,
            "budget": budget,
            "arms": len(arms),
            "best_objective": None if best.objective == _NEG_INF
            else best.objective,
            "crashed": sum(1 for a in arms if a.status == "crashed"),
        })
        say(f"rung {rung}: {len(arms)} arms @ budget {budget}, best "
            f"objective {trajectory[-1]['best_objective']}")
        if rung == max(1, rungs) - 1:
            break
        n_keep = max(1, int(math.ceil(len(arms) * keep)))
        survivors = arms[:n_keep]
        if not any(a.pinned for a in survivors):
            survivors.append(next(a for a in arms if a.pinned))
        counters["pruned"] += len(arms) - len(survivors)
        arms = survivors

    default_arm = next(a for a in arms if a.pinned)
    best_arm = arms[0]  # sorted: argmax of the final rung, default incl.
    none_ok = best_arm.objective != _NEG_INF
    return {
        "best_config": best_arm.config,
        "best_objective": best_arm.objective if none_ok else None,
        "best_tiebreak": list(best_arm.tiebreak),
        "default_objective": None if default_arm.objective == _NEG_INF
        else default_arm.objective,
        "default_tiebreak": list(default_arm.tiebreak),
        "delta": (best_arm.objective - default_arm.objective)
        if none_ok and default_arm.objective != _NEG_INF else None,
        "trajectory": trajectory,
        "trials": counters["trials"],
        "crashed": counters["crashed"],
        "pruned": counters["pruned"],
        "ok": none_ok,
    }
