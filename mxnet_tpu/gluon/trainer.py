"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py): applies an Optimizer
to a ParameterDict, syncing gradients through a KVStore.

step(batch_size) = allreduce_grads() + update() — identical contract to the
reference (CS2 in SURVEY.md).  On a sharded mesh the allreduce is in-graph
(psum inserted by XLA via the parallel module); here the KVStore handles
replica reduction + optional DCN sync.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Union

from .. import kvstore as kvs_mod
from .. import optimizer as opt_mod
from ..base import MXNetError
from ..telemetry import instruments as _ins
from ..telemetry import tracing as _tracing
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


def _phase_metric(phase: str):
    """Histogram child for a step phase — None when telemetry is off
    (a profiler-only capture must not register zero-count phantom
    families in the scrape registry)."""
    return _ins.training_phase_seconds(phase) if _tracing._ENABLED \
        else None


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = [params[k] for k in sorted(params.keys())] \
                if isinstance(params, dict) else list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a ParameterDict/dict/list")
        self._params: List[Parameter] = []
        self._param2idx: Dict[str, int] = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p!r}")
            self._param2idx[p.name] = i
            self._params.append(p)
            p._trainer = self
        optimizer_params = optimizer_params or {}
        self._scale = optimizer_params.get("rescale_grad", 1.0)
        self._init_optimizer(optimizer, optimizer_params)
        self._compression_params = compression_params
        self._kvstore_kind = kvstore
        self._kvstore: Optional[kvs_mod.KVStore] = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._states_to_load = None

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params and set(optimizer_params) - {"rescale_grad"}:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer, param_dict=param_dict,
                                             **optimizer_params)
        # one updater per context replica (ref: Trainer._updaters) — each
        # replica must own its optimizer state; allocated lazily once the
        # context list is known
        self._updaters: List[opt_mod.Updater] = []

    def _init_kvstore(self):
        if self._kvstore_kind is None or self._kvstore_kind is False:
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            kind = self._kvstore_kind if isinstance(self._kvstore_kind, str) \
                else "device"
            self._kvstore = self._kvstore_kind \
                if isinstance(self._kvstore_kind, kvs_mod.KVStore) \
                else kvs_mod.create(kind)
            if self._compression_params:
                self._kvstore.set_gradient_compression(self._compression_params)
            if self._update_on_kvstore is None:
                # single-worker: local update is cheaper (no store copies)
                self._update_on_kvstore = self._kvstore.type.startswith("dist")
            if self._update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    self._kvstore.init(i, p.data())
        self._kv_initialized = True
        if self._states_to_load is not None:
            self.load_states(self._states_to_load)
            self._states_to_load = None

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size: int, ignore_stale_grad: bool = False):
        """Forward through KVStore then optimizer (ref: Trainer.step)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        if not _tracing.active():  # disabled: one predicate check
            self._allreduce_grads()
            self._update(ignore_stale_grad)
            return
        with _tracing.span("step", cat="training"):
            with _tracing.span("grad-allreduce", cat="training",
                               metric=_phase_metric("grad-allreduce")):
                self._allreduce_grads()
            with _tracing.span("optimizer-update", cat="training",
                               metric=_phase_metric("optimizer-update")):
                self._update(ignore_stale_grad)
        if _tracing._ENABLED:
            _ins.training_steps_total().inc()

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        if not _tracing.active():
            self._allreduce_grads()
            return
        with _tracing.span("grad-allreduce", cat="training",
                           metric=_phase_metric("grad-allreduce")):
            self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            grads = p.list_grad()
            if self._update_on_kvstore:
                # server-side update: push grads, pull fresh weights
                self._kvstore.pushpull(i, grads, out=p.list_data())
            elif len(grads) > 1 or self._kvstore.type.startswith("dist"):
                self._kvstore.push(i, grads)
                self._kvstore.pull(i, out=grads)

    def update(self, batch_size: int, ignore_stale_grad: bool = False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        if not _tracing.active():
            self._update(ignore_stale_grad)
            return
        with _tracing.span("optimizer-update", cat="training",
                           metric=_phase_metric("optimizer-update")):
            self._update(ignore_stale_grad)
        if _tracing._ENABLED:
            _ins.training_steps_total().inc()

    def _update(self, ignore_stale_grad: bool = False):
        if self._update_on_kvstore:
            return  # weights already refreshed by pushpull
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            for r, (data, grad) in enumerate(zip(p.list_data(),
                                                 p.list_grad())):
                while len(self._updaters) <= r:
                    self._updaters.append(opt_mod.get_updater(self._optimizer))
                self._updaters[r](i, grad, data)

    def save_states(self, fname: str):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=False)
        else:
            if not self._updaters:
                self._updaters.append(opt_mod.get_updater(self._optimizer))
            with open(fname, "wb") as f:
                f.write(self._updaters[0].get_states(dump_optimizer=False))

    def load_states(self, fname: str):
        if not self._kv_initialized:
            self._states_to_load = fname
            return
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            if not self._updaters:
                self._updaters.append(opt_mod.get_updater(self._optimizer))
            with open(fname, "rb") as f:
                self._updaters[0].set_states(f.read())
