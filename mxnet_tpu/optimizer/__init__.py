from .optimizer import (Optimizer, Updater, create, register, get_updater,
                        SGD, NAG, Adam, AdaGrad, AdaDelta, Adamax, Nadam,
                        RMSProp, Ftrl, Signum, SignSGD, LAMB, Test)
from .fused import FusedUpdater, FusedUnsupported
from .spmd import SpmdUpdater

__all__ = ["Optimizer", "Updater", "FusedUpdater", "FusedUnsupported",
           "SpmdUpdater",
           "create", "register",
           "get_updater", "SGD", "NAG", "Adam", "AdaGrad", "AdaDelta",
           "Adamax", "Nadam", "RMSProp", "Ftrl", "Signum", "SignSGD",
           "LAMB", "Test"]
