from .optimizer import (Optimizer, Updater, create, register, get_updater,
                        SGD, NAG, Adam, AdaGrad, AdaDelta, Adamax, Nadam,
                        RMSProp, Ftrl, Signum, SignSGD, LAMB, Test)

__all__ = ["Optimizer", "Updater", "create", "register", "get_updater",
           "SGD", "NAG", "Adam", "AdaGrad", "AdaDelta", "Adamax", "Nadam",
           "RMSProp", "Ftrl", "Signum", "SignSGD", "LAMB", "Test"]
