"""Weight initializers (ref: python/mxnet/initializer.py — Xavier, MSRAPrelu,
Normal, Uniform, Orthogonal, Constant, Zero, One, Bilinear, Mixed, Load;
registry + InitDesc attribute-based dispatch)."""
from __future__ import annotations

import math
import re
from typing import Optional

import numpy as np

from .base import MXNetError, Registry

__all__ = ["Initializer", "register", "create", "Zero", "One", "Constant",
           "Uniform", "Normal", "Orthogonal", "Xavier", "MSRAPrelu",
           "Bilinear", "LSTMBias", "Mixed", "InitDesc"]

_REG: Registry = Registry("initializer")
register = _REG.register


class InitDesc(str):
    """Parameter name carrying init attrs (ref: initializer.py::InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base initializer; subclasses implement _init_weight(name, arr)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr):
        """Dispatch by parameter-name convention (ref: Initializer.__call__):
        *_bias/beta -> zero, gamma -> one, *_weight -> _init_weight, etc."""
        if not isinstance(name, str):
            name = str(name)
        if isinstance(name, InitDesc):
            attr_init = name.attrs.get("__init__")
            if attr_init:
                create(attr_init).init_array(name, arr)
                return
        n = name.lower()
        if n.endswith("bias") or n.endswith("beta") or n.endswith("moving_mean"):
            self._init_zero(name, arr)
        elif n.endswith("gamma") or n.endswith("moving_var") or n.endswith("running_var"):
            self._init_one(name, arr)
        elif n.endswith("min") or n.endswith("max"):
            self._init_zero(name, arr)
        else:
            self._init_weight(name, arr)

    def init_array(self, name, arr):
        """Unconditional init of `arr` with this initializer's distribution."""
        self._init_weight(name, arr)

    def _init_zero(self, name, arr):
        arr[:] = 0.0

    def _init_one(self, name, arr):
        arr[:] = 1.0

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"

    def dumps(self):
        import json

        return json.dumps([type(self).__name__.lower(), self._kwargs])


def create(init, **kwargs) -> Initializer:
    if isinstance(init, Initializer):
        return init
    if init is None:
        return Uniform(0.07)
    if isinstance(init, str):
        return _REG.get(init)(**kwargs)
    raise MXNetError(f"cannot create initializer from {init!r}")


@register("zeros")
@register("zero")
class Zero(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 0.0


@register("ones")
@register("one")
class One(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 1.0


@register("constant")
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr[:] = self.value


@register("uniform")
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr[:] = np.random.uniform(-self.scale, self.scale, arr.shape)


@register("normal")
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr[:] = np.random.normal(0.0, self.sigma, arr.shape)


@register("orthogonal")
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:])) if len(arr.shape) > 1 else 1
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape)


@register("xavier")
class Xavier(Initializer):
    """ref: initializer.py::Xavier (rnd_type uniform|gaussian,
    factor_type avg|in|out, magnitude)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = float(np.prod(shape[2:])) if len(shape) > 2 else 1.0
        fan_in = (shape[1] if len(shape) > 1 else shape[0]) * hw_scale
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError(f"bad factor_type {self.factor_type}")
        scale = math.sqrt(self.magnitude / max(factor, 1.0))
        if self.rnd_type == "uniform":
            arr[:] = np.random.uniform(-scale, scale, shape)
        elif self.rnd_type == "gaussian":
            arr[:] = np.random.normal(0, scale, shape)
        else:
            raise MXNetError(f"bad rnd_type {self.rnd_type}")


@register("msraprelu")
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register("bilinear")
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        weight = np.zeros(arr.shape, dtype="float32")
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight


@register("lstmbias")
class LSTMBias(Initializer):
    """Forget-gate bias to 1 (ref: initializer.py::LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        arr[:] = 0.0
        n = arr.shape[0] // 4
        arr[n:2 * n] = self.forget_bias


@register("mixed")
class Mixed(Initializer):
    def __init__(self, patterns, initializers):
        super().__init__()
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.match(str(name)):
                init(name, arr)
                return
        raise MXNetError(f"parameter {name} did not match any pattern")
