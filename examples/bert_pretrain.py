"""BERT pretraining example (BASELINE config 3: BERT-base).

Synthetic-corpus MLM + NSP pretraining loop over the BERT stack: fused
attention (Pallas on TPU), tied MLM decoder, NSP classifier.  The
reference-era equivalent is GluonNLP's scripts/bert/run_pretraining.py.

Usage:
  python examples/bert_pretrain.py                  # TPU, bert-base
  python examples/bert_pretrain.py --cpu --small    # CPU smoke (CI)
"""
from __future__ import annotations

import argparse
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=30522)
    ap.add_argument("--dtype", default="float32")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import Trainer
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    from mxnet_tpu.gluon.model_zoo.bert import get_bert_model

    ctx = mx.cpu() if args.cpu else mx.tpu(0)
    if args.small:
        args.vocab, args.seq_len, args.batch_size = 1000, 32, 4
        net = get_bert_model("bert_12_768_12", vocab_size=args.vocab,
                             num_layers=2, units=64, hidden_size=128,
                             num_heads=4, max_length=args.seq_len)
    else:
        net = get_bert_model("bert_12_768_12", vocab_size=args.vocab,
                             max_length=max(512, args.seq_len))
    net.initialize(mx.initializer.Normal(0.02), ctx=ctx)
    if args.dtype != "float32":
        net.cast(args.dtype)

    loss_fn = SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 1e-4})

    rng = np.random.RandomState(0)
    b, s = args.batch_size, args.seq_len
    tokens = nd.array(rng.randint(0, args.vocab, (b, s)).astype("float32"),
                      ctx=ctx)
    segments = nd.zeros((b, s), ctx=ctx)
    vlen = nd.array(np.full(b, s, "float32"), ctx=ctx)
    mlm_labels = nd.array(rng.randint(0, args.vocab, (b, s)).astype("float32"),
                          ctx=ctx)
    nsp_labels = nd.array(rng.randint(0, 2, (b,)).astype("float32"), ctx=ctx)

    step_time = None
    for step in range(args.steps):
        tic = time.time()
        with autograd.record():
            seq, pooled = net(tokens, segments, vlen)
            mlm_scores = net.decode_mlm(seq)
            nsp_scores = net.classify_nsp(pooled)
            loss = loss_fn(mlm_scores, mlm_labels).mean() + \
                loss_fn(nsp_scores, nsp_labels).mean()
        loss.backward()
        trainer.step(b)
        lval = float(loss.asnumpy())  # sync point ends the step timing
        step_time = time.time() - tic
        print(f"step {step}: loss={lval:.4f} ({step_time:.2f}s)")
    if step_time is not None:
        print(f"last-step throughput: {b * s / step_time:.0f} tokens/s")


if __name__ == "__main__":
    main()
