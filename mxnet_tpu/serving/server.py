"""InferenceServer: bounded admission + per-model batchers + metrics.

Threaded and stdlib-only.  The server owns one DynamicBatcher per
(model, version) it has seen traffic for, and an admission bound over
EVERYTHING it has accepted but not yet completed: at `max_queue` the
next submit fails fast with ServerOverloaded (HTTP 503 semantics) —
load-shedding at the door beats unbounded queueing, where every request
eventually times out after burning queue memory (the reject-don't-block
rule every production serving stack converges on).

Deadlines: a request may carry `timeout_ms` (or inherit
`config.default_timeout_ms`); if it expires while queued the caller
gets DeadlineExceeded (504) and the rows never launch.

Shutdown: `shutdown(drain=True)` stops admission immediately, lets
every accepted request finish, then stops the batcher threads;
`drain=False` fails queued requests with ServerClosed.
"""
from __future__ import annotations

import json
import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional

from .. import profiler as _prof
from ..telemetry import tracing as _tracing
from . import (ModelUnavailable, ServerClosed, ServerOverloaded,
               ServingConfig)
from .batcher import DynamicBatcher
from .repository import ModelRepository

__all__ = ["InferenceServer"]


class InferenceServer:
    def __init__(self, repository: ModelRepository,
                 config: Optional[ServingConfig] = None):
        self.repository = repository
        self.config = config or ServingConfig()
        self._lock = threading.Lock()
        self._batchers: Dict[tuple, DynamicBatcher] = {}
        self._pending = 0
        self._pending_per: Dict[tuple, int] = {}
        self._closed = False
        # entries whose breaker already took this config's overrides
        # (configure once, not per request on the hot path)
        self._breaker_configured: set = set()

    # ---- request path -------------------------------------------------

    def _admit_locked(self, m) -> None:
        """Raise the 503-class error a submit would get right now.
        Caller holds self._lock; touches nothing on the (possibly
        cold, not-yet-imported) artifact."""
        if self._closed:
            raise ServerClosed("server is shut down")
        if self._pending >= self.config.max_queue:
            if m is not None:
                m.bump("rejected")
            raise ServerOverloaded(
                f"admission queue full ({self._pending} pending >= "
                f"max_queue {self.config.max_queue}); retry with "
                f"backoff")

    def _breaker_gate(self, entry, consume: bool) -> None:
        """Raise ModelUnavailable (503 this model, nothing else) while
        the entry's circuit breaker refuses traffic.  `consume=True`
        (the submit path) takes the half-open probe slot; the advisory
        front-end check must not.  Config overrides land lazily — the
        breaker exists before any batcher does."""
        cfg = self.config
        if cfg.breaker_threshold is not None \
                or cfg.breaker_cooldown_ms is not None:
            key = (entry.name, entry.version)
            with self._lock:
                needs_cfg = key not in self._breaker_configured
                if needs_cfg:
                    self._breaker_configured.add(key)
            if needs_cfg:
                entry.breaker.configure(
                    threshold=cfg.breaker_threshold,
                    cooldown_s=None if cfg.breaker_cooldown_ms is None
                    else cfg.breaker_cooldown_ms / 1e3)
        ok = entry.breaker.allow() if consume \
            else entry.breaker.would_allow()
        if not ok:
            entry.metrics.bump("breaker_rejected")
            raise ModelUnavailable(
                f"model {entry.name!r} v{entry.version} is "
                f"unavailable: circuit breaker is "
                f"{entry.breaker.state()} after repeated executor "
                f"failures; retry after the cooldown (the server "
                f"itself is healthy)")

    def check_admission(self, entry=None) -> None:
        """Cheap advisory fail-fast for front ends: raises
        ServerClosed/ServerOverloaded/ModelUnavailable exactly as
        submit() would, WITHOUT importing the artifact.  Call it before
        any per-request work that needs the model (input specs, dtype
        casts) so load-shedding stays cheap for cold models; submit()
        still re-checks authoritatively."""
        with self._lock:
            self._admit_locked(entry.metrics if entry is not None
                               else None)
        if entry is not None:
            self._breaker_gate(entry, consume=False)

    def submit(self, model: str, inputs, version: Optional[int] = None,
               seed: int = 0,
               timeout_ms: Optional[float] = None) -> Future:
        """Admit one request; returns a Future of the model's output
        structure.  Raises ServerOverloaded when the admission queue is
        full and ServerClosed after shutdown begins."""
        entry = self.repository.get(model, version)
        m = entry.metrics
        key = (entry.name, entry.version)
        # breaker first: an OPEN model's 503 must not consume an
        # admission slot, and a half-open probe is granted HERE
        self._breaker_gate(entry, consume=True)
        # a fresh trace root per request: every span this request
        # produces — here, on the batcher thread, in the executor —
        # carries ONE trace id (exposed on the returned Future)
        adm = None
        if _prof._running:  # spans record only during a capture —
            # scrape-only telemetry must not pay per-request id/span
            # machinery that lands nowhere
            adm = _tracing.Span(
                "admission", "serving", root=True,
                args={"model": entry.name, "version": entry.version})
        # admission first, import after: rejection (closed / queue
        # full) needs only entry.metrics, so it must fail fast rather
        # than wait behind a cold model's multi-second artifact import
        try:
            with self._lock:
                self._admit_locked(m)
                self._pending += 1
                self._pending_per[key] = self._pending_per.get(key, 0) + 1
                m.bump("requests")
                m.gauge("queue_depth", self._pending_per[key])
        except BaseException:
            entry.breaker.abandon_probe()  # never reached the executor
            if adm is not None:
                adm.finish()
            raise
        # rollover pin: this request finishes on THIS entry's executors
        # even if a version swap retires it mid-flight (the entry only
        # releases artifact+executables once its last use ends)
        entry.begin_use()

        released = []  # idempotence latch: the release may be reached
        # from both the done-callback and the submit error path when a
        # callback attached to an already-completed future raises

        def _release():
            with self._lock:
                if released:
                    return
                released.append(True)
                self._pending -= 1
                self._pending_per[key] -= 1
                m.gauge("queue_depth", self._pending_per[key])
            entry.end_use()  # outside self._lock (entry has its own)

        t0 = time.monotonic()
        if timeout_ms is None:
            timeout_ms = self.config.default_timeout_ms
        deadline = None if timeout_ms is None else t0 + timeout_ms / 1e3

        def _done(f: Future):
            _release()
            if f.cancelled() or f.exception() is not None:
                # deadline_expired/failed are counted at the batcher,
                # where the cause is known
                return
            m.bump("completed")
            m.observe_latency(time.monotonic() - t0)

        try:
            entry.served  # lazy artifact import, OUTSIDE every lock:
            # a cold model's multi-second import must not stall other
            # models' submits (the entry has its own import lock); the
            # request holds its admitted slot while importing
            with self._lock:
                # re-checked: shutdown() may have snapshotted (and
                # closed) the batcher map between the admission check
                # and here — a batcher born after that snapshot would
                # never be closed and would break the drain guarantee
                if self._closed:
                    raise ServerClosed("server is shut down")
                batcher = self._batchers.get(key)
                if batcher is None:
                    # cheap here: the artifact is already imported above
                    batcher = DynamicBatcher(entry, self.config)
                    self._batchers[key] = batcher
            fut = batcher.submit(
                inputs, seed=seed, deadline=deadline,
                trace=(adm.trace_id, adm.span_id)
                if adm is not None else None)
            # hand the slot + use-count release to the done-callback
            # INSIDE the guarded region (mxflow MX010): once the
            # request is enqueued, no later failure — span teardown,
            # trace bookkeeping — may strand the admission slot
            fut.add_done_callback(_done)
        except BaseException:
            _release()  # admitted but never enqueued: free the slot
            entry.breaker.abandon_probe()
            raise
        finally:
            if adm is not None:
                adm.finish()  # admission span = submit-side machinery
        fut.trace_id = adm.trace_id if adm is not None else None
        return fut

    def infer(self, model: str, inputs, version: Optional[int] = None,
              seed: int = 0, timeout_ms: Optional[float] = None):
        """Blocking single call (submit + result)."""
        return self.submit(model, inputs, version=version, seed=seed,
                           timeout_ms=timeout_ms).result()

    # ---- observability ------------------------------------------------

    def pending(self) -> int:
        with self._lock:
            return self._pending

    @property
    def draining(self) -> bool:
        """True once shutdown has begun — the /healthz drain signal
        (load balancers stop routing here while accepted work
        finishes)."""
        with self._lock:
            return self._closed

    def metrics(self) -> dict:
        """Per-model snapshot (QPS, p50/p99 latency, occupancy, queue
        depth, rejections, executor-cache hits) — the `dumps()`-style
        structure documented in docs/serving.md."""
        models = [e.metrics.snapshot() for e in self.repository.entries()]
        return {
            "pending": self.pending(),
            "max_queue": self.config.max_queue,
            "closed": self._closed,
            "models": models,
        }

    def dumps(self, indent: Optional[int] = 1) -> str:
        """JSON metrics snapshot (profiler.dumps analogue)."""
        return json.dumps(self.metrics(), indent=indent)

    # ---- lifecycle ----------------------------------------------------

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop admission now; drain=True completes accepted work
        (graceful), drain=False fails it with ServerClosed.

        The drain has a HARD deadline: `timeout`, else
        config.drain_timeout_s, else the MXNET_DRAIN_TIMEOUT_MS knob.
        One wedged batch (executor hang, driver stall) must not hang
        shutdown forever — past the deadline every still-queued request
        fails with ServerClosed and shutdown returns.  The deadline is
        shared across batchers, not per batcher."""
        from ..util import env

        if timeout is None:
            timeout = self.config.drain_timeout_s
        if timeout is None:
            timeout = env.get_float("MXNET_DRAIN_TIMEOUT_MS") / 1e3
        deadline = time.monotonic() + timeout
        with self._lock:
            self._closed = True
            batchers = list(self._batchers.values())
        for b in batchers:
            b.close(drain=drain,
                    timeout=max(deadline - time.monotonic(), 0.0))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=True)
        return False
