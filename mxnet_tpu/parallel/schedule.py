"""Runtime collective-schedule ledger — the dynamic half of mxrank.

The static rules (MX019/MX020, ``analysis/mxrank/``) prevent
rank-divergent collective schedules at lint time; this module catches
the instances that survive.  Every collective site appends
``(site, op, dtype, nbytes, seq)`` to a bounded rolling fingerprint —
one deque append when the ledger is on, one boolean check when off —
and each rank piggybacks its last-K window on the elastic heartbeat
seam as an atomic ``sched-rank<k>.json`` stamp next to the
``hb-rank<k>.json`` liveness stamps.

On a collective watchdog timeout the ``PeerFailed`` path first calls
:func:`divergence_details`: publish our fingerprint, poll the peers'
stamps for a bounded wait, and align the overlapping windows by
``seq``.  Same seq + different ``(site, op, dtype, nbytes)`` means the
ranks issued different collectives — a deterministic program bug the
supervisor must NOT restart-loop on — and the failure reclassifies to
``ScheduleDivergence``.  A peer that is merely *behind* (shorter
window, all overlapping entries equal) stays a ``PeerFailed``: that is
a dead or stalled peer, and restarting is the right reaction.

Knobs: ``MXNET_RANKCHECK`` (master switch, default on),
``MXNET_RANKCHECK_WINDOW`` (entries kept), ``MXNET_RANKCHECK_WAIT_S``
(timeout-path poll bound).  See docs/resilience.md (Schedule
divergence).
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

__all__ = ["stamp_name", "enabled", "record", "fingerprint", "publish",
           "read_peer", "compare", "divergence_details", "configure",
           "reset"]

_PREFIX = "sched-rank"

_lock = threading.Lock()
#: tri-state master switch: None = not yet resolved from MXNET_RANKCHECK
_ON: Optional[bool] = None
_window: Optional[Deque[Tuple[str, str, str, int, int]]] = None
_seq = 0
_published_seq = -1
#: explicit configure() beats the elastic env contract
_dir: Optional[str] = None
_rank: Optional[int] = None
_gauge = None


def stamp_name(rank: int) -> str:
    return f"{_PREFIX}{rank}.json"


def enabled() -> bool:
    """Ledger on?  Resolved once from ``MXNET_RANKCHECK``; after that
    this is the one boolean check the ledger-off path pays."""
    global _ON
    if _ON is None:
        from ..util import env

        _ON = bool(env.get_bool("MXNET_RANKCHECK"))
    return _ON


def configure(directory: Optional[str] = None,
              rank: Optional[int] = None) -> None:
    """Pin the stamp directory / rank explicitly (the heartbeat writer
    does this; outside an elastic job the env contract is absent)."""
    global _dir, _rank
    if directory is not None:
        _dir = os.path.abspath(directory)
    if rank is not None:
        _rank = int(rank)


def reset() -> None:
    """Test hook: drop the ledger and re-resolve every lazy global."""
    global _ON, _window, _seq, _published_seq, _dir, _rank, _gauge
    with _lock:
        _ON = None
        _window = None
        _seq = 0
        _published_seq = -1
        _dir = None
        _rank = None
        _gauge = None


def _ensure_window() -> Deque[Tuple[str, str, str, int, int]]:
    global _window
    if _window is None:
        from ..util import env

        n = env.get_int("MXNET_RANKCHECK_WINDOW") or 256
        _window = deque(maxlen=max(8, n))
    return _window


def _resolve_dir() -> Optional[str]:
    if _dir is not None:
        return _dir
    from ..util import env

    return env.get_str("MXNET_ELASTIC_DIR") or None


def _resolve_rank() -> Optional[int]:
    if _rank is not None:
        return _rank
    for name in ("MXNET_ELASTIC_RANK", "DMLC_WORKER_ID", "PROCESS_ID"):
        v = os.environ.get(name)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                continue
    return None


def _set_gauge(seq: int) -> None:
    global _gauge
    if _gauge is None:
        try:
            from ..telemetry import instruments as _ins

            _gauge = _ins.collective_schedule_seq()
        except Exception:
            return
    _gauge.set(seq)


def record(site: str, op: str, dtype: str = "", nbytes: int = 0) -> int:
    """Append one collective issue to the ledger; returns its seq (or
    -1 with the ledger off).  Called once per *logical* collective —
    before the attempt, outside the retry loop — so a transient-fault
    retry on one rank cannot shift its seq numbering off its peers'."""
    if not enabled():
        return -1
    global _seq
    with _lock:
        seq = _seq
        _seq += 1
        _ensure_window().append((site, op, dtype, int(nbytes), seq))
    _set_gauge(seq)
    return seq


def fingerprint() -> dict:
    """The publishable view: rank, next seq, rolling window, digest."""
    with _lock:
        win: List[list] = [list(e) for e in (_window or ())]
        seq = _seq
    h = hashlib.sha1()
    for e in win:
        h.update(f"{e[0]}|{e[1]}|{e[2]}|{e[3]}|{e[4]}\n".encode())
    return {"rank": _resolve_rank(), "seq": seq, "window": win,
            "digest": h.hexdigest()[:16]}


def publish(force: bool = False) -> bool:
    """Atomically stamp this rank's fingerprint into the shared
    directory (tmp-write + ``os.replace``, like the heartbeat).  Skips
    the write when nothing was recorded since the last publish unless
    ``force``; best-effort like the heartbeat — a flaky filesystem
    must never fail the step that carried the piggyback."""
    if not enabled():
        return False
    d, r = _resolve_dir(), _resolve_rank()
    if d is None or r is None:
        return False
    global _published_seq
    fp = fingerprint()
    if not force and fp["seq"] == _published_seq:
        return False
    path = os.path.join(d, stamp_name(r))
    tmp = os.path.join(d, f".tmp-{stamp_name(r)}")
    try:
        with open(tmp, "w") as f:
            json.dump(fp, f)
        os.replace(tmp, path)
    except OSError:
        return False  # mxlint: disable=MX007 — piggyback is best-effort
    _published_seq = fp["seq"]
    return True


def read_peer(rank: int,
              directory: Optional[str] = None) -> Optional[dict]:
    d = directory or _resolve_dir()
    if d is None:
        return None
    try:
        with open(os.path.join(d, stamp_name(rank))) as f:
            fp = json.load(f)
    except (OSError, ValueError):
        return None
    return fp if isinstance(fp, dict) else None


def _trail(fp: dict, seq: int, k: int = 5) -> List[str]:
    """The last ``k`` schedule entries up to and including ``seq``,
    rendered ``op@seq(site)`` — what the divergence error names."""
    win = [e for e in fp.get("window", ()) if e[4] <= seq]
    return [f"{e[1]}@{e[4]}({e[0]})" for e in win[-k:]]


def compare(mine: dict, theirs: dict) -> Optional[dict]:
    """Align the two windows by seq; first overlapping seq whose
    ``(site, op, dtype, nbytes)`` differs is the divergence.  Returns
    ``{"seq", "peer", "mine", "theirs"}`` or None when every
    overlapping entry matches (a peer merely behind is NOT divergent —
    that is what PeerFailed is for)."""
    a = {e[4]: e for e in mine.get("window", ())}
    b = {e[4]: e for e in theirs.get("window", ())}
    for q in sorted(set(a) & set(b)):
        if tuple(a[q][:4]) != tuple(b[q][:4]):
            return {"seq": q, "peer": theirs.get("rank"),
                    "mine": _trail(mine, q), "theirs": _trail(theirs, q)}
    return None


def _peer_ranks(d: str, me: int) -> List[int]:
    try:
        names = os.listdir(d)
    except OSError:
        return []
    out = []
    for name in names:
        if name.startswith(_PREFIX) and name.endswith(".json"):
            try:
                r = int(name[len(_PREFIX):-len(".json")])
            except ValueError:
                continue
            if r != me:
                out.append(r)
    return sorted(out)


def divergence_details(wait_s: Optional[float] = None
                       ) -> Optional[dict]:
    """The watchdog-timeout hook: publish our fingerprint, then poll
    the peers' stamps for up to ``wait_s`` (MXNET_RANKCHECK_WAIT_S)
    comparing windows.  First mismatch wins; None means no divergence
    evidence surfaced in time and the timeout stays a PeerFailed."""
    if not enabled():
        return None
    d, me = _resolve_dir(), _resolve_rank()
    if d is None or me is None:
        return None
    publish(force=True)
    if wait_s is None:
        from ..util import env

        w = env.get_float("MXNET_RANKCHECK_WAIT_S")
        wait_s = 3.0 if w is None else w
    deadline = time.monotonic() + max(0.0, wait_s)
    mine = fingerprint()
    settled = set()  # peers whose window already reached our seq
    while True:
        for r in _peer_ranks(d, me):
            if r in settled:
                continue
            fp = read_peer(r, d)
            if fp is None:
                continue
            div = compare(mine, fp)
            if div is not None:
                return div
            if fp.get("seq", -1) >= mine["seq"]:
                settled.add(r)
        if time.monotonic() >= deadline:
            return None
        time.sleep(0.1)
