"""Deterministic fault injection (`mxnet_tpu.resilience.chaos`).

Production code cannot be trusted to survive faults nobody can
reproduce.  This module gives every failure-handling path in the
framework a deterministic trigger: a *site* in first-party code calls
``chaos.check("<kind>")`` behind the module-level ``_ACTIVE`` flag, and
a test (or the nightly chaos stage) installs a *plan* saying which call
at that site fails, and how.

The disabled path is one attribute read — call sites are written

    if _chaos._ACTIVE:
        _chaos.check("dist.collective")

so with no plan installed (the production default) nothing else runs:
no counter, no lock, no branch beyond the falsy check.  A tier-1 test
asserts both the zero-overhead property and that behavior is bit-equal
with chaos compiled out.

Kinds wired into the framework (docs/resilience.md has the full fault
model):

    dataloader.worker   worker death in gluon/data/dataloader.py
                        (thread pool: the worker thread exits without
                        publishing; process pool: os._exit in the
                        spawned child)
    dist.collective     failure/hang in parallel/dist.py collectives
    kvstore.pushpull    failure in KVStore.pushpull_fused buckets
    serving.artifact    artifact import error in serving/repository.py
    serving.execute     executor error in _ModelEntry.execute
    trainer.preempt     simulated preemption signal (SIGTERM-style)
                        observed by gluon/trainer.py's auto-checkpoint
                        hook at the next step boundary
    trainer.numerics    numerics corruption at gluon/trainer.py step
                        entry: one gradient bucket poisoned to NaN on
                        the selected step (the mxhealth detection /
                        skip_step bit-consistency fixture)
    comm.quant          quantized-collective corruption at the SPMD
                        step (optimizer/spmd.py, MXNET_COMM_QUANT):
                        the first quantized bucket's dequant scale is
                        flipped to inf on the selected step, so a bad
                        encode/decode must light up mxhealth's
                        nonfinite detector rather than silently skew
                        the weights

Plans are installed via the :func:`inject` context manager (scoped,
exception-safe) or — for subprocess experiments like the nightly chaos
stage — via the ``MXNET_CHAOS``/``MXNET_CHAOS_SPEC`` env knobs parsed
at first import of the resilience package.  Spec grammar, comma
separated:  ``kind@N`` (fail the Nth call, 1-based), ``kind@xN`` (fail
the next N calls), ``kind@pF`` (each call fails with prob. F, seeded by
``MXNET_CHAOS_SEED``), each optionally ``:action`` where action is one
of ``error`` (raise :class:`FaultInjected` — the default), ``die``
(worker death), ``hang`` (sleep ``duration`` inside the site so real
timeout machinery fires; ``hang=SECONDS`` sets the duration in a
spec), ``preempt`` (trigger the preemption flag); and optionally
``:rank=R``.

``rank=`` is the multi-process selector: a plan carrying it fires only
in the process whose job rank is R, so one spec shipped identically
into every worker's environment (the elastic supervisor does exactly
this) can still kill or hang ONE deterministic rank.  The process rank
is stamped by ``dist.init()`` (:func:`set_rank`) or resolved lazily
from the launcher env (``MXNET_ELASTIC_RANK``, ``DMLC_WORKER_ID``,
``PROCESS_ID``); a rank-selected plan in a process with no resolvable
rank never fires.

Every fire bumps ``mx_fault_injected_total{kind}`` and the per-kind
:func:`stats`, which persist after a scope exits so tests can assert
exactly how many faults landed.
"""
from __future__ import annotations

import random as _random
import threading
import time
from typing import Dict, List, Optional

from ..base import MXNetError

__all__ = ["FaultInjected", "inject", "check", "stats", "reset_stats",
           "export_plans", "install_plans", "active", "set_rank"]


class FaultInjected(MXNetError):
    """The error a chaos plan raises at an injection site.  ``transient``
    is True: retry policies treat an injected fault exactly like a
    transient infrastructure error (that equivalence is the point)."""

    transient = True

    def __init__(self, kind: str, nth: int):
        super().__init__(
            f"[chaos] injected fault at site '{kind}' (call #{nth})")
        self.kind = kind
        self.nth = nth

    def __reduce__(self):
        # picklable with its real constructor args: a fault injected
        # inside a process-pool worker must cross the result pipe as
        # itself, not kill the parent's result handler with a
        # TypeError during unpickling
        return (FaultInjected, (self.kind, self.nth))


#: Fast-path flag: False means no plan is installed anywhere in this
#: process and every `if _chaos._ACTIVE:` site is a single falsy check.
_ACTIVE = False

_LOCK = threading.Lock()
_PLANS: List["_Plan"] = []
_CALLS: Dict[str, int] = {}     # per-kind site call counter
_INJECTED: Dict[str, int] = {}  # per-kind fires
_ENV_DONE = False

_DEFAULT_ACTION = {"trainer.preempt": "preempt",
                   "dataloader.worker": "die",
                   "trainer.numerics": "corrupt",
                   "comm.quant": "corrupt",
                   "dist.divergence": "corrupt",
                   "elastic.worker": "die"}

#: This process's job rank for `rank=`-selected plans.  Stamped by
#: dist.init() / set_rank(); None = not yet known (resolved lazily
#: from the launcher env when a rank-selected plan is consulted).
_RANK: Optional[int] = None


def set_rank(rank: Optional[int]) -> None:
    """Stamp the process's job rank (dist.init does this) — what a
    ``rank=``-selected plan matches against."""
    global _RANK
    with _LOCK:
        _RANK = None if rank is None else int(rank)


def _current_rank_locked() -> Optional[int]:
    """The stamped rank, else the launcher env contract (the elastic
    supervisor / dmlc launchers export the rank before the framework
    ever imports, so env resolution is race-free)."""
    if _RANK is not None:
        return _RANK
    import os as _os

    for name in ("MXNET_ELASTIC_RANK", "DMLC_WORKER_ID", "PROCESS_ID"):
        v = _os.environ.get(name)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                continue
    return None


class _Plan:
    __slots__ = ("kind", "at", "times", "p", "action", "duration",
                 "rank", "_rng", "_fired")

    def __init__(self, kind: str, at: Optional[int] = None,
                 times: Optional[int] = None, p: Optional[float] = None,
                 action: Optional[str] = None, duration: float = 0.0,
                 rank: Optional[int] = None, seed: int = 0):
        if action is None:
            # the natural action per kind: a preemption site preempts,
            # a worker site kills the worker, everything else errors
            action = _DEFAULT_ACTION.get(kind, "error")
        if action not in ("error", "die", "hang", "preempt", "corrupt"):
            raise MXNetError(f"chaos action {action!r} unknown; expected "
                             "error/die/hang/preempt/corrupt")
        if sum(x is not None for x in (at, times, p)) != 1:
            raise MXNetError(
                "chaos plan needs exactly one selector: at=N (the Nth "
                "call), times=N (the next N calls), or p=F (probability)")
        self.kind, self.at, self.times, self.p = kind, at, times, p
        self.action, self.duration = action, float(duration)
        self.rank = None if rank is None else int(rank)
        self._rng = _random.Random(seed)
        self._fired = 0

    def rank_matches(self) -> bool:
        if self.rank is None:
            return True
        cur = _current_rank_locked()
        return cur is not None and cur == self.rank

    def wants(self, nth: int) -> bool:
        if self.at is not None:
            return nth == self.at
        if self.times is not None:
            return self._fired < self.times
        return self._rng.random() < self.p

    def to_spec(self) -> dict:
        """Picklable form for shipping into spawn children."""
        return {"kind": self.kind, "at": self.at, "times": self.times,
                "p": self.p, "action": self.action,
                "duration": self.duration, "rank": self.rank}


def active() -> bool:
    return _ACTIVE


def _recompute_active_locked() -> None:
    global _ACTIVE
    _ACTIVE = bool(_PLANS)


def check(kind: str) -> Optional[str]:
    """One injection-site probe.  Counts the call; if a plan fires,
    bumps telemetry + stats and performs the action:

      * ``error``   — raises :class:`FaultInjected` here;
      * ``hang``    — sleeps ``duration`` seconds here (so the caller's
                      real timeout/watchdog machinery trips), then
                      returns ``"hang"``;
      * ``preempt`` — sets the preemption flag, returns ``"preempt"``;
      * ``die``     — returns ``"die"``: the CALLER performs the death
                      (a thread exits silently, a worker process
                      ``os._exit``\\ s) because only it knows how;
      * ``corrupt`` — returns ``"corrupt"``: the CALLER poisons its
                      own data (the trainer.numerics site NaNs one
                      gradient bucket) because only it owns it.

    Returns None when nothing fired."""
    with _LOCK:
        nth = _CALLS.get(kind, 0) + 1
        _CALLS[kind] = nth
        plan = next((pl for pl in _PLANS
                     if pl.kind == kind and pl.rank_matches()
                     and pl.wants(nth)), None)
        if plan is None:
            return None
        plan._fired += 1
        _INJECTED[kind] = _INJECTED.get(kind, 0) + 1
        action, duration = plan.action, plan.duration
    from ..telemetry import instruments as _ins
    from ..telemetry import mxblackbox as _bb

    _ins.fault_injected_total(kind).inc()
    if _bb._ACTIVE:
        # fired OUTSIDE _LOCK (the journal takes its own leaf lock);
        # the entry lands before the action so a 'die' caller's
        # os._exit still leaves the fire on disk
        _bb.emit("chaos", f"fault fired at site '{kind}' call #{nth}",
                 kind=kind, action=action, nth=nth)
    if action == "error":
        raise FaultInjected(kind, nth)
    if action == "hang":
        time.sleep(duration)
        return "hang"
    if action == "preempt":
        from . import preemption

        preemption.trigger(reason=f"chaos at site '{kind}' call #{nth}")
        return "preempt"
    return action  # "die" / "corrupt": the caller performs it


class inject:
    """Scoped plan installation::

        with chaos.inject("serving.execute", at=2):      # fail call #2
        with chaos.inject("dist.collective", times=3):   # next 3 calls
        with chaos.inject("dataloader.worker", at=1, action="die"):
        with chaos.inject("dist.collective", at=1, action="hang",
                          duration=5.0):
        with chaos.inject("trainer.preempt", at=4, action="preempt"):
        with chaos.inject("elastic.worker", at=4, rank=1):  # only rank 1

    ``rank=`` makes a plan fire only in the process whose job rank
    matches (multi-process chaos: one deterministic rank dies, the
    siblings run clean even though they installed the same plan).

    Exiting the scope removes the plan (stats persist; see
    :func:`stats`/:func:`reset_stats`).  Scopes nest."""

    def __init__(self, kind: str, at: Optional[int] = None,
                 times: Optional[int] = None, p: Optional[float] = None,
                 action: Optional[str] = None, duration: float = 0.0,
                 rank: Optional[int] = None, seed: int = 0):
        self._plan = _Plan(kind, at=at, times=times, p=p, action=action,
                           duration=duration, rank=rank, seed=seed)

    def __enter__(self):
        with _LOCK:
            _PLANS.append(self._plan)
            # a fresh scope restarts the site's call numbering so at=N
            # means "the Nth call inside this scope", independent of
            # whatever ran earlier in the process
            _CALLS[self._plan.kind] = 0
            _recompute_active_locked()
        return self

    def __exit__(self, *exc):
        with _LOCK:
            try:
                _PLANS.remove(self._plan)
            except ValueError:
                pass  # mxlint: disable=MX007 — double-exit of the scope
            _recompute_active_locked()
        return False

    @property
    def fired(self) -> int:
        with _LOCK:
            return self._plan._fired


def stats() -> Dict[str, dict]:
    """Per-kind ``{"calls": n, "injected": m}`` — persists after scopes
    exit so a test can assert exactly what landed."""
    with _LOCK:
        kinds = set(_CALLS) | set(_INJECTED)
        return {k: {"calls": _CALLS.get(k, 0),
                    "injected": _INJECTED.get(k, 0)} for k in kinds}


def reset_stats() -> None:
    with _LOCK:
        _CALLS.clear()
        _INJECTED.clear()


# ---------------------------------------------------------------------------
# spawn-child transport: the DataLoader process pool ships the active
# dataloader.worker plans to its children (each child runs its own
# counters — with one worker the schedule is deterministic; with N,
# per-child).
# ---------------------------------------------------------------------------

def export_plans(kind: Optional[str] = None) -> List[dict]:
    with _LOCK:
        return [pl.to_spec() for pl in _PLANS
                if kind is None or pl.kind == kind]


def install_plans(specs: List[dict]) -> None:
    """Install exported plans (used inside spawn children at init)."""
    if not specs:
        return
    with _LOCK:
        for s in specs:
            _PLANS.append(_Plan(**s))
        _recompute_active_locked()


# ---------------------------------------------------------------------------
# env activation (subprocess experiments: nightly chaos stage, bench)
# ---------------------------------------------------------------------------

def _parse_spec(spec: str, seed: int) -> List[_Plan]:
    plans = []
    for part in (p.strip() for p in spec.split(",") if p.strip()):
        if "@" not in part:
            raise MXNetError(
                f"MXNET_CHAOS_SPEC entry {part!r}: expected kind@selector"
                "[:action][:rank=R] (e.g. 'trainer.preempt@4:preempt' "
                "or 'elastic.worker@4:die:rank=1')")
        kind, rest = part.split("@", 1)
        sel, *mods = rest.split(":")
        action, duration, rank = None, 0.0, None
        for mod in mods:
            if not mod:
                continue
            if mod.startswith("rank="):
                rank = int(mod[len("rank="):])
            elif mod.startswith("hang="):
                action, duration = "hang", float(mod[len("hang="):])
            elif "=" in mod:
                # a typo'd key= modifier must die HERE with the real
                # diagnosis, not fall through as a bogus action name
                raise MXNetError(
                    f"MXNET_CHAOS_SPEC entry {part!r}: unknown "
                    f"modifier {mod!r} (expected rank=R or "
                    f"hang=SECONDS)")
            else:
                action = mod
        at = times = p = None
        if sel.startswith("x"):
            times = int(sel[1:])
        elif sel.startswith("p"):
            p = float(sel[1:])
        else:
            at = int(sel)
        plans.append(_Plan(kind, at=at, times=times, p=p, action=action,
                           duration=duration, rank=rank, seed=seed))
    return plans


def _init_from_env() -> None:
    """Install plans from MXNET_CHAOS/MXNET_CHAOS_SPEC once (called by
    the package __init__; idempotent)."""
    global _ENV_DONE
    with _LOCK:
        if _ENV_DONE:
            return
        _ENV_DONE = True
    from ..util import env

    if not env.get_bool("MXNET_CHAOS"):
        return
    spec = env.get_str("MXNET_CHAOS_SPEC") or ""
    plans = _parse_spec(spec, env.get_int("MXNET_CHAOS_SEED"))
    with _LOCK:
        _PLANS.extend(plans)
        _recompute_active_locked()
