"""Boot-time application of a stored tuned config.

``import mxnet_tpu`` calls :func:`apply_startup_overlay` right after the
knob registry exists (before any subsystem reads its knobs), so a warm
process on a machine with a populated store boots already-tuned with
zero manual env settings.  Precedence is owned by the registry: the
overlay only fills knobs the process env leaves unset — an operator's
explicit ``MXNET_*`` export always wins.

This path MUST be free of failure modes: no store, an unreadable store,
or a corrupt entry all mean "boot on defaults", silently.  It must also
never initialize an accelerator backend (device_kind is therefore not
part of startup matching — entries carry the tune-time ``platform``
instead).
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

from ..util import env
from .store import ConfigStore, default_dir

__all__ = ["apply_startup_overlay"]


def apply_startup_overlay(framework_version: str = "") \
        -> Optional[Dict[str, Any]]:
    """Apply the best matching stored config, if any.  Returns the
    overlay application record (also via ``env.overlay_info()``) or
    None; never raises."""
    try:
        if not env.get_bool("MXNET_AUTOTUNE"):
            return None
        root = default_dir()
        if not root or not os.path.isdir(root):
            return None
        store = ConfigStore(root)
        entry = store.best_for_startup(
            scenario=env.get_str("MXNET_AUTOTUNE_SCENARIO") or "",
            framework_version=framework_version,
            platform=os.environ.get("JAX_PLATFORMS", "") or "")
        if entry is None:
            return None
        return env.apply_overlay(
            entry["config"],
            fingerprint=entry.get("config_fingerprint", ""),
            source=entry.get("path", root))
    except Exception:  # noqa: BLE001 — tuning is an optimization, never a crash
        return None
