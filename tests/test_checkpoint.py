"""Sharded checkpoint tests: save/restore of SPMDTrainer params +
optimizer state in tensorstore layout, including resume across a mesh
shape change (SURVEY.md §5 checkpoint/resume; VERDICT r2 ask #7)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel
from mxnet_tpu.gluon import loss as gloss

pytest.importorskip("orbax.checkpoint")


def _make_net(seed=7):
    np.random.seed(seed)
    mx.random.seed(seed)
    # fixed prefix: checkpoint keys must not depend on how many nets
    # were created earlier in the process
    net = mx.gluon.nn.HybridSequential(prefix="net_")
    with net.name_scope():
        net.add(mx.gluon.nn.Dense(32, activation="relu"),
                mx.gluon.nn.Dense(8))
    net.initialize(ctx=mx.cpu())
    net(nd.zeros((2, 16)))
    return net


def _gathered(trainer):
    import jax

    return {n: np.asarray(jax.device_get(v))
            for n, v in trainer.params.items()}


def test_sharded_roundtrip_same_mesh(tmp_path):
    mesh = parallel.make_mesh(dp=8)
    rng = np.random.RandomState(0)
    x = rng.randn(32, 16).astype("f4")
    y = (rng.rand(32) * 8).astype(np.int32)
    with mesh:
        tr = parallel.SPMDTrainer(_make_net(), gloss.SoftmaxCrossEntropyLoss(),
                                  "sgd", {"learning_rate": 0.1,
                                          "momentum": 0.9})
        for _ in range(3):
            tr.step(x, y)
        tr.save_checkpoint(str(tmp_path / "ckpt"))
        before = _gathered(tr)
        t_before = tr._t
        mom_before = {n: np.asarray(s[0]) for n, s in tr.opt_state.items()}

        tr2 = parallel.SPMDTrainer(_make_net(seed=99),
                                   gloss.SoftmaxCrossEntropyLoss(),
                                   "sgd", {"learning_rate": 0.1,
                                           "momentum": 0.9})
        tr2.load_checkpoint(str(tmp_path / "ckpt"))
        after = _gathered(tr2)
        assert tr2._t == t_before
        for n in before:
            np.testing.assert_array_equal(before[n], after[n])
        for n, m in mom_before.items():
            np.testing.assert_array_equal(m, np.asarray(tr2.opt_state[n][0]))


def test_resume_across_mesh_change_matches_uninterrupted(tmp_path):
    """Save on an fsdp=8 mesh (params sharded), resume on dp=2 x fsdp=4
    — the restored run must produce bit-identical training to an
    uninterrupted run."""
    rng = np.random.RandomState(1)
    x = rng.randn(32, 16).astype("f4")
    y = (rng.rand(32) * 8).astype(np.int32)

    # small fsdp threshold so the tiny test net actually shards
    rules = parallel.ShardingRules(fsdp_min_size=64)

    # uninterrupted reference: 6 steps on the SECOND mesh layout
    with parallel.make_mesh(dp=2, fsdp=4):
        ref = parallel.SPMDTrainer(_make_net(), gloss.SoftmaxCrossEntropyLoss(),
                                   "sgd", {"learning_rate": 0.1,
                                           "momentum": 0.9}, rules=rules)
        ref_losses = [float(ref.step(x, y).asnumpy()) for _ in range(6)]
        ref_params = _gathered(ref)

    # interrupted: 3 steps on fsdp=8, checkpoint, resume on dp=2 x fsdp=4
    with parallel.make_mesh(fsdp=8):
        tr = parallel.SPMDTrainer(_make_net(), gloss.SoftmaxCrossEntropyLoss(),
                                  "sgd", {"learning_rate": 0.1,
                                          "momentum": 0.9}, rules=rules)
        sharded = [n for n in tr.params
                   if not tr._shardings[n].is_fully_replicated]
        assert sharded, "fsdp mesh must actually shard some params"
        for _ in range(3):
            tr.step(x, y)
        tr.save_checkpoint(str(tmp_path / "ckpt2"))

    with parallel.make_mesh(dp=2, fsdp=4):
        tr2 = parallel.SPMDTrainer(_make_net(seed=99),
                                   gloss.SoftmaxCrossEntropyLoss(),
                                   "sgd", {"learning_rate": 0.1,
                                           "momentum": 0.9}, rules=rules)
        tr2.load_checkpoint(str(tmp_path / "ckpt2"))
        assert tr2._t == 3
        resumed = [float(tr2.step(x, y).asnumpy()) for _ in range(3)]
        res_params = _gathered(tr2)

    np.testing.assert_allclose(resumed, ref_losses[3:], rtol=1e-5)
    for n in ref_params:
        np.testing.assert_allclose(res_params[n], ref_params[n],
                                   rtol=1e-5, atol=1e-6)


def test_load_mismatched_params_is_loud(tmp_path):
    with parallel.make_mesh(dp=8):
        tr = parallel.SPMDTrainer(_make_net(), gloss.SoftmaxCrossEntropyLoss(),
                                  "sgd", {"learning_rate": 0.1})
        tr.save_checkpoint(str(tmp_path / "ckpt3"))
        other = mx.gluon.nn.Dense(4)
        other.initialize(ctx=mx.cpu())
        other(nd.zeros((1, 16)))
        tr2 = parallel.SPMDTrainer(other, gloss.SoftmaxCrossEntropyLoss(),
                                   "sgd", {"learning_rate": 0.1})
        with pytest.raises(Exception):
            tr2.load_checkpoint(str(tmp_path / "ckpt3"))
