"""Tests for mxnet_tpu.parallel: mesh, sharding rules, SPMD training,
ring attention, pipeline parallelism — on the 8-virtual-device CPU backend
(SURVEY.md §4: multi-device behaviour simulated via XLA host devices)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.gluon import nn, loss as gloss


def test_mesh_basics():
    mesh = parallel.make_mesh(dp=4, tp=2)
    assert mesh.size() == 8
    assert mesh.size("dp") == 4 and mesh.size("tp") == 2
    assert "dp" in mesh and "pp" not in mesh
    with mesh:
        assert parallel.current_mesh() is mesh
    assert parallel.current_mesh() is None


def test_mesh_default_all_devices():
    mesh = parallel.make_mesh()
    assert mesh.size("dp") == jax.device_count()


def test_sharding_rules_tp_and_fallback():
    mesh = parallel.make_mesh(dp=2, tp=2)
    rules = parallel.DEFAULT_RULES
    spec = rules.spec_for("bert0_attn_qkv_weight", (192, 64), mesh)
    assert spec == P("tp", None)
    # row-parallel out projection
    spec = rules.spec_for("bert0_attn_out_proj_weight", (64, 64), mesh)
    assert spec == P(None, "tp")
    # unmatched -> replicated (no fsdp axis)
    assert rules.spec_for("conv0_weight", (64, 3, 3, 3), mesh) == P()
    # non-divisible dims fall through to replication
    assert rules.spec_for("q_proj_weight", (63, 64), mesh) == P()


def test_sharding_rules_fsdp():
    mesh = parallel.make_mesh(fsdp=8)
    rules = parallel.ShardingRules()
    spec = rules.spec_for("dense0_weight", (256, 128), mesh)
    assert spec == P("fsdp", None)
    # tiny params stay replicated
    assert rules.spec_for("dense0_bias", (128,), mesh) == P()


def test_shard_batch_spec():
    mesh = parallel.make_mesh(dp=2, sp=4)
    sh = parallel.shard_batch(mesh, extra_dims=2, seq_axis=1)
    assert sh.spec == P(("dp",), "sp", None)


def _make_mlp():
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=16))
    net.add(nn.Dense(10, in_units=32))
    net.initialize()
    return net


def test_spmd_trainer_dp_loss_decreases():
    mesh = parallel.make_mesh(dp=8)
    with mesh:
        net = _make_mlp()
        trainer = parallel.SPMDTrainer(
            net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.5})
        rng = np.random.RandomState(0)
        x = rng.randn(64, 16).astype(np.float32)
        y = (rng.rand(64) * 10).astype(np.int32)
        losses = [float(trainer.step(x, y).asnumpy()) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_spmd_trainer_matches_local_training():
    """DP-SPMD must compute the same math as single-device Trainer+KVStore
    (the check_consistency pattern, SURVEY.md §4)."""
    rng = np.random.RandomState(1)
    x = rng.randn(32, 16).astype(np.float32)
    y = (rng.rand(32) * 10).astype(np.int32)

    def run_local():
        np.random.seed(7)
        mx.random.seed(7)
        net = _make_mlp()
        tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                              {"learning_rate": 0.1})
        lfn = gloss.SoftmaxCrossEntropyLoss()
        for _ in range(5):
            with mx.autograd.record():
                l = lfn(net(mx.nd.array(x)), mx.nd.array(y)).mean()
            l.backward()
            tr.step(1)  # loss is already a mean
        return {n: p.data().asnumpy()
                for n, p in sorted(net.collect_params().items())}

    def run_spmd():
        np.random.seed(7)
        mx.random.seed(7)
        mesh = parallel.make_mesh(dp=4)
        with mesh:
            net = _make_mlp()
            tr = parallel.SPMDTrainer(net, gloss.SoftmaxCrossEntropyLoss(),
                                      "sgd", {"learning_rate": 0.1})
            for _ in range(5):
                tr.step(x, y)
            tr.sync_to_block()
            return {n: p.data().asnumpy()
                    for n, p in sorted(net.collect_params().items())}

    local, spmd = run_local(), run_spmd()
    # strip differing name-scope counters: compare by order
    for (_, a), (_, b) in zip(sorted(local.items()), sorted(spmd.items())):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_spmd_trainer_tp_mesh():
    """Params matching tp rules actually shard; training still works."""
    mesh = parallel.make_mesh(dp=2, tp=4)
    with mesh:
        net = nn.HybridSequential(prefix="tpnet_")
        with net.name_scope():
            net.add(nn.Dense(64, activation="relu", in_units=16,
                             prefix="fc1_"))
            net.add(nn.Dense(10, in_units=64, prefix="head_"))
        net.initialize()
        trainer = parallel.SPMDTrainer(
            net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.2})
        w1 = trainer.params["tpnet_fc1_weight"]
        assert w1.sharding.spec == P("tp", None)
        rng = np.random.RandomState(0)
        x = rng.randn(16, 16).astype(np.float32)
        y = (rng.rand(16) * 10).astype(np.int32)
        l0 = float(trainer.step(x, y).asnumpy())
        for _ in range(20):
            l = float(trainer.step(x, y).asnumpy())
        assert l < l0


def test_spmd_trainer_adam_and_bn():
    """Adam functional path + BatchNorm aux-state updates under SPMD."""
    mesh = parallel.make_mesh(dp=8)
    with mesh:
        net = nn.HybridSequential()
        net.add(nn.Dense(32, in_units=16))
        net.add(nn.BatchNorm(in_channels=32))
        net.add(nn.Activation("relu"))
        net.add(nn.Dense(10, in_units=32))
        net.initialize()
        trainer = parallel.SPMDTrainer(
            net, gloss.SoftmaxCrossEntropyLoss(), "adam",
            {"learning_rate": 1e-2})
        rng = np.random.RandomState(0)
        x = rng.randn(64, 16).astype(np.float32) * 3 + 1
        y = (rng.rand(64) * 10).astype(np.int32)
        mean_before = net[1].running_mean.data().asnumpy().copy()
        losses = [float(trainer.step(x, y).asnumpy()) for _ in range(20)]
        mean_after = net[1].running_mean.data().asnumpy()
    assert losses[-1] < losses[0]
    assert not np.allclose(mean_before, mean_after)
    # stats must ACCUMULATE across steps (EMA toward the batch stats), not
    # re-apply one step from init: after N steps with near-constant input
    # distribution, |mean| magnitude ≈ (1 - momentum^N) * batch_mean ≫ one
    # step's (1 - momentum) * batch_mean
    one_step_norm = 0.1 * np.abs(mean_after).max() / max(
        1.0 - 0.9 ** 20, 1e-9)
    assert np.abs(mean_after).max() > 3 * one_step_norm


def test_functional_rmsprop_centered_and_adagrad_eps():
    from mxnet_tpu.parallel.spmd import functional_optimizer
    import mxnet_tpu.optimizer as opt_mod

    f = functional_optimizer(opt_mod.create("rmsprop", centered=True))
    assert f.n_state == 3
    f2 = functional_optimizer(opt_mod.create("rmsprop"))
    assert f2.n_state == 1
    # adagrad with custom eps must not crash and must produce finite updates
    f3 = functional_optimizer(opt_mod.create("adagrad"))
    w = jnp.ones((4,))
    g = jnp.ones((4,))
    nw, ns = f3.apply(w, g, f3.init(w), jnp.float32(0.1), jnp.int32(1))
    assert np.all(np.isfinite(np.asarray(nw)))


def test_ring_attention_matches_dense():
    mesh = parallel.make_mesh(sp=8)
    rng = np.random.RandomState(0)
    B, H, L, D = 2, 4, 64, 16
    q = rng.randn(B, H, L, D).astype(np.float32)
    k = rng.randn(B, H, L, D).astype(np.float32)
    v = rng.randn(B, H, L, D).astype(np.float32)
    ref = parallel.ring.local_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    with mesh:
        out = parallel.ring.ring_attention_sharded(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_causal():
    mesh = parallel.make_mesh(sp=4)
    rng = np.random.RandomState(1)
    B, H, L, D = 1, 2, 32, 8
    q = rng.randn(B, H, L, D).astype(np.float32)
    k = rng.randn(B, H, L, D).astype(np.float32)
    v = rng.randn(B, H, L, D).astype(np.float32)
    ref = parallel.ring.local_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True)
    with mesh:
        out = parallel.ring.ring_attention_sharded(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_matches_sequential():
    mesh = parallel.make_mesh(pp=4)
    rng = np.random.RandomState(2)
    S, B, Dm = 4, 16, 32
    ws = [rng.randn(Dm, Dm).astype(np.float32) * 0.1 for _ in range(S)]
    stacked = {"w": jnp.stack([jnp.asarray(w) for w in ws])}

    def stage(params, x):
        return jnp.tanh(x @ params["w"])

    x = rng.randn(B, Dm).astype(np.float32)
    ref = jnp.asarray(x)
    for w in ws:
        ref = jnp.tanh(ref @ jnp.asarray(w))
    with mesh:
        out = parallel.pipeline.pipeline_apply(
            stage, stacked, jnp.asarray(x), n_microbatch=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_dist_single_process_noops():
    parallel.dist.init()
    assert parallel.dist.rank() == 0
    assert parallel.dist.num_workers() == 1
    parallel.dist.barrier()
    x = mx.nd.array(np.ones((3,), np.float32))
    out = parallel.dist.allreduce_nd(x)
    np.testing.assert_allclose(out.asnumpy(), np.ones(3))


def test_spmd_trainer_bf16_master_weights():
    """bf16 params carry an fp32 master weight in the optimizer state
    (reference mp_sgd_* weight32 semantics): updates far below one bf16
    ulp must still accumulate instead of rounding away."""
    mesh = parallel.make_mesh(dp=1)
    with mesh:
        net = mx.gluon.nn.Dense(1, use_bias=False)
        net.initialize(mx.initializer.One(), ctx=mx.cpu())
        net(mx.nd.ones((1, 4)))
        net.cast("bfloat16")
        # plain SGD, no momentum: each update is lr * grad
        opt = mx.optimizer.SGD(learning_rate=1e-4, multi_precision=True)
        trainer = parallel.SPMDTrainer(
            net, lambda out, y: ((out - y) ** 2).mean(), opt,
            n_labels=1)
        name = [n for n, _ in trainer._plist][0]
        assert trainer._has_master[name]
        x = np.ones((8, 4), "bfloat16")
        y = np.zeros((8, 1), "bfloat16")
        for _ in range(40):
            trainer.step(x, y)
        master = np.asarray(trainer.opt_state[name][-1], dtype="float32")
        # grad = 2*(w.x) * x = 8 per element initially; 40 steps of ~8e-4
        # updates: far below bf16 ulp (0.0078 at 1.0) per step, but the
        # master must have accumulated a visible decrease
        assert master.max() < 1.0 - 1e-3, master
        assert master.dtype == np.float32


def test_spmd_trainer_retrace_on_shape_change():
    """Mid-training input-shape change retraces the step; BN aux stats
    must keep flowing correctly (aux is keyed by name in the traced
    outputs, not by a trace-order side channel)."""
    mesh = parallel.make_mesh(dp=1)
    with mesh:
        net = mx.gluon.nn.HybridSequential()
        net.add(mx.gluon.nn.Dense(8), mx.gluon.nn.BatchNorm(),
                mx.gluon.nn.Dense(4))
        net.initialize(ctx=mx.cpu())
        net(mx.nd.zeros((2, 6)))
        trainer = parallel.SPMDTrainer(
            net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1})
        bn = [b for b in net._children.values()
              if isinstance(b, mx.gluon.nn.BatchNorm)][0]
        rng = np.random.RandomState(0)
        for bs in (16, 16, 24, 16, 32):  # shape changes force retraces
            x = (rng.randn(bs, 6) * 2 + 1).astype("f4")
            y = (rng.rand(bs) * 4).astype(np.int32)
            loss = trainer.step(x, y)
            assert np.isfinite(float(loss.asnumpy()))
        # moving stats moved off their init and stayed finite
        mm = bn.running_mean.data().asnumpy()
        mv = bn.running_var.data().asnumpy()
        assert np.isfinite(mm).all() and np.isfinite(mv).all()
        assert not np.allclose(mm, 0.0)
        assert not np.allclose(mv, 1.0)


def test_collective_watchdog():
    """_run_with_watchdog: passes values/errors through, and converts a
    never-completing collective into a loud MXNetError."""
    import os
    import time

    from mxnet_tpu.parallel import dist

    try:
        assert dist._run_with_watchdog(lambda: 42, timeout=5,
                                       what="x") == 42
        with pytest.raises(ValueError):
            dist._run_with_watchdog(lambda: (_ for _ in ()).throw(
                ValueError("boom")), timeout=5, what="x")
        with pytest.raises(mx.MXNetError, match="timed out.*unreachable"):
            dist._run_with_watchdog(lambda: time.sleep(30), timeout=0.2,
                                    what="hung")
        # the timed-out collective may complete later on its stuck
        # thread: all further collectives must refuse (sequence desync)
        with pytest.raises(mx.MXNetError, match="refused"):
            dist._run_with_watchdog(lambda: 1, timeout=5, what="next")
        dist._POISONED = None
        # env-var route (MXNET_KVSTORE_TIMEOUT)
        os.environ[dist._TIMEOUT_ENV] = "0.2"
        with pytest.raises(mx.MXNetError, match="timed out"):
            dist._run_with_watchdog(lambda: time.sleep(30), timeout=None,
                                    what="hung")
        os.environ[dist._TIMEOUT_ENV] = "5m"
        with pytest.raises(mx.MXNetError, match="MXNET_KVSTORE_TIMEOUT"):
            dist._collective_timeout(None)
    finally:
        dist._POISONED = None
        os.environ.pop(dist._TIMEOUT_ENV, None)


def test_dist_async_emulation_pin():
    """dist_async is served by the dist_sync path (documented emulation:
    synchronous application is a legal schedule of async). Pin the
    observable semantics so a behavioral change is caught — and that
    creation warns ONCE that the staleness semantics changed (round-4
    verdict item #7)."""
    import warnings

    from mxnet_tpu import kvstore as kvs

    kvs._ASYNC_WARNED[0] = False
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        kv = mx.kvstore.create("dist_async")
        again = mx.kvstore.create("dist_async")
    msgs = [str(w.message) for w in rec
            if "emulated as 'dist_sync'" in str(w.message)]
    assert len(msgs) == 1, msgs  # loud, but once per process
    del again
    assert kv.type == "dist_async"
    assert kv.num_workers == 1  # single-process here
    kv.init(0, mx.nd.zeros((3,)))
    kv.push(0, mx.nd.array(np.array([1.0, 2.0, 3.0], "f4")))
    out = mx.nd.zeros((3,))
    kv.pull(0, out)
    # same-result-as-sync pin: push overwrites the stored value
    np.testing.assert_array_equal(out.asnumpy(), [1.0, 2.0, 3.0])
    sync = mx.kvstore.create("dist_sync")
    sync.init(0, mx.nd.zeros((3,)))
    sync.push(0, mx.nd.array(np.array([1.0, 2.0, 3.0], "f4")))
    out2 = mx.nd.zeros((3,))
    sync.pull(0, out2)
    np.testing.assert_array_equal(out.asnumpy(), out2.asnumpy())


def test_spmd_trainer_remat_segments():
    """SPMDTrainer(remat=True): gradients identical to the plain step,
    and the compiled step really contains remat segments."""
    from mxnet_tpu.gluon import nn, loss as gloss
    from mxnet_tpu import parallel

    def build(remat):
        np.random.seed(0)
        net = nn.Sequential()
        net.add(nn.Dense(8, activation="relu", in_units=6))
        net.add(nn.Dense(4, in_units=8))
        net.initialize(mx.initializer.Xavier())
        mesh = parallel.make_mesh(dp=2)
        return parallel.SPMDTrainer(
            net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1}, mesh=mesh, remat=remat), net

    rng = np.random.RandomState(0)
    X = rng.randn(8, 6).astype("f4")
    y = (rng.rand(8) * 4).astype(np.int32)
    losses = []
    jaxprs = []
    for remat in (False, True):
        tr, net = build(remat)
        for _ in range(3):
            l = tr.step(X, y)
        losses.append(float(l.asnumpy()))
        # the compiled step must literally contain remat segments when on
        import jax as _jax

        pure = tr._build_pure()
        key = _jax.numpy.zeros((2,), _jax.numpy.uint32)
        jaxprs.append(str(_jax.make_jaxpr(pure)(
            {n: v for n, v in tr.params.items()}, tr.opt_state,
            (_jax.numpy.asarray(X),), (_jax.numpy.asarray(y),), key,
            _jax.numpy.float32(0.1), _jax.numpy.int32(1))))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5)
    assert "remat" not in jaxprs[0] and "checkpoint" not in jaxprs[0]
    assert "remat" in jaxprs[1] or "checkpoint" in jaxprs[1]


def test_spmd_batchnorm_is_sync_bn():
    """Under dp-sharded SPMD, BatchNorm statistics are computed over the
    GLOBAL batch (GSPMD reduces over the full logical array), i.e.
    SyncBatchNorm semantics come for free — pin it: per-shard stats
    would differ from the global-batch oracle."""
    from mxnet_tpu.gluon import nn, loss as gloss

    mesh = parallel.make_mesh(dp=8)
    rng = np.random.RandomState(0)
    # make shards statistically DIFFERENT so per-shard stats would be
    # visibly wrong: sample i's scale grows with its index
    x = (rng.randn(64, 16) * np.linspace(0.5, 4.0, 64)[:, None]) \
        .astype(np.float32)
    y = (rng.rand(64) * 4).astype(np.int32)
    with mesh:
        net = nn.HybridSequential()
        net.add(nn.Dense(8, in_units=16))
        net.add(nn.BatchNorm(in_channels=8, momentum=0.0))  # stats=batch
        net.add(nn.Dense(4, in_units=8))
        net.initialize(mx.initializer.Xavier())
        tr = parallel.SPMDTrainer(net, gloss.SoftmaxCrossEntropyLoss(),
                                  "sgd", {"learning_rate": 0.0})
        tr.step(x, y)
        tr.sync_to_block()
        got_mean = net[1].running_mean.data().asnumpy()
    # oracle: global-batch stats of the SAME pre-BN activations
    w = net[0].weight.data().asnumpy()
    b = net[0].bias.data().asnumpy()
    pre = x @ w.T + b
    np.testing.assert_allclose(got_mean, pre.mean(axis=0), rtol=1e-4,
                               atol=1e-5)


def test_hetero_pipeline_matches_sequential():
    """HeteroPipeline: stages with DIFFERENT param shapes and activation
    widths (16->32->8->4) across devices must reproduce the
    single-device forward, loss, and every parameter gradient."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel.pipeline import HeteroPipeline

    rng = np.random.RandomState(0)
    p0 = {"w": jnp.asarray(rng.randn(16, 32).astype("float32")) * 0.1}
    p1 = {"w": jnp.asarray(rng.randn(32, 8).astype("float32")) * 0.1,
          "b": jnp.zeros((8,), jnp.float32)}
    p2 = {"w": jnp.asarray(rng.randn(8, 4).astype("float32")) * 0.1}

    def f0(p, a):
        return jnp.tanh(a @ p["w"])

    def f1(p, a):
        return jax.nn.relu(a @ p["w"] + p["b"])

    def f2(p, a):
        return a @ p["w"]

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    x = rng.randn(8, 16).astype("float32")
    t = rng.randn(8, 4).astype("float32")

    pipe = HeteroPipeline([f0, f1, f2], [p0, p1, p2])
    y = np.asarray(pipe(x, n_microbatch=4))

    def seq(params, xx):
        return f2(params[2], f1(params[1], f0(params[0], xx)))

    y_ref = np.asarray(seq([p0, p1, p2], jnp.asarray(x)))
    np.testing.assert_allclose(y, y_ref, rtol=1e-5, atol=1e-6)

    loss, grads = pipe.value_and_grad(loss_fn, x, t, n_microbatch=4)
    ref_loss, ref_grads = jax.value_and_grad(
        lambda ps: loss_fn(seq(ps, jnp.asarray(x)), jnp.asarray(t)))(
        [p0, p1, p2])
    np.testing.assert_allclose(loss, float(ref_loss), rtol=1e-5)
    for g, rg in zip(grads, ref_grads):
        for k in rg:
            np.testing.assert_allclose(np.asarray(g[k]),
                                       np.asarray(rg[k]),
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=f"grad {k}")
    # stages really live on distinct devices
    devs = {list(p["w"].devices())[0] for p in pipe.params}
    assert len(devs) == 3


def test_ulysses_attention_matches_dense_and_ring():
    """All-to-all sequence parallelism: matches dense attention exactly
    (and hence the ring variant) for plain and causal, including H == n
    (one head per device)."""
    mesh = parallel.make_mesh(sp=4)
    rng = np.random.RandomState(3)
    B, H, L, D = 2, 4, 32, 8
    q = jnp.asarray(rng.randn(B, H, L, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, L, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, L, D).astype(np.float32))
    for causal in (False, True):
        ref = parallel.ring.local_attention(q, k, v, causal=causal)
        with mesh:
            out = parallel.ulysses.ulysses_attention_sharded(
                q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = parallel.make_mesh(sp=8)
    rng = np.random.RandomState(4)
    q = jnp.asarray(rng.randn(1, 4, 32, 8).astype(np.float32))  # H=4 < sp=8
    with mesh, pytest.raises(mx.MXNetError, match="divisible"):
        parallel.ulysses.ulysses_attention_sharded(q, q, q)


def _moe_oracle(ws, x, gl, capacity):
    """Pure-numpy top-1 capacity MoE (GShard drop semantics)."""
    t, e = gl.shape
    probs = np.exp(gl - gl.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    pick = probs.argmax(-1)
    gate = probs.max(-1)
    counts = np.zeros(e, np.int64)
    y = np.zeros((t, ws[0].shape[1]), np.float32)
    for i in range(t):
        ei = pick[i]
        if counts[ei] < capacity:
            y[i] = np.tanh(x[i] @ ws[ei]) * gate[i]
            counts[ei] += 1
    return y


def test_moe_expert_parallel_matches_oracle():
    import math
    from mxnet_tpu.parallel import moe

    rng = np.random.RandomState(5)
    T, D, E, cf = 32, 8, 4, 1.25
    x = rng.randn(T, D).astype(np.float32)
    gl = rng.randn(T, E).astype(np.float32)
    ws = [rng.randn(D, D).astype(np.float32) * 0.3 for _ in range(E)]
    stacked = {"w": jnp.stack([jnp.asarray(w) for w in ws])}

    def expert(p, tok):
        return jnp.tanh(tok @ p["w"])

    cap = max(1, math.ceil(T / E * cf))
    ref = _moe_oracle(ws, x, gl, cap)
    with parallel.make_mesh(ep=4):
        y, aux = moe.moe_apply(expert, stacked, jnp.asarray(x),
                               jnp.asarray(gl), capacity_factor=cf)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-5, atol=2e-5)
    assert 0.0 <= float(aux["dropped_frac"]) < 1.0
    # no-mesh fallback matches too
    y2, _ = moe.moe_apply(expert, stacked, jnp.asarray(x),
                          jnp.asarray(gl), capacity_factor=cf)
    np.testing.assert_allclose(np.asarray(y2), ref, rtol=2e-5, atol=2e-5)
