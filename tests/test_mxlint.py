"""Tier-1 mxlint gate (ISSUE 4): the framework must lint clean against
the committed baseline, the baseline must ratchet (new violations
fail), docs/env_vars.md must match the live knob registry, and the
lint-driven thread-safety fixes must hold under contention."""
import json
import os
import threading
import time

import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis
from mxnet_tpu.util import env

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BASELINE = os.path.join(_REPO, "MXLINT_BASELINE.json")
_PKG = os.path.join(_REPO, "mxnet_tpu")


_LINT_CACHE = []


def _run_lint():
    """One full-package lint shared by every assertion in this module
    (each run costs ~4s of tier-1 budget)."""
    if not _LINT_CACHE:
        eng = analysis.LintEngine(root=_REPO)
        t0 = time.perf_counter()
        violations = eng.run([_PKG])
        _LINT_CACHE.append((eng, violations, time.perf_counter() - t0))
    return _LINT_CACHE[0]


class TestSelfLintGate:
    def test_package_lints_clean_against_baseline(self):
        eng, violations, elapsed = _run_lint()
        new, suppressed, stale = analysis.diff_baseline(
            violations, analysis.load_baseline(_BASELINE))
        assert eng.errors == [], f"unparsable files: {eng.errors}"
        assert new == [], (
            "NEW mxlint violations (fix them or — with a written "
            "justification — add to MXLINT_BASELINE.json):\n"
            + "\n".join(v.format() for v in new))
        # acceptance criterion (ISSUE 8): full-package lint incl. the
        # mxflow whole-program pass stays under 30s even with a cold
        # summary cache (warm runs are ~6s)
        assert elapsed < 30.0, f"lint took {elapsed:.1f}s (budget 30s)"

    def test_introducing_a_violation_fails_the_gate(self, tmp_path):
        bad = tmp_path / "regression.py"
        bad.write_text("_CACHE = {}\n\n"
                       "def put(k, v):\n"
                       "    _CACHE[k] = v\n")
        eng = analysis.LintEngine(root=_REPO)
        violations = eng.run([str(bad)])  # package itself is covered
                                          # by the gate test above
        new, _, _ = analysis.diff_baseline(
            violations, analysis.load_baseline(_BASELINE))
        assert [v.rule for v in new] == ["MX004"]

    def test_baseline_entries_all_have_justifications(self):
        entries = analysis.load_baseline(_BASELINE)
        assert entries, "baseline unexpectedly empty"
        bad = [e for e in entries
               if not e.get("justification", "").strip()]
        assert bad == []

    def test_no_stale_baseline_entries(self):
        _, violations, _ = _run_lint()
        _, _, stale = analysis.diff_baseline(
            violations, analysis.load_baseline(_BASELINE))
        assert stale == [], (
            "baseline entries whose violation was fixed — delete them "
            "(ratchet down):\n" + json.dumps(stale, indent=1))


class TestEnvDocsSync:
    def test_env_vars_md_matches_registry(self):
        committed = open(os.path.join(_REPO, "docs", "env_vars.md"),
                         encoding="utf-8").read()
        assert committed == env.generate_docs(), (
            "docs/env_vars.md is stale — regenerate with "
            "`python tools/mxlint.py --env-docs docs/env_vars.md`")

    def test_every_mxnet_read_site_is_declared(self):
        # the knob registry raises on undeclared names; a couple of
        # spot checks that migrated call sites resolve
        assert env.is_declared("MXNET_ENGINE_TYPE")
        assert env.is_declared("MXNET_FUSED_BUCKET_BYTES")
        with pytest.raises(mx.MXNetError):
            env.get_bool("MXNET_TOTALLY_UNKNOWN_KNOB")

    def test_numeric_bool_values_keep_working(self, monkeypatch):
        # reference knobs are int-typed booleans: MXNET_TELEMETRY=2
        # historically meant true — the registry migration must not
        # turn that into an import-time crash
        monkeypatch.setenv("MXNET_TELEMETRY", "2")
        assert env.get_bool("MXNET_TELEMETRY") is True
        monkeypatch.setenv("MXNET_TELEMETRY", "0")
        assert env.get_bool("MXNET_TELEMETRY") is False
        monkeypatch.setenv("MXNET_TELEMETRY", "banana")
        with pytest.raises(mx.MXNetError):
            env.get_bool("MXNET_TELEMETRY")

    def test_empty_string_means_unset(self, monkeypatch):
        # launchers export VAR="" as the 'use the default' spelling
        monkeypatch.setenv("MXNET_KVSTORE_TIMEOUT", "")
        assert env.get_float("MXNET_KVSTORE_TIMEOUT") is None
        monkeypatch.setenv("MXNET_FUSED_BUCKET_BYTES", "")
        assert env.get_int("MXNET_FUSED_BUCKET_BYTES") == 4 << 20

    def test_duplicate_declaration_raises(self):
        with pytest.raises(mx.MXNetError, match="already registered"):
            env.declare("MXNET_ENGINE_TYPE", int, 3, "conflict")
        # even an IDENTICAL re-declaration is rejected loudly: two call
        # sites each believing they own a knob is the drift the
        # registry exists to prevent (the second would silently shadow
        # doc/tunable edits to the first)
        with pytest.raises(mx.MXNetError, match="already registered"):
            env.declare("MXNET_USE_PALLAS", bool, True,
                        "Master switch for Pallas kernels (flash "
                        "attention, fused Conv+BN). 0 selects the XLA "
                        "fallbacks with identical semantics.")


class TestLintDrivenHardening:
    """Regression tests for the CONFIRMED MX004 findings fixed in this
    PR: module caches shared with serving/dataloader threads now take
    the double-checked-lock path."""

    def test_pallas_convbn_decides_once_under_contention(self, monkeypatch):
        from mxnet_tpu.ops import pallas_convbn as pc

        calls = []

        def slow_decide():
            calls.append(1)
            time.sleep(0.05)
            return False

        monkeypatch.setattr(pc, "_decide_pallas", slow_decide)
        # swap the whole latch dict (not setitem): under MXNET_SAN the
        # module dict is lockset-tracked, and monkeypatch's unlocked
        # teardown write would read as a seeded race
        monkeypatch.setattr(pc, "_STATE", {"enabled": None})
        results = []
        threads = [threading.Thread(
            target=lambda: results.append(pc._pallas_wanted()))
            for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(calls) == 1, "probe ran once despite 8 racing threads"
        assert results == [False] * 8

    def test_pallas_attention_decides_once_under_contention(
            self, monkeypatch):
        from mxnet_tpu.ops import pallas_attention as pa

        calls = []

        def slow_decide():
            calls.append(1)
            time.sleep(0.05)
            return False

        monkeypatch.setattr(pa, "_decide_pallas", slow_decide)
        # setattr, not setitem — see the convbn twin above
        monkeypatch.setattr(pa, "_PALLAS_STATE", {"enabled": None})
        results = []
        threads = [threading.Thread(
            target=lambda: results.append(pa._pallas_wanted()))
            for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(calls) == 1 and results == [False] * 8

    def test_probe_cache_single_probe_per_key(self, monkeypatch):
        from mxnet_tpu.ops import pallas_convbn as pc

        monkeypatch.setattr(pc, "_SHAPE_OK", {})
        monkeypatch.setattr(pc, "_PROBE_SPENT", [0.0])
        monkeypatch.setattr(pc.env, "get_bool",
                            lambda name, default=None: False)
        compiles = []

        class _FakeJit:
            def lower(self, *a):
                return self

            def compile(self):
                compiles.append(1)
                time.sleep(0.05)
                return self

        monkeypatch.setattr(pc.jax, "jit", lambda fn: _FakeJit())
        out = []
        threads = [threading.Thread(
            target=lambda: out.append(pc._probe_ok("k", None, ())))
            for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(compiles) == 1, "one probe compile despite the race"
        assert out == [True] * 8
        assert pc._SHAPE_OK == {("k", False): True}

    def test_deploy_namedtuple_cache_yields_one_class(self):
        from mxnet_tpu.contrib import deploy

        deploy._NT_CACHE.clear()
        got = []
        barrier = threading.Barrier(8)

        def hit():
            barrier.wait()
            got.append(deploy._namedtuple_cls("Out", ("a", "b")))

        threads = [threading.Thread(target=hit) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len({id(c) for c in got}) == 1, \
            "identity-stable class per (name, fields) key"

    def test_symbol_namespace_cache_identity(self):
        import mxnet_tpu.symbol as sym

        sym._CACHE.pop("relu", None)
        got = []
        barrier = threading.Barrier(8)

        def hit():
            barrier.wait()
            got.append(getattr(sym, "relu"))

        threads = [threading.Thread(target=hit) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # the lazily generated wrapper must resolve to ONE function
        # object no matter which thread generated it
        assert len({id(f) for f in got}) == 1

    def test_profiler_set_config_is_lock_guarded(self):
        # concurrent set_config must neither corrupt nor lose keys
        before = dict(mx.profiler._config)
        try:
            threads = [threading.Thread(
                target=mx.profiler.set_config,
                kwargs={"aggregate_stats": bool(i % 2)})
                for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert set(mx.profiler._config) == set(before)
        finally:
            mx.profiler.set_config(
                aggregate_stats=before["aggregate_stats"])


class TestDocDriftSync:
    """Cross-artifact drift (ISSUE 8, the cheap seventh pass): the
    operator-facing docs must list what actually exists — same shape
    as the env_vars.md sync gate."""

    def test_instruments_and_chaos_sites_are_documented(self):
        assert analysis.drift_findings(_REPO) == [], (
            "doc drift — every telemetry instrument belongs in "
            "docs/observability.md, every chaos site in "
            "docs/resilience.md (run `python tools/mxlint.py --drift`)")

    def test_scanners_see_the_real_surfaces(self):
        names = analysis.instrument_names(os.path.join(
            _REPO, "mxnet_tpu", "telemetry", "instruments.py"))
        assert "mx_retry_total" in names
        assert "mx_compile_cache_hit_total" in names
        assert len(names) >= 15
        sites = analysis.chaos_sites(os.path.join(_REPO, "mxnet_tpu"))
        assert {"serving.execute", "compile_cache.io",
                "dataloader.worker"} <= sites

    def test_missing_doc_row_is_reported(self, tmp_path):
        # synthetic repo: one instrument, empty docs -> one finding
        (tmp_path / "mxnet_tpu" / "telemetry").mkdir(parents=True)
        (tmp_path / "docs").mkdir()
        (tmp_path / "mxnet_tpu" / "telemetry" / "instruments.py"
         ).write_text('def x():\n    return _child("mx_shiny_total",'
                      ' "counter", "h")\n')
        (tmp_path / "docs" / "observability.md").write_text("# empty\n")
        (tmp_path / "docs" / "resilience.md").write_text("# empty\n")
        findings = analysis.drift_findings(str(tmp_path))
        assert any("mx_shiny_total" in f for f in findings)


class TestSarif:
    def test_render_sarif_shape_and_fingerprints(self, tmp_path):
        bad = tmp_path / "s.py"
        bad.write_text("_C = {}\n\ndef p(k, v):\n    _C[k] = v\n")
        eng = analysis.LintEngine(root=str(tmp_path))
        vs = eng.run([str(bad)])
        doc = analysis.render_sarif(vs)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert {"MX004", "MX008", "MX012"} <= rule_ids
        [res] = [r for r in run["results"] if r["ruleId"] == "MX004"]
        assert res["partialFingerprints"]["mxlint/v1"] == \
            vs[0].fingerprint
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "s.py"
        assert loc["region"]["startLine"] == 4

    def test_cli_sarif_file_output(self, tmp_path):
        import subprocess
        import sys

        out = tmp_path / "lint.sarif"
        p = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "mxlint.py"),
             os.path.join(_REPO, "mxnet_tpu", "analysis"),
             "--baseline", _BASELINE, "--sarif", str(out)],
            capture_output=True, text=True, timeout=120, cwd=_REPO)
        assert p.returncode == 0, p.stdout[-1500:] + p.stderr[-1500:]
        doc = json.loads(out.read_text())
        assert doc["runs"][0]["results"] == []  # shipped tree is clean


class TestCLISmoke:
    def test_cli_exits_zero_on_shipped_tree(self):
        import subprocess
        import sys

        p = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "mxlint.py"),
             os.path.join(_REPO, "mxnet_tpu"),
             "--baseline", _BASELINE, "--json"],
            capture_output=True, text=True, timeout=120, cwd=_REPO)
        assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
        report = json.loads(p.stdout)
        assert report["ok"] and report["counts"]["new"] == 0
        assert report["elapsed_seconds"] < 30.0

    def test_diff_mode_flags_an_untracked_violating_file(self):
        import subprocess
        import sys

        # an untracked file inside the repo is "changed vs HEAD"
        tmp = os.path.join(_REPO, "tests", "_tmp_diff_fixture.py")
        with open(tmp, "w") as f:
            f.write("_C = {}\n\ndef p(k, v):\n    _C[k] = v\n")
        try:
            p = subprocess.run(
                [sys.executable,
                 os.path.join(_REPO, "tools", "mxlint.py"), tmp,
                 "--diff", "HEAD", "--json"],
                capture_output=True, text=True, timeout=60, cwd=_REPO)
            report = json.loads(p.stdout)
            assert p.returncode == 1
            assert report["new_per_rule"] == {"MX004": 1}
            # the point of --diff: a one-file lint is near-instant
            assert report["elapsed_seconds"] < 2.0
        finally:
            os.unlink(tmp)

    def test_diff_mode_clean_scope_is_instant_ok(self):
        import subprocess
        import sys

        p = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "mxlint.py"),
             os.path.join(_REPO, "docs"), "--diff", "HEAD"],
            capture_output=True, text=True, timeout=60, cwd=_REPO)
        assert p.returncode == 0, p.stdout + p.stderr
        assert "no .py files changed" in p.stdout \
            or "0 new violation(s)" in p.stdout

    def test_diff_mode_relative_scope_resolves_from_any_cwd(self,
                                                           tmp_path):
        # a repo-relative scope path must work when the CLI runs from
        # another directory (pre-commit hooks rarely cd first)
        import subprocess
        import sys

        tmp = os.path.join(_REPO, "tests", "_tmp_diff_cwd_fixture.py")
        with open(tmp, "w") as f:
            f.write("_C = {}\n\ndef p(k, v):\n    _C[k] = v\n")
        try:
            p = subprocess.run(
                [sys.executable,
                 os.path.join(_REPO, "tools", "mxlint.py"), "tests",
                 "--diff", "HEAD", "--json"],
                capture_output=True, text=True, timeout=60,
                cwd=str(tmp_path))
            report = json.loads(p.stdout)
            assert p.returncode == 1, p.stdout[-500:] + p.stderr[-500:]
            # other changed files under tests/ may add findings; the
            # point is that the repo-relative scope resolved at all
            assert any(v["path"].endswith("_tmp_diff_cwd_fixture.py")
                       for v in report["new"])
        finally:
            os.unlink(tmp)


class TestIncrementalCache:
    """ISSUE 19 satellite: findings cache keyed on (content sha256,
    rules-version).  The invariant everything rests on: a warm run is
    finding-identical to a cold run — including MX006's cross-file
    duplicate detection, which replays per-file contributions instead
    of per-file findings."""

    def _tree(self, tmp_path):
        (tmp_path / "a.py").write_text(
            "import jax\n\n"
            "from mxnet_tpu.ops.registry import register_op\n\n\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return int(x) + 1\n\n\n"
            "@register_op(\"dup_op\")\n"
            "def _dup1(a):\n"
            "    return a\n")
        (tmp_path / "b.py").write_text(
            "from mxnet_tpu.ops.registry import register_op\n\n\n"
            "@register_op(\"dup_op\")\n"
            "def _dup2(a):\n"
            "    \"\"\"Doc.\"\"\"\n"
            "    return a\n")
        return str(tmp_path), str(tmp_path / "cache.json")

    def test_cold_warm_parity(self, tmp_path):
        root, cache = self._tree(tmp_path)
        cold_eng = analysis.LintEngine(root=root)
        cold = cold_eng.run([root], cache_path=cache)
        assert cold_eng.cache_misses == 2 and cold_eng.cache_hits == 0
        # the synthetic tree must exercise a "file" rule, a per-file
        # MX006 finding, AND the cross-file MX006 dup
        assert {v.rule for v in cold} >= {"MX001", "MX006"}
        assert any("already registered" in v.message for v in cold)
        warm_eng = analysis.LintEngine(root=root)
        warm = warm_eng.run([root], cache_path=cache)
        assert warm_eng.cache_hits == 2 and warm_eng.cache_misses == 0
        assert warm == cold

    def test_edit_invalidates_only_that_file(self, tmp_path):
        root, cache = self._tree(tmp_path)
        analysis.LintEngine(root=root).run([root], cache_path=cache)
        (tmp_path / "b.py").write_text(
            "from mxnet_tpu.ops.registry import register_op\n\n\n"
            "@register_op(\"other_op\")\n"
            "def _dup2(a):\n"
            "    \"\"\"Doc.\"\"\"\n"
            "    return a\n")
        eng = analysis.LintEngine(root=root)
        vs = eng.run([root], cache_path=cache)
        assert eng.cache_hits == 1 and eng.cache_misses == 1
        assert not any("already registered" in v.message for v in vs)

    def test_rules_version_change_invalidates_everything(self, tmp_path):
        root, cache = self._tree(tmp_path)
        analysis.LintEngine(root=root).run([root], cache_path=cache)
        with open(cache) as f:
            doc = json.load(f)
        doc["rules_version"] = "0" * 64
        with open(cache, "w") as f:
            json.dump(doc, f)
        eng = analysis.LintEngine(root=root)
        eng.run([root], cache_path=cache)
        assert eng.cache_misses == 2 and eng.cache_hits == 0

    def test_corrupt_cache_is_a_cold_run_not_an_error(self, tmp_path):
        root, cache = self._tree(tmp_path)
        with open(cache, "w") as f:
            f.write("{not json")
        eng = analysis.LintEngine(root=root)
        vs = eng.run([root], cache_path=cache)
        assert eng.cache_misses == 2 and vs
        with open(cache) as f:
            assert json.load(f)["version"] == 1  # rewritten valid

    def test_no_cache_path_writes_nothing(self, tmp_path):
        root, cache = self._tree(tmp_path)
        analysis.LintEngine(root=root).run([root])
        assert not os.path.exists(cache)

    def test_narrower_enable_entry_does_not_serve_wider_run(self,
                                                            tmp_path):
        # an entry written by --enable=MX001 lacks the other cacheable
        # rules' findings; a full run must treat it as a miss, never
        # silently drop findings
        root, cache = self._tree(tmp_path)
        analysis.LintEngine(root=root, enable=["MX001"]).run(
            [root], cache_path=cache)
        eng = analysis.LintEngine(root=root)
        vs = eng.run([root], cache_path=cache)
        assert eng.cache_misses == 2
        assert any(v.rule == "MX006" for v in vs)

    def test_cli_cache_flags(self, tmp_path):
        import subprocess
        import sys

        root, _ = self._tree(tmp_path)
        cache = str(tmp_path / "cli_cache.json")
        cli = [sys.executable, os.path.join(_REPO, "tools", "mxlint.py"),
               root, "--json", "--cache-file", cache]
        runs = []
        for extra in ([], [], ["--no-cache"]):
            p = subprocess.run(cli + extra, capture_output=True,
                               text=True, timeout=60)
            runs.append(json.loads(p.stdout))
        cold, warm, off = runs
        assert cold["cache"] == {"enabled": True, "hits": 0, "misses": 2}
        assert warm["cache"] == {"enabled": True, "hits": 2, "misses": 0}
        assert off["cache"]["enabled"] is False
        assert cold["new"] == warm["new"] == off["new"]


class TestLintDocsSync:
    """tools/gen_lint_docs.py: the rule catalogue table in
    docs/static_analysis.md is generated from RULE_REGISTRY and must
    not drift (the registry-then-docs contract gen_metric_docs keeps
    for metrics and --env-docs keeps for knobs)."""

    def _mod(self):
        import importlib.util
        path = os.path.join(_REPO, "tools", "gen_lint_docs.py")
        spec = importlib.util.spec_from_file_location("gen_lint_docs",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_catalog_in_sync(self):
        mod = self._mod()
        ok, table = mod.apply_block(
            os.path.join(_REPO, "docs", "static_analysis.md"),
            write=False)
        assert ok, ("lint rule catalogue out of sync — run "
                    "`python tools/gen_lint_docs.py --write`")
        # every registered rule has a row
        for rid in analysis.RULE_REGISTRY:
            assert f"| {rid} |" in table

    def test_check_mode_detects_drift(self, tmp_path):
        mod = self._mod()
        doc = tmp_path / "doc.md"
        doc.write_text("x\n" + mod._BEGIN + "\nstale\n" + mod._END
                       + "\ny\n")
        ok, _ = mod.apply_block(str(doc), write=False)
        assert not ok
        ok, _ = mod.apply_block(str(doc), write=True)
        ok2, _ = mod.apply_block(str(doc), write=False)
        assert ok2

    def test_missing_markers_is_an_error(self, tmp_path):
        mod = self._mod()
        doc = tmp_path / "doc.md"
        doc.write_text("no markers here\n")
        with pytest.raises(ValueError):
            mod.apply_block(str(doc), write=False)
