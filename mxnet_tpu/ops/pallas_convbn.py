"""Cross-layer fused Conv+BN+ReLU unit: Pallas TPU kernel + XLA fallback.

The ResNet-50 train step is HBM-bound (PERF.md roofline: 85-95% of
achievable bandwidth at op granularity), so the remaining headroom is
activation *traffic*, not FLOPs.  The reference gets its version of this
from cuDNN fused conv epilogues + MKLDNN subgraph fusion (ref:
src/operator/subgraph/mkldnn/mkldnn_conv.cc fuses conv+BN+ReLU); the
TPU-native equivalent is this kernel.

The unit computes, for one conv layer k inside a conv->BN->ReLU chain:

    u  = act(x * in_scale + in_bias)        # layer k-1's BatchNorm+ReLU,
                                            # applied WHILE READING x (the
                                            # raw conv_{k-1} output) so the
                                            # normalized activation is never
                                            # materialized in HBM
    y  = conv(u, w)                         # this layer's conv (raw out)
    s1 = sum_c(y); s2 = sum_c((y-shift)^2)  # BN statistics of y, folded
                                            # into the conv epilogue so the
                                            # separate stats pass disappears

A chain of these units touches HBM twice per layer (read x, write y) vs
~5 passes/layer for the op-granular path (conv write, stats read,
normalize read+write, next-conv read).  `shift` is the running mean: the
variance uses the same shifted single-pass formula as ops/nn.py
`_batch_norm` (E[(y-c)^2] - (mean-c)^2, warm-stat exact, floor-bounded)
so fused and unfused training see identical statistics semantics.

Backward is hand-written XLA (not Pallas): dgrad/wgrad via
jax.linear_transpose of the forward conv (exactly the transpose convs
XLA autodiff would emit, with no forward recompute), the BN-stat
cotangents folded into dy (dy_tot = dy + g_s1 + 2(y-shift)g_s2), and the
input-affine/ReLU backward recomputed elementwise from x.  Residuals are
(inputs, y): y is the layer activation that the op-granular path would
have stored anyway, so fusion adds no activation memory.

The Pallas path needs layout NHWC (channels on the 128-lane axis) and a
TPU backend; everything else (CPU tests, NCHW, probe failure,
MXNET_USE_PALLAS=0) takes the XLA fallback with identical semantics.
"""
from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..analysis import sanitizer as _mxsan
from ..util import env
from .registry import register_op

__all__ = ["fused_conv_unit"]

# mxsan: the enable latch is read lock-free (double-checked idiom);
# writes must hold _PROBE_LOCK
_STATE = _mxsan.track({"enabled": None}, "ops.pallas_convbn._STATE",
                      reads="unlocked-ok")
#: guards _STATE plus the probe cache/budget below — serving threads and
#: the training loop race the first conv dispatch (mxlint MX004)
_PROBE_LOCK = threading.Lock()

# VMEM working-set budget for choosing the per-program batch tile
# (padded activation + fp32 accumulator + double-buffered x/y grid
# blocks), leaving headroom for the weight taps and Mosaic's own
# scratch inside the 16MB core VMEM.
_COLS_BUDGET_BYTES = 8 * 1024 * 1024


def _pallas_wanted() -> bool:
    """Pallas usable?  Decided once: not on CPU (unless interpret mode is
    forced for tests) and only if a probe kernel actually compiles.
    Double-checked under _PROBE_LOCK: the first conv can arrive from
    several serving threads at once, and an unguarded decide would race
    the probe compile."""
    if _STATE["enabled"] is None:
        with _PROBE_LOCK:
            if _STATE["enabled"] is None:
                _STATE["enabled"] = _decide_pallas()
    return _STATE["enabled"]


def _decide_pallas() -> bool:
    """The one-time probe behind _pallas_wanted (caller holds
    _PROBE_LOCK)."""
    if not env.get_bool("MXNET_USE_PALLAS"):
        return False
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    interp = env.get_bool("MXNET_PALLAS_INTERPRET")
    if backend == "cpu" and not interp:
        return False
    try:
        x = jnp.zeros((2, 8, 8, 128), jnp.bfloat16)
        w = jnp.zeros((128, 128, 3, 3), jnp.bfloat16)
        sc = jnp.ones((128,), jnp.float32)
        sh = jnp.zeros((128,), jnp.float32)
        jax.eval_shape(functools.partial(
            _pallas_unit, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
            act_in=True, want_stats=True), x, w, sc, sc, sh)
        if interp:
            return True
        jax.jit(functools.partial(
            _pallas_unit, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
            act_in=True, want_stats=True)).lower(x, w, sc, sc, sh) \
            .compile()
        return True
    except Exception:
        return False


def _batch_tile(n, h, w, ci, ho, wo, co, itemsize=2, pad=(1, 1)):
    """Largest power-of-two batch tile dividing n whose whole VMEM
    working set (bytes) fits the budget.  Tap-accumulation working set:
    padded activation block u, fp32 accumulator, one tap slice, plus
    double-buffered x and y grid blocks.  >=1 even when one image
    overflows (the 56x56 stage must still run).  `itemsize` is the
    activation dtype width (2 for bf16, 4 for fp32)."""
    hp, wp = h + 2 * pad[0], w + 2 * pad[1]
    per_image = (hp * wp * ci * itemsize             # u (padded)
                 + ho * wo * co * 4                  # fp32 accumulator
                 + ho * wo * ci * itemsize           # tap slice temp
                 + 2 * h * w * ci * itemsize         # x block, dbuf
                 + 2 * ho * wo * co * itemsize)      # y block, dbuf
    nb = 1
    while nb * 2 <= n and n % (nb * 2) == 0 \
            and (nb * 2) * per_image <= _COLS_BUDGET_BYTES:
        nb *= 2
    return nb


def _out_hw(h, w, kernel, stride, pad):
    ho = (h + 2 * pad[0] - kernel[0]) // stride[0] + 1
    wo = (w + 2 * pad[1] - kernel[1]) // stride[1] + 1
    return ho, wo


def _weight_taps(w):
    """(Co, Ci, kh, kw) checkpoint layout -> (kh, kw, Ci, Co) tap array.

    One (Ci, Co) MXU panel per kernel tap — the tap-accumulation kernel
    indexes w_ref[ky, kx] instead of building an im2col panel (Mosaic
    rejects the in-kernel concatenate an im2col needs; round-5 on-chip
    finding)."""
    return jnp.transpose(w, (2, 3, 1, 0))


# ---------------------------------------------------------------------------
# Pallas forward
# ---------------------------------------------------------------------------

def _pallas_unit(x, w, in_scale, in_bias, shift, *, kernel, stride, pad,
                 act_in, want_stats):
    """Tap-accumulation formulation (round-5, validated on-chip): one
    (Ci, Co) MXU matmul per kernel tap accumulated in fp32, with the
    input affine+ReLU applied in VMEM and padding applied AFTER the
    affine (padded positions must be exact zeros, not relu(bias)).
    Strided taps extract their polyphase plane via contiguous slice +
    reshape + unit-index — a strided slice lowers to a gather Mosaic
    does not support."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, h, wd, ci = x.shape
    co = w.shape[0]
    kh, kw = kernel
    sh_, sw_ = stride
    ho, wo = _out_hw(h, wd, kernel, stride, pad)
    nb = _batch_tile(n, h, wd, ci, ho, wo, co,
                     itemsize=x.dtype.itemsize, pad=pad)
    wtaps = _weight_taps(w)
    out_dtype = x.dtype

    def kern(x_ref, w_ref, sc_ref, bi_ref, sh_ref, y_ref, s1_ref, s2_ref):
        xb = x_ref[...]
        if act_in:
            u = xb.astype(jnp.float32) * sc_ref[...] + bi_ref[...]
            u = jnp.maximum(u, 0.0).astype(xb.dtype)
        else:
            u = xb
        # window pad + (stride-1) extra so every tap's CONTIGUOUS slice
        # of length s*ho / s*wo stays in bounds
        if pad != (0, 0) or sh_ > 1 or sw_ > 1:
            u = jnp.pad(u, ((0, 0), (pad[0], pad[0] + sh_ - 1),
                            (pad[1], pad[1] + sw_ - 1), (0, 0)))
        acc = jnp.zeros((nb * ho * wo, co), jnp.float32)
        for ky in range(kh):
            for kx in range(kw):
                if sh_ == 1 and sw_ == 1:
                    sl = u[:, ky:ky + ho, kx:kx + wo, :]
                else:
                    rows = u[:, ky:ky + sh_ * ho, :, :]
                    rows = rows.reshape(nb, ho, sh_, rows.shape[2],
                                        ci)[:, :, 0]
                    cols = rows[:, :, kx:kx + sw_ * wo, :]
                    sl = cols.reshape(nb, ho, wo, sw_, ci)[:, :, :, 0]
                acc = acc + jnp.dot(sl.reshape(nb * ho * wo, ci),
                                    w_ref[ky, kx],
                                    preferred_element_type=jnp.float32)
        yc = acc.astype(out_dtype)
        y_ref[...] = yc.reshape(nb, ho, wo, co)
        # the stat outputs must be written in EVERY mode — an output
        # block left untouched returns whatever was in VMEM (the XLA
        # fallback returns zeros for want_stats=False; match it)
        @pl.when(pl.program_id(0) == 0)
        def _():
            s1_ref[...] = jnp.zeros_like(s1_ref)
            s2_ref[...] = jnp.zeros_like(s2_ref)

        if want_stats:
            # stats of the STORED (cast) value, accumulated fp32 across
            # the sequential grid — semantics identical to the unfused
            # BatchNorm reading the bf16 activation back from HBM
            yf = yc.astype(jnp.float32)
            d = yf - sh_ref[...]
            s1_ref[...] += jnp.sum(yf, axis=0, keepdims=True)
            s2_ref[...] += jnp.sum(d * d, axis=0, keepdims=True)

    grid = (n // nb,)
    y, s1, s2 = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((nb, h, wd, ci), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kh, kw, ci, co), lambda i: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ci), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ci), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, co), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((nb, ho, wo, co), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, co), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, co), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, ho, wo, co), out_dtype),
            jax.ShapeDtypeStruct((1, co), jnp.float32),
            jax.ShapeDtypeStruct((1, co), jnp.float32),
        ],
        interpret=env.get_bool("MXNET_PALLAS_INTERPRET"),
    )(x, wtaps, in_scale.reshape(1, ci), in_bias.reshape(1, ci),
      shift.reshape(1, co))
    return y, s1.reshape(co), s2.reshape(co)


# ---------------------------------------------------------------------------
# Pallas backward (opt-in: MXNET_FUSED_CONVBN_BWD=1)
# ---------------------------------------------------------------------------

def _batch_tile_bwd(n, h, w, ci, ho, wo, co, kh, kw, itemsize=2,
                    pad=(1, 1)):
    """Batch tile for the backward kernel: the fp32 du accumulator and
    the padded activation dominate; the fp32 dw tap accumulator is a
    FIXED cost independent of nb and is subtracted from the budget
    up front (512-channel stages overflow VMEM here and take the XLA
    fallback via the compile probe)."""
    fixed = kh * kw * ci * co * 4          # dw accumulator (f32)
    budget = _COLS_BUDGET_BYTES - fixed
    hp, wp = h + 2 * pad[0], w + 2 * pad[1]
    per_image = (hp * wp * ci * (itemsize + 4)            # u_pad + du_pad
                 + 2 * h * w * ci * itemsize              # x block, dbuf
                 + 3 * ho * wo * co * itemsize            # y + gy + dy
                 + h * w * ci * itemsize)                 # gx out
    nb = 1
    while nb * 2 <= n and n % (nb * 2) == 0 \
            and (nb * 2) * per_image <= max(budget, 0):
        nb *= 2
    return nb


def _pallas_unit_bwd(x, w, in_scale, in_bias, shift, y, gy, gs1, gs2, *,
                     kernel, stride, pad, act_in, want_stats):
    """Single-pass fused backward: dy_tot (BN-stat cotangent fold) is
    computed once in VMEM, then each kernel tap contributes one wgrad
    matmul (Ci,Co) and one dgrad matmul (M,Ci) whose result is
    accumulated into the padded input-grad buffer by a static pad —
    dy and the recomputed activation are read from HBM exactly once,
    where the XLA path's separate dgrad/wgrad convs read them twice.
    Stride-1 only (the dgrad of a strided conv needs interior-dilated
    pads, unproven under Mosaic; strided shapes take the XLA path)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, h, wd, ci = x.shape
    co = w.shape[0]
    kh, kw = kernel
    ho, wo = _out_hw(h, wd, kernel, stride, pad)
    hp, wp = h + 2 * pad[0], wd + 2 * pad[1]
    nb = _batch_tile_bwd(n, h, wd, ci, ho, wo, co, kh, kw,
                         itemsize=x.dtype.itemsize, pad=pad)
    wtaps = _weight_taps(w)
    gy_dtype = gy.dtype

    def kern(x_ref, w_ref, sc_ref, bi_ref, sh_ref, y_ref, gy_ref,
             gs1_ref, gs2_ref, gx_ref, dw_ref, gsc_ref, gbi_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            dw_ref[...] = jnp.zeros_like(dw_ref)
            gsc_ref[...] = jnp.zeros_like(gsc_ref)
            gbi_ref[...] = jnp.zeros_like(gbi_ref)

        gyb = gy_ref[...].astype(jnp.float32)
        if want_stats:
            yf = y_ref[...].astype(jnp.float32)
            dy = (gyb + gs1_ref[...].reshape(1, 1, 1, co)
                  + 2.0 * (yf - sh_ref[...].reshape(1, 1, 1, co))
                  * gs2_ref[...].reshape(1, 1, 1, co))
        else:
            dy = gyb
        # match the XLA path's rounding: dy_tot is cast to gy.dtype
        # before entering the transpose convs
        dyf = dy.astype(gy_dtype).reshape(nb * ho * wo, co)

        xb = x_ref[...]
        if act_in:
            uf32 = (xb.astype(jnp.float32) * sc_ref[...] + bi_ref[...])
            u = jnp.maximum(uf32, 0.0).astype(xb.dtype)
        else:
            u = xb
        if pad != (0, 0):
            u = jnp.pad(u, ((0, 0), (pad[0], pad[0]),
                            (pad[1], pad[1]), (0, 0)))

        du_pad = jnp.zeros((nb, hp, wp, ci), jnp.float32)
        for ky in range(kh):
            for kx in range(kw):
                sl = u[:, ky:ky + ho, kx:kx + wo, :] \
                    .reshape(nb * ho * wo, ci)
                # wgrad tap: (Ci, Co), contract the patch dim
                dw_ref[ky, kx] += jax.lax.dot_general(
                    sl, dyf, dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                # dgrad tap: (M, Ci), contract Co
                contrib = jax.lax.dot_general(
                    dyf, w_ref[ky, kx],
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                du_pad = du_pad + jnp.pad(
                    contrib.reshape(nb, ho, wo, ci),
                    ((0, 0), (ky, hp - ho - ky), (kx, wp - wo - kx),
                     (0, 0)))
        du = du_pad[:, pad[0]:pad[0] + h, pad[1]:pad[1] + wd, :]
        if act_in:
            gu = jnp.where(uf32 > 0.0, du, 0.0)
            gx_ref[...] = (gu * sc_ref[...]).astype(gx_ref.dtype)
            gsc_ref[...] += jnp.sum(
                gu * xb.astype(jnp.float32), axis=(0, 1, 2)) \
                .reshape(1, ci)
            gbi_ref[...] += jnp.sum(gu, axis=(0, 1, 2)).reshape(1, ci)
        else:
            gx_ref[...] = du.astype(gx_ref.dtype)

    grid = (n // nb,)
    cspec = lambda r, c: pl.BlockSpec((r, c), lambda i: (0, 0),
                                      memory_space=pltpu.VMEM)
    gx, dw_taps, gsc, gbi = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((nb, h, wd, ci), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kh, kw, ci, co), lambda i: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            cspec(1, ci), cspec(1, ci), cspec(1, co),
            pl.BlockSpec((nb, ho, wo, co), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nb, ho, wo, co), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            cspec(1, co), cspec(1, co),
        ],
        out_specs=[
            pl.BlockSpec((nb, h, wd, ci), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kh, kw, ci, co), lambda i: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            cspec(1, ci), cspec(1, ci),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, wd, ci), x.dtype),
            jax.ShapeDtypeStruct((kh, kw, ci, co), jnp.float32),
            jax.ShapeDtypeStruct((1, ci), jnp.float32),
            jax.ShapeDtypeStruct((1, ci), jnp.float32),
        ],
        interpret=env.get_bool("MXNET_PALLAS_INTERPRET"),
    )(x, wtaps, in_scale.reshape(1, ci), in_bias.reshape(1, ci),
      shift.reshape(1, co), y, gy,
      gs1.reshape(1, co), gs2.reshape(1, co))
    dw = jnp.transpose(dw_taps, (3, 2, 0, 1)).astype(w.dtype)
    if act_in:
        return gx, dw, gsc.reshape(ci), gbi.reshape(ci)
    return gx, dw, jnp.zeros_like(in_scale), jnp.zeros_like(in_bias)


def _pallas_unit_bwd_sharded(x, w, in_scale, in_bias, shift, y, gy, gs1,
                             gs2, *, mesh, axes, kernel, stride, pad,
                             act_in, want_stats):
    """Per-shard backward kernel over the batch axes; the batch-summed
    cotangents (dw, gscale, gbias) are psum'd global, mirroring how
    GSPMD reduces them for the XLA backward.  gx stays batch-sharded
    like x."""
    from jax.sharding import PartitionSpec as P

    from ..parallel._compat import shard_map_unchecked

    def per_shard(xs, ws, scs, bis, shs, ys, gys, g1s, g2s):
        gx, dw, gsc, gbi = _pallas_unit_bwd(
            xs, ws, scs, bis, shs, ys, gys, g1s, g2s, kernel=kernel,
            stride=stride, pad=pad, act_in=act_in, want_stats=want_stats)
        if axes:
            dw = lax.psum(dw, axes)
            gsc = lax.psum(gsc, axes)
            gbi = lax.psum(gbi, axes)
        return gx, dw, gsc, gbi

    bspec = P(axes if axes else None)
    rep = P()
    fn = shard_map_unchecked(
        per_shard, mesh=mesh.mesh,
        in_specs=(bspec, rep, rep, rep, rep, bspec, bspec, rep, rep),
        out_specs=(bspec, rep, rep, rep))
    return fn(x, w, in_scale, in_bias, shift, y, gy, gs1, gs2)


def _bwd_wanted() -> bool:
    return env.get_bool("MXNET_FUSED_CONVBN_BWD") \
        and _pallas_wanted()


def _bwd_shape_supported(x, w, kernel, stride, pad, act_in,
                         want_stats) -> bool:
    n, h, wd, ci = x.shape
    co = w.shape[0]
    ho, wo = _out_hw(h, wd, kernel, stride, pad)
    key = ("bwd", x.shape, str(x.dtype), w.shape, kernel, stride, pad,
           act_in, want_stats)
    return _probe_ok(
        key,
        functools.partial(_pallas_unit_bwd, kernel=kernel, stride=stride,
                          pad=pad, act_in=act_in, want_stats=want_stats),
        [jax.ShapeDtypeStruct(x.shape, x.dtype),
         jax.ShapeDtypeStruct(w.shape, w.dtype),
         jax.ShapeDtypeStruct((ci,), jnp.float32),
         jax.ShapeDtypeStruct((ci,), jnp.float32),
         jax.ShapeDtypeStruct((co,), jnp.float32),
         jax.ShapeDtypeStruct((n, ho, wo, co), x.dtype),
         jax.ShapeDtypeStruct((n, ho, wo, co), x.dtype),
         jax.ShapeDtypeStruct((co,), jnp.float32),
         jax.ShapeDtypeStruct((co,), jnp.float32)])


# ---------------------------------------------------------------------------
# XLA fallback (identical semantics) + shared backward
# ---------------------------------------------------------------------------

def _apply_in_affine(x, in_scale, in_bias, act_in):
    if not act_in:
        return x
    u = (x.astype(jnp.float32) * in_scale.reshape(1, 1, 1, -1)
         + in_bias.reshape(1, 1, 1, -1))
    return jnp.maximum(u, 0.0).astype(x.dtype)


def _conv_nhwc(u, w_hwio, stride, pad):
    return lax.conv_general_dilated(
        u, w_hwio, window_strides=stride,
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _xla_unit(x, w, in_scale, in_bias, shift, *, kernel, stride, pad,
              act_in, want_stats):
    u = _apply_in_affine(x, in_scale, in_bias, act_in)
    y = _conv_nhwc(u, jnp.transpose(w, (2, 3, 1, 0)), stride, pad)
    if want_stats:
        yf = y.astype(jnp.float32)
        s1 = jnp.sum(yf, axis=(0, 1, 2))
        d = yf - shift.reshape(1, 1, 1, -1)
        s2 = jnp.sum(d * d, axis=(0, 1, 2))
    else:
        co = y.shape[-1]
        s1 = jnp.zeros((co,), jnp.float32)
        s2 = jnp.zeros((co,), jnp.float32)
    return y, s1, s2


# Trace-time success does NOT imply the kernel will survive Mosaic
# lowering (that happens later, when the enclosing jitted program
# compiles, far outside any try/except here).  So each distinct
# (shapes, statics) configuration is probe-COMPILED standalone once —
# with fresh ShapeDtypeStructs, never tracers, so it is safe to do in
# the middle of an outer trace — and configurations Mosaic rejects are
# pinned to the XLA fallback.
_SHAPE_OK: dict = _mxsan.track({}, "ops.pallas_convbn._SHAPE_OK",
                               reads="unlocked-ok")
# cumulative probe-compile seconds; every access holds _PROBE_LOCK
_PROBE_SPENT = _mxsan.track([0.0], "ops.pallas_convbn._PROBE_SPENT")


def _probe_budget() -> float:
    """Default probe-compile budget, scaled for the backward knob:
    MXNET_FUSED_CONVBN_BWD=1 roughly doubles the number of distinct
    configurations to probe (~20 fwd + ~20 bwd at 3-17s each on-chip),
    so the default must grow with it — at the library layer, not per
    launcher."""
    dflt = 600.0 if env.get_bool("MXNET_FUSED_CONVBN_BWD") \
        else 300.0
    return env.get_float("MXNET_PALLAS_PROBE_BUDGET", default=dflt)


def _probe_ok(key, fn, arg_structs) -> bool:
    """Shared probe/budget/cache mechanism for fwd and bwd kernels.

    Budget-exhausted is deliberately NOT cached: 'never probed' must
    stay distinguishable from 'Mosaic rejected' so a later call with
    budget headroom can still probe this configuration."""
    # the interpret flag is part of the key: interpreter-mode ok=True
    # says nothing about Mosaic, so a later non-interpret call in the
    # same process must re-probe instead of reusing it (ADVICE round 5)
    interpret = env.get_bool("MXNET_PALLAS_INTERPRET")
    key = (key, interpret)
    ok = _SHAPE_OK.get(key)
    if ok is not None:
        return ok
    with _PROBE_LOCK:
        ok = _SHAPE_OK.get(key)
        if ok is None:
            import time as _time

            if interpret:
                ok = True  # interpreter mode has no Mosaic stage
            elif _PROBE_SPENT[0] >= _probe_budget():
                return False
            else:
                _t0 = _time.perf_counter()
                try:
                    jax.jit(fn).lower(*arg_structs).compile()
                    ok = True
                except Exception:
                    ok = False
                finally:
                    _PROBE_SPENT[0] += _time.perf_counter() - _t0
            _SHAPE_OK[key] = ok
    return ok


def _shape_supported(x, w, kernel, stride, pad, act_in, want_stats) -> bool:
    key = (x.shape, str(x.dtype), w.shape, kernel, stride, pad, act_in,
           want_stats)
    return _probe_ok(
        key,
        functools.partial(_pallas_unit, kernel=kernel, stride=stride,
                          pad=pad, act_in=act_in, want_stats=want_stats),
        [jax.ShapeDtypeStruct(x.shape, x.dtype),
         jax.ShapeDtypeStruct(w.shape, w.dtype),
         jax.ShapeDtypeStruct((x.shape[-1],), jnp.float32),
         jax.ShapeDtypeStruct((x.shape[-1],), jnp.float32),
         jax.ShapeDtypeStruct((w.shape[0],), jnp.float32)])


def _dispatch_plan(x, shape_probe):
    """ONE dispatch rule for fwd and bwd: returns
    ('single', None, None)   — no multi-device mesh active,
    ('sharded', mesh, axes)  — mesh active, batch divides the shards,
                               and the PER-SHARD shape probe-compiles,
    ('xla', None, None)      — mesh active but unsupported.
    Keeping this in one place means forward and backward can never
    silently disagree about when the Pallas path engages."""
    plan = _mesh_shard_plan()
    if plan is None:
        return ("single", None, None)
    mesh, axes = plan
    nshard = 1
    for a in axes:
        nshard *= mesh.axis_sizes[a]
    shard_shape = (x.shape[0] // nshard,) + tuple(x.shape[1:])
    if x.shape[0] % nshard == 0 and shard_shape[0] > 0 \
            and shape_probe(jax.ShapeDtypeStruct(shard_shape, x.dtype)):
        return ("sharded", mesh, axes)
    return ("xla", None, None)


def _mesh_shard_plan():
    """(mesh, batch_axes) for the active multi-device mesh, else None.

    GSPMD cannot partition a `pallas_call` on its own, so under a
    multi-device mesh the kernel is wrapped in an explicit shard_map
    over the batch-splitting axes (dp/fsdp) with the BN statistics
    psum'd across shards — keeping the fused path alive on exactly the
    configuration the north-star scaling metric measures (round-4
    verdict item #2).  Axes that don't split the batch (tp/pp/sp/ep)
    see the unit's operands replicated, which matches how the ResNet
    SPMD path lays them out."""
    try:
        from ..parallel.mesh import current_mesh

        m = current_mesh()
    except Exception:
        return None
    if m is None or m.mesh.size == 1:
        return None
    axes = tuple(a for a in ("dp", "fsdp")
                 if m.axis_sizes.get(a, 1) > 1)
    return m, axes


def _pallas_unit_sharded(x, w, in_scale, in_bias, shift, *, mesh, axes,
                         kernel, stride, pad, act_in, want_stats):
    """Per-shard pallas_call over the batch axes; stats psum'd global.

    Each device runs the single-chip kernel on its batch shard; s1/s2
    are per-shard partial sums, made global (and replicated) with a
    psum over the batch axes — semantically identical to the XLA
    fallback's jnp.sum over the GSPMD-sharded activation."""
    from jax.sharding import PartitionSpec as P

    from ..parallel._compat import shard_map_unchecked

    def per_shard(xs, ws, scs, bis, shs):
        y, s1, s2 = _pallas_unit(xs, ws, scs, bis, shs, kernel=kernel,
                                 stride=stride, pad=pad, act_in=act_in,
                                 want_stats=want_stats)
        if want_stats and axes:
            s1 = lax.psum(s1, axes)
            s2 = lax.psum(s2, axes)
        return y, s1, s2

    xspec = P(axes if axes else None)
    rep = P()
    fn = shard_map_unchecked(
        per_shard, mesh=mesh.mesh,
        in_specs=(xspec, rep, rep, rep, rep),
        out_specs=(xspec, rep, rep))
    return fn(x, w, in_scale, in_bias, shift)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _unit(x, w, in_scale, in_bias, shift, kernel, stride, pad, act_in,
          want_stats):
    if _pallas_wanted():
        probe = lambda xs: _shape_supported(xs, w, kernel, stride, pad,
                                            act_in, want_stats)
        kind, mesh, axes = _dispatch_plan(x, probe)
        if kind == "single" and probe(x):
            try:
                return _pallas_unit(x, w, in_scale, in_bias, shift,
                                    kernel=kernel, stride=stride,
                                    pad=pad, act_in=act_in,
                                    want_stats=want_stats)
            except Exception:
                pass
        elif kind == "sharded":
            try:
                return _pallas_unit_sharded(
                    x, w, in_scale, in_bias, shift, mesh=mesh,
                    axes=axes, kernel=kernel, stride=stride, pad=pad,
                    act_in=act_in, want_stats=want_stats)
            except Exception:
                pass
    return _xla_unit(x, w, in_scale, in_bias, shift, kernel=kernel,
                     stride=stride, pad=pad, act_in=act_in,
                     want_stats=want_stats)


def _unit_fwd(x, w, in_scale, in_bias, shift, kernel, stride, pad, act_in,
              want_stats):
    out = _unit(x, w, in_scale, in_bias, shift, kernel, stride, pad,
                act_in, want_stats)
    # y rides along as a residual: it is the stored activation either way
    return out, (x, w, in_scale, in_bias, shift, out[0])


def _unit_bwd(kernel, stride, pad, act_in, want_stats, res, cots):
    x, w, in_scale, in_bias, shift, y = res
    gy, gs1, gs2 = cots
    if _bwd_wanted() and stride == (1, 1):
        probe = lambda xs: _bwd_shape_supported(xs, w, kernel, stride,
                                                pad, act_in, want_stats)
        kind, mesh, axes = _dispatch_plan(x, probe)
        if kind == "single" and probe(x):
            try:
                gx, dw, gscale, gbias = _pallas_unit_bwd(
                    x, w, in_scale, in_bias, shift, y, gy, gs1, gs2,
                    kernel=kernel, stride=stride, pad=pad,
                    act_in=act_in, want_stats=want_stats)
                return gx, dw, gscale, gbias, jnp.zeros_like(shift)
            except Exception:
                pass
        elif kind == "sharded":
            try:
                gx, dw, gscale, gbias = _pallas_unit_bwd_sharded(
                    x, w, in_scale, in_bias, shift, y, gy, gs1, gs2,
                    mesh=mesh, axes=axes, kernel=kernel,
                    stride=stride, pad=pad, act_in=act_in,
                    want_stats=want_stats)
                return gx, dw, gscale, gbias, jnp.zeros_like(shift)
            except Exception:
                pass
    if want_stats:
        # fold the BN-stat cotangents into dy: d(s1)/dy = 1,
        # d(s2)/dy = 2(y - shift); all C-sized broadcasts, XLA fuses
        # this into the transpose-conv input reads
        gy_tot = (gy.astype(jnp.float32)
                  + gs1.reshape(1, 1, 1, -1)
                  + 2.0 * (y.astype(jnp.float32)
                           - shift.reshape(1, 1, 1, -1))
                  * gs2.reshape(1, 1, 1, -1)).astype(gy.dtype)
    else:
        gy_tot = gy
    u = _apply_in_affine(x, in_scale, in_bias, act_in)
    w_hwio = jnp.transpose(w, (2, 3, 1, 0))
    # dgrad / wgrad as the EXACT transpose of the forward conv — no
    # forward recompute (linear_transpose only traces abstractly)
    du = jax.linear_transpose(
        lambda l: _conv_nhwc(l, w_hwio, stride, pad), u)(gy_tot)[0]
    dw_hwio = jax.linear_transpose(
        lambda r: _conv_nhwc(u, r, stride, pad), w_hwio)(gy_tot)[0]
    dw = jnp.transpose(dw_hwio, (3, 2, 0, 1)).astype(w.dtype)
    if act_in:
        uf = (x.astype(jnp.float32) * in_scale.reshape(1, 1, 1, -1)
              + in_bias.reshape(1, 1, 1, -1))
        mask = uf > 0.0
        gu = jnp.where(mask, du.astype(jnp.float32), 0.0)
        gx = (gu * in_scale.reshape(1, 1, 1, -1)).astype(x.dtype)
        gscale = jnp.sum(gu * x.astype(jnp.float32), axis=(0, 1, 2))
        gbias = jnp.sum(gu, axis=(0, 1, 2))
    else:
        gx = du.astype(x.dtype)
        gscale = jnp.zeros_like(in_scale)
        gbias = jnp.zeros_like(in_bias)
    # shift is a running statistic (stop-gradient, like _batch_norm's c)
    return gx, dw, gscale, gbias, jnp.zeros_like(shift)


_unit.defvjp(_unit_fwd, _unit_bwd)


@register_op("FusedConvUnit")
def fused_conv_unit(data, weight, in_scale=None, in_bias=None, shift=None,
                    kernel=(1, 1), stride=(1, 1), pad=(0, 0), act_in=False,
                    want_stats=True):
    """Fused (input-affine+ReLU) -> conv -> (BN stats) unit, NHWC.

    data (N,H,W,Ci) raw previous-layer conv output; weight (Co,Ci,kh,kw)
    in the layout-independent checkpoint layout; in_scale/in_bias the
    fp32 per-channel affine that normalizes `data` (None = identity);
    shift the fp32 variance shift for this layer's stats (the running
    mean; None = zeros).  Returns (y_raw, s1, s2) with s1/s2 fp32
    per-channel sum / shifted sum-of-squares of y_raw.
    """
    kernel = tuple(int(k) for k in kernel)
    stride = tuple(int(s) for s in stride)
    pad = tuple(int(p) for p in pad)
    ci = data.shape[-1]
    co = weight.shape[0]
    if in_scale is None:
        in_scale = jnp.ones((ci,), jnp.float32)
    if in_bias is None:
        in_bias = jnp.zeros((ci,), jnp.float32)
    if shift is None:
        shift = jnp.zeros((co,), jnp.float32)
    return _unit(data, weight, in_scale.astype(jnp.float32),
                 in_bias.astype(jnp.float32), shift.astype(jnp.float32),
                 kernel, stride, pad, bool(act_in), bool(want_stats))
