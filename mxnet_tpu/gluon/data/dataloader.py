"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

The reference uses fork()ed worker processes with NDArrays in POSIX shm
(CPUSharedStorage) to parallelise decode/augment.  Forking a process that
holds a PjRt/TPU client is unsafe, so this loader parallelises with a
thread pool + double-buffered prefetch: batchify runs in numpy (releases
the GIL for decode/augment-heavy datasets), and only the assembled batch
is handed to the device.  The C++ RecordIO pipeline (src/io, see native/)
is the high-throughput path for ImageNet-style training.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

import numpy as np

from ...base import MXNetError
from ...ndarray.ndarray import NDArray, array as nd_array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py::default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp

        return NDArray(jnp.stack([d.data for d in data]))
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(d)) for d in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    if arr.dtype == np.int64:
        arr = arr.astype(np.int32)
    return nd_array(arr)


default_mp_batchify_fn = default_batchify_fn


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False, timeout=120):
        self._dataset = dataset
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size is required when batch_sampler "
                                 "is not given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError("batch_size/shuffle/sampler/last_batch must not "
                             "be set with explicit batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        yield from self._threaded_iter()

    def _threaded_iter(self):
        """Prefetching iterator: worker threads assemble batches ahead
        (counterpart of the reference's PrefetcherIter double-buffering)."""
        batches = list(self._batch_sampler)
        out_q: "queue.Queue" = queue.Queue(maxsize=max(self._prefetch, 2))
        stop = threading.Event()

        def producer():
            try:
                for indices in batches:
                    if stop.is_set():
                        return
                    out_q.put(("ok", self._make_batch(indices)))
                out_q.put(("done", None))
            except BaseException as e:  # propagate to consumer
                out_q.put(("err", e))

        threads = [threading.Thread(target=producer, daemon=True)]
        # single producer keeps order; extra workers would need reordering —
        # the native pipeline (src/io) owns the truly parallel path
        for t in threads:
            t.start()
        try:
            while True:
                kind, payload = out_q.get(timeout=self._timeout)
                if kind == "done":
                    return
                if kind == "err":
                    raise payload
                yield payload
        finally:
            stop.set()

    def __len__(self):
        return len(self._batch_sampler)
