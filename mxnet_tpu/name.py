"""Name management for symbol auto-naming (ref: python/mxnet/name.py).

`NameManager` assigns `opname%d` names; `Prefix` prepends a fixed prefix.
The symbolic frontend consults the active manager when a node has no
explicit name.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["NameManager", "Prefix", "current"]


class NameManager:
    _state = threading.local()

    def __init__(self):
        self._counter: Dict[str, int] = {}
        self._old: Optional["NameManager"] = None

    def get(self, name: Optional[str], hint: str) -> str:
        if name:
            return name
        n = self._counter.get(hint, 0)
        self._counter[hint] = n + 1
        return f"{hint}{n}"

    def __enter__(self):
        self._old = current()
        NameManager._state.mgr = self
        return self

    def __exit__(self, *exc):
        NameManager._state.mgr = self._old
        return False


class Prefix(NameManager):
    """ref: name.Prefix — prepend `prefix` to every auto name."""

    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


def current() -> NameManager:
    mgr = getattr(NameManager._state, "mgr", None)
    if mgr is None:
        mgr = NameManager()
        NameManager._state.mgr = mgr
    return mgr
