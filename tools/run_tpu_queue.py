"""Run the whole on-chip measurement queue in one command.

The TPU tunnel in this container dies for hours at a time (see
CHANGES_r04.md / TUNNEL_LOG_r04.md), so when a window opens, everything
must land in one shot — run this the moment a probe succeeds:

    python tools/run_tpu_queue.py [--round 5]

Or, the self-firing mode (round-4 verdict item #1) — start it at round
open and leave it running; it probes on the TUNNEL_LOG_r04 protocol
(bounded fresh-process `jax.devices()`, one prober at a time, ~8.5 min
spacing) and fires the full queue automatically on the FIRST successful
probe, then exits:

    python tools/run_tpu_queue.py --watch [--round 5]

Sequential bounded steps (the tunnel is single-client — nothing may run
concurrently with this):
  1. tools/run_tpu_tests.py      -> TPU_TESTS_r0N.json (TPU-lane cases)
  2. bench.py                    -> BENCH snapshot (unfused + fused in one run)
  3. bench_all.py                -> BENCH_ALL.json (5 configs + variants)
  4. tools/opperf.py --large     -> OPPERF_TPU.json
Each step's outcome is recorded in TPU_QUEUE_RESULTS.json; a failed or
timed-out step does not stop the rest.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


_PROBE_SRC = (
    "import jax, time, json; t0 = time.time(); d = jax.devices(); "
    "print(json.dumps({'ok': True, 'devices': [str(x) for x in d], "
    "'init_s': round(time.time() - t0, 1)}))"
)


def probe(timeout=240):
    """One bounded fresh-process tunnel probe (TUNNEL_LOG_r04 protocol).

    Returns (ok: bool, detail: str). A fresh process is mandatory: a hung
    backend init poisons the whole interpreter, and the axon plugin is
    force-registered by sitecustomize, so in-process retry is impossible.
    """
    t0 = time.time()
    try:
        p = subprocess.run([sys.executable, "-c", _PROBE_SRC], cwd=_REPO,
                           capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return False, "backend init exceeded %ds (hung tunnel)" % timeout
    if p.returncode != 0:
        tail = "\n".join((p.stdout + p.stderr).splitlines()[-3:])
        return False, "probe rc=%d: %s" % (p.returncode, tail)
    for line in p.stdout.splitlines():
        if line.startswith("{"):
            return True, line.strip()
    return False, "no probe output (%.0fs)" % (time.time() - t0)


def watch(args):
    """Probe until the tunnel answers, then fire the queue once and exit.

    Round-4 verdict item #1: two full rounds were lost because the queue
    required a human to notice the tunnel was up. This loop is that human.
    Spacing ~8.5 min between failed probes, single prober at a time.
    """
    log_path = os.path.join(_REPO, "TUNNEL_LOG_r%02d.md" % args.round)
    attempt = 0
    while True:
        attempt += 1
        ok, detail = probe()
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        with open(log_path, "a") as f:
            f.write("- %s watch probe %d: %s %s\n"
                    % (stamp, attempt, "OK" if ok else "FAIL", detail))
        print("[watch] probe %d: %s %s" % (attempt, ok, detail), flush=True)
        if ok:
            return run_queue(args)
        if args.max_probes and attempt >= args.max_probes:
            print("[watch] giving up after %d probes" % attempt)
            return 1
        time.sleep(args.spacing)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=5)
    ap.add_argument("--out",
                    default=os.path.join(_REPO, "TPU_QUEUE_RESULTS.json"))
    ap.add_argument("--watch", action="store_true",
                    help="probe until the tunnel answers, then fire the "
                         "queue once and exit")
    ap.add_argument("--spacing", type=float, default=510.0,
                    help="seconds between failed watch probes (~8.5 min)")
    ap.add_argument("--max-probes", type=int, default=0,
                    help="watch gives up after this many probes (0 = never)")
    args = ap.parse_args()
    if args.watch:
        return watch(args)
    return run_queue(args)


def run_queue(args):
    n = args.round
    steps = [
        ("tpu_tests",
         [sys.executable, "tools/run_tpu_tests.py",
          "--out", f"TPU_TESTS_r{n:02d}.json"], 1800),
        ("bench",
         [sys.executable, "bench.py"], 2400),
        ("bench_all",
         [sys.executable, "bench_all.py"], 7200),
        ("opperf_tpu",
         [sys.executable, "tools/opperf.py", "--large",
          "--out", "OPPERF_TPU.json"], 2400),
    ]

    results = []
    for name, cmd, timeout in steps:
        t0 = time.time()
        try:
            p = subprocess.run(cmd, cwd=_REPO, capture_output=True,
                               text=True, timeout=timeout)
            tail = "\n".join((p.stdout + p.stderr).splitlines()[-5:])
            rec = {"step": name, "rc": p.returncode,
                   "seconds": round(time.time() - t0, 1), "tail": tail}
        except subprocess.TimeoutExpired:
            rec = {"step": name, "rc": -1, "timeout_s": timeout,
                   "seconds": round(time.time() - t0, 1)}
        results.append(rec)
        print(json.dumps(rec), flush=True)
        with open(args.out, "w") as f:
            json.dump({"when": time.strftime("%Y-%m-%d %H:%M:%S"),
                       "round": n, "results": results}, f, indent=1)
    return 0 if all(r.get("rc") == 0 for r in results) else 1


if __name__ == "__main__":
    sys.exit(main())
