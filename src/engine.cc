// Dependency engine implementation (see engine.h for the design notes and
// reference citations: src/engine/threaded_engine.cc semantics).
#include "engine.h"

namespace mxt {

std::string& LastError() {
  static thread_local std::string err;
  return err;
}

Engine::Engine(int num_workers) {
  for (int i = 0; i < num_workers; ++i) {
    workers_.emplace_back([this] { WorkerLoop(); });
  }
}

Engine::~Engine() {
  WaitForAll();
  {
    std::lock_guard<std::mutex> lk(ready_m_);
    shutdown_ = true;
  }
  ready_cv_.notify_all();
  for (auto& t : workers_) t.join();
  std::lock_guard<std::mutex> lk(vars_m_);
  for (auto& kv : vars_) delete kv.second;
}

int64_t Engine::NewVariable() {
  int64_t h = next_var_.fetch_add(1);
  std::lock_guard<std::mutex> lk(vars_m_);
  vars_[h] = new Var();
  return h;
}

Var* Engine::GetVar(int64_t handle) {
  std::lock_guard<std::mutex> lk(vars_m_);
  auto it = vars_.find(handle);
  MXT_CHECK_MSG(it != vars_.end(), "unknown engine variable handle");
  return it->second;
}

static void NoopFn(void*) {}

void Engine::DeleteVariable(int64_t handle) {
  // erase from the map now (new pushes on the handle become errors), then
  // schedule a final write op that frees the Var once all pending ops on
  // it have drained (ref: FnProperty::kDeleteVar push)
  Var* v;
  {
    std::lock_guard<std::mutex> lk(vars_m_);
    auto it = vars_.find(handle);
    MXT_CHECK_MSG(it != vars_.end(), "unknown engine variable handle");
    v = it->second;
    vars_.erase(it);
  }
  PushAsyncVars(NoopFn, nullptr, {}, {v}, 0, /*delete_writes=*/true);
}

// caller holds v->m; grants head reads (concurrent) or one head write
void Engine::GrantLocked(Var* v) {
  while (!v->queue.empty()) {
    Var::Entry& head = v->queue.front();
    if (head.is_write) {
      if (v->running_reads == 0 && !v->running_write) {
        Opr* o = head.opr;
        v->queue.pop_front();
        v->running_write = true;
        DecWait(o);
        continue;  // next iteration sees running_write and stops
      }
      break;
    } else {
      if (!v->running_write) {
        Opr* o = head.opr;
        v->queue.pop_front();
        ++v->running_reads;
        DecWait(o);
        continue;
      }
      break;
    }
  }
}

// NOTE: may be called while holding a Var lock — never executes inline;
// ready work queues up and is drained by workers (threaded) or by the
// pushing thread after locks are released (naive)
void Engine::DecWait(Opr* opr) {
  if (opr->wait.fetch_sub(1) == 1) {
    {
      std::lock_guard<std::mutex> lk(ready_m_);
      (opr->priority > 0 ? ready_hi_ : ready_lo_).push_back(opr);
    }
    ready_cv_.notify_one();
  }
}

void Engine::PushAsyncVars(EngineFn fn, void* arg, std::vector<Var*> reads,
                           std::vector<Var*> writes, int priority,
                           bool delete_writes) {
  // the reference contract (threaded_engine.cc CheckDuplicate): an op's
  // read and write sets must be disjoint and duplicate-free — a var in
  // both would deadlock the op against itself, silently
  for (size_t i = 0; i < writes.size(); ++i) {
    for (size_t j = i + 1; j < writes.size(); ++j)
      MXT_CHECK_MSG(writes[i] != writes[j],
                    "engine: duplicate variable in write set");
    for (Var* r : reads)
      MXT_CHECK_MSG(writes[i] != r,
                    "engine: variable appears in BOTH read and write "
                    "sets of one op (would deadlock)");
  }
  for (size_t i = 0; i < reads.size(); ++i)
    for (size_t j = i + 1; j < reads.size(); ++j)
      MXT_CHECK_MSG(reads[i] != reads[j],
                    "engine: duplicate variable in read set");
  Opr* opr = new Opr();
  opr->fn = fn;
  opr->arg = arg;
  opr->priority = priority;
  opr->delete_writes = delete_writes;
  opr->reads = std::move(reads);
  opr->writes = std::move(writes);
  {
    std::lock_guard<std::mutex> lk(pending_m_);
    ++pending_;
  }
  // +1 guard keeps the op from firing while dependencies are appended
  opr->wait.store(static_cast<int>(opr->reads.size() + opr->writes.size()) +
                  1);
  for (Var* v : opr->reads) {
    std::lock_guard<std::mutex> lk(v->m);
    v->queue.push_back({opr, false});
    GrantLocked(v);
  }
  for (Var* v : opr->writes) {
    std::lock_guard<std::mutex> lk(v->m);
    v->queue.push_back({opr, true});
    GrantLocked(v);
  }
  DecWait(opr);  // release the guard
  if (is_naive()) DrainReady();
}

void Engine::PushAsync(EngineFn fn, void* arg, const int64_t* read_vars,
                       int n_read, const int64_t* write_vars, int n_write,
                       int priority) {
  std::vector<Var*> reads, writes;
  for (int i = 0; i < n_read; ++i) reads.push_back(GetVar(read_vars[i]));
  for (int i = 0; i < n_write; ++i) writes.push_back(GetVar(write_vars[i]));
  PushAsyncVars(fn, arg, std::move(reads), std::move(writes), priority,
                false);
}

// synchronous mode: the pushing thread runs everything that is ready
// (including work unblocked by completions) — ref: naive_engine.cc
void Engine::DrainReady() {
  for (;;) {
    Opr* opr = nullptr;
    {
      std::lock_guard<std::mutex> lk(ready_m_);
      if (!ready_hi_.empty()) {
        opr = ready_hi_.front();
        ready_hi_.pop_front();
      } else if (!ready_lo_.empty()) {
        opr = ready_lo_.front();
        ready_lo_.pop_front();
      }
    }
    if (opr == nullptr) return;
    Execute(opr);
  }
}

void Engine::Execute(Opr* opr) {
  opr->fn(opr->arg);
  CompleteDeps(opr);
  delete opr;
  {
    std::lock_guard<std::mutex> lk(pending_m_);
    --pending_;
  }
  pending_cv_.notify_all();
}

void Engine::CompleteDeps(Opr* opr) {
  for (Var* v : opr->reads) {
    std::lock_guard<std::mutex> lk(v->m);
    --v->running_reads;
    GrantLocked(v);
  }
  for (Var* v : opr->writes) {
    bool free_var = false;
    {
      std::lock_guard<std::mutex> lk(v->m);
      v->running_write = false;
      ++v->version;
      GrantLocked(v);
      // the deleting op is the var's final write: safe to free once its
      // queue drained (the handle was removed from the map beforehand)
      free_var = opr->delete_writes && v->queue.empty() &&
                 v->running_reads == 0 && !v->running_write;
    }
    if (free_var) delete v;
  }
}

void Engine::WorkerLoop() {
  for (;;) {
    Opr* opr = nullptr;
    {
      std::unique_lock<std::mutex> lk(ready_m_);
      ready_cv_.wait(lk, [this] {
        return shutdown_ || !ready_hi_.empty() || !ready_lo_.empty();
      });
      if (shutdown_ && ready_hi_.empty() && ready_lo_.empty()) return;
      if (!ready_hi_.empty()) {
        opr = ready_hi_.front();
        ready_hi_.pop_front();
      } else {
        opr = ready_lo_.front();
        ready_lo_.pop_front();
      }
    }
    Execute(opr);
  }
}

namespace {
struct WaitCtx {
  std::mutex m;
  std::condition_variable cv;
  bool done = false;
};
void SignalFn(void* arg) {
  WaitCtx* w = static_cast<WaitCtx*>(arg);
  std::lock_guard<std::mutex> lk(w->m);
  w->done = true;
  w->cv.notify_all();
}
}  // namespace

void Engine::WaitForVar(int64_t handle) {
  // a read op that signals — serializes after all pending writes
  WaitCtx w;
  PushAsync(SignalFn, &w, &handle, 1, nullptr, 0, 1);
  std::unique_lock<std::mutex> lk(w.m);
  w.cv.wait(lk, [&] { return w.done; });
}

void Engine::WaitForAll() {
  std::unique_lock<std::mutex> lk(pending_m_);
  pending_cv_.wait(lk, [this] { return pending_ == 0; });
}

int Engine::NumPending() {
  std::lock_guard<std::mutex> lk(pending_m_);
  return pending_;
}

uint64_t Engine::VarVersion(int64_t handle) {
  Var* v = GetVar(handle);
  std::lock_guard<std::mutex> lk(v->m);
  return v->version;
}

}  // namespace mxt
