"""SPMDTrainer — the whole training step as ONE sharded XLA program.

This is the TPU-native scale-out path that subsumes the reference's
Trainer + KVStore pipeline (SURVEY.md CS2/CS5).  Where the reference does
    forward (engine ops) -> backward (engine ops) -> kvstore push/pull
    (NCCL allreduce or ps-lite) -> optimizer update ops
as four separately-scheduled phases, here the entire step —
forward, backward, gradient allreduce, optimizer update — is a single
jitted program over a DeviceMesh.  XLA overlaps the gradient collectives
with remaining backward compute (bucketing for free) and the collectives
ride ICI; parameters/optimizer state stay resident in HBM in their sharded
layout; buffers are donated so updates are in-place.

Grad sync semantics: the loss is a mean over the GLOBAL batch, so the psum
XLA inserts for the 'dp'/'fsdp' axes IS the gradient allreduce — identical
math to KVStore('nccl') push/pull in the reference, one fused program here.
"""
from __future__ import annotations

import functools
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..telemetry import instruments as _ins
from ..telemetry import mxgoodput as _goodput
from ..telemetry import mxhealth as _mxhealth
from ..telemetry import tracing as _tracing
from .. import optimizer as opt_mod
from .. import random as rnd
from .mesh import DeviceMesh, current_mesh, layout_key, make_mesh
from .sharding import (ShardingRules, DEFAULT_RULES, shard_batch,
                       zero_state_spec)

__all__ = ["SPMDTrainer", "functional_optimizer", "FunctionalOptimizer",
           "step_compile_stats"]

# mesh-wide fwd+bwd+update executables: routed through the persistent
# compile cache (PR 7) so a same-topology restart warm-starts the step
# without an XLA compile; program-text keys ONLY (the program embeds
# the user's model forward, which no framework version can pin)
_STEP_CACHE = opt_mod.fused.ExecutableCache(
    "parallel.spmd_step", "parallel.spmd._STEP_CACHE", "spmd",
    "spmd-compile", lambda: _ins.spmd_compile_seconds())


def step_compile_stats():
    """SPMDTrainer step-executable builds/loads in this process (same
    shape as optimizer.fused.compile_stats)."""
    return _STEP_CACHE.stats()


# class qualname + param names + avals do NOT pin the model's forward
# MATH (two same-shape nets can wire differently), so the in-process
# sig carries a per-block token: only the same block instance short-
# circuits the trace; a different block re-lowers and lets the
# persistent tier dedupe by program text.  Weak-keyed so a dead block
# releases its executables' cache slot identity.
import itertools as _itertools
import threading as _threading
import weakref as _weakref

# distinct input SHAPES a trainer keeps hot executables for (the
# evicted ones stay reachable through _STEP_CACHE / the persistent
# tier — eviction costs a sig rebuild, never an XLA compile)
_STEP_FNS_MAX = 16

_BLOCK_TOKENS: "_weakref.WeakKeyDictionary" = _weakref.WeakKeyDictionary()
_BLOCK_TOKENS_LOCK = _threading.Lock()
_BLOCK_TOKEN_NEXT = _itertools.count()


def _block_token(block) -> int:
    with _BLOCK_TOKENS_LOCK:
        tok = _BLOCK_TOKENS.get(block)
        if tok is None:
            tok = next(_BLOCK_TOKEN_NEXT)
            _BLOCK_TOKENS[block] = tok
        return tok


# ---------------------------------------------------------------------------
# functional optimizers — pure (w, g, state, lr, t) -> (w', state') built on
# the same registered update ops the imperative Optimizer classes use
# (ops/optimizer_ops.py; ref src/operator/optimizer_op.cc)
# ---------------------------------------------------------------------------

class FunctionalOptimizer:
    def __init__(self, n_state: int, update: Callable, wd: float = 0.0,
                 clip_gradient: float = -1.0):
        self.n_state = n_state
        self._update = update
        self.wd = wd
        self.clip_gradient = clip_gradient
        # set from Optimizer.multi_precision by functional_optimizer()
        self.multi_precision = False

    def needs_master(self, value) -> bool:
        """Under Optimizer(multi_precision=True), low-precision params get
        fp32 optimizer state AND an fp32 master weight carried as the LAST
        element of the state tuple — the reference's mp_sgd_* / mp_adam
        weight32 state (ref: optimizer_op.cc MP_SGD kernels).  Without the
        master, updates below one bf16 ulp round away (reference non-mp
        behavior, the default there too); mp costs ~4% step time on the
        ResNet-50 bench."""
        return (self.multi_precision
                and value.dtype in (jnp.bfloat16, jnp.float16))

    def init(self, value: jax.Array) -> Tuple[jax.Array, ...]:
        # state dtype is FIXED from step 0 (update math runs in fp32; a
        # bf16 state that flipped to fp32 after step 1 would retrace)
        if self.needs_master(value):
            return tuple(jnp.zeros(value.shape, jnp.float32)
                         for _ in range(self.n_state)) + (
                value.astype(jnp.float32),)
        return tuple(jnp.zeros_like(value) for _ in range(self.n_state))

    def apply(self, value, grad, state, lr, t, lr_mult=1.0, wd_mult=1.0):
        return self._update(value, grad, state, lr * lr_mult,
                            self.wd * wd_mult, self.clip_gradient, t)


def _global_put(v, sh):
    """device_put that also works on multi-process meshes whose backend
    has no cross-host transfers (CPU+gloo).

    Host values: every process holds the same global value (the launcher
    contract), so each device takes its shard locally via
    make_array_from_callback.  Values that are ALREADY global jax arrays
    (e.g. optimizer state computed from global params) cannot be pulled
    to host; they reshard through a jitted identity, which moves data
    with in-program collectives instead of host transfers."""
    if getattr(sh, "is_fully_addressable", True):
        return jax.device_put(v, sh)
    if isinstance(v, jax.Array) and not v.is_fully_addressable:
        if v.sharding == sh:
            return v
        return jax.jit(lambda x: x, out_shardings=sh)(v)
    v = np.asarray(v)
    return jax.make_array_from_callback(v.shape, sh, lambda idx: v[idx])


def _pure(name):
    from ..ops.registry import apply_pure

    return functools.partial(apply_pure, name)


def functional_optimizer(opt) -> FunctionalOptimizer:
    """Build the pure update for an Optimizer instance (or name)."""
    if isinstance(opt, str):
        opt = opt_mod.create(opt)
    fo = _functional_optimizer_impl(opt)
    fo.multi_precision = bool(getattr(opt, "multi_precision", False))
    return fo


def _functional_optimizer_impl(opt) -> FunctionalOptimizer:
    wd = float(opt.wd)
    clip = float(opt.clip_gradient) if opt.clip_gradient is not None else -1.0
    kind = type(opt).__name__

    if kind in ("SGD", "NAG"):
        momentum = float(getattr(opt, "momentum", 0.0))
        if momentum == 0.0:
            upd = _pure("sgd_update")

            def update(w, g, s, lr, wd_, c, t):
                return upd(w, g, lr=lr, wd=wd_, clip_gradient=c), ()
            return FunctionalOptimizer(0, update, wd, clip)
        op_name = "nag_mom_update" if kind == "NAG" else "sgd_mom_update"
        upd = _pure(op_name)

        def update(w, g, s, lr, wd_, c, t):
            nw, nm = upd(w, g, s[0], lr=lr, momentum=momentum, wd=wd_,
                         clip_gradient=c)
            return nw, (nm,)
        return FunctionalOptimizer(1, update, wd, clip)

    if kind == "Adam":
        b1, b2, eps = float(opt.beta1), float(opt.beta2), float(opt.epsilon)
        upd = _pure("adam_update")

        def update(w, g, s, lr, wd_, c, t):
            # bias correction (ref: Adam.update computes coef host-side)
            tt = t.astype(jnp.float32)
            coef = jnp.sqrt(1.0 - b2 ** tt) / (1.0 - b1 ** tt)
            nw, nm, nv = upd(w, g, s[0], s[1], lr=1.0, beta1=b1, beta2=b2,
                             epsilon=eps, wd=wd_, clip_gradient=c)
            # adam_update applies lr directly; redo with scaled lr instead
            return w + (nw - w) * (lr * coef), (nm, nv)
        return FunctionalOptimizer(2, update, wd, clip)

    if kind == "RMSProp":
        g1 = float(getattr(opt, "gamma1", 0.9))
        g2 = float(getattr(opt, "gamma2", 0.9))
        eps = float(getattr(opt, "epsilon", 1e-8))
        if getattr(opt, "centered", False):
            upd = _pure("rmspropalex_update")

            def update(w, g, s, lr, wd_, c, t):
                nw, nn, ng, ndel = upd(w, g, s[0], s[1], s[2], lr=lr,
                                       gamma1=g1, gamma2=g2, epsilon=eps,
                                       wd=wd_, clip_gradient=c)
                return nw, (nn, ng, ndel)
            return FunctionalOptimizer(3, update, wd, clip)
        upd = _pure("rmsprop_update")

        def update(w, g, s, lr, wd_, c, t):
            nw, nn = upd(w, g, s[0], lr=lr, gamma1=g1, epsilon=eps, wd=wd_,
                         clip_gradient=c)
            return nw, (nn,)
        return FunctionalOptimizer(1, update, wd, clip)

    if kind == "AdaGrad":
        eps = float(getattr(opt, "float_stable_eps",
                            getattr(opt, "eps",
                                    getattr(opt, "epsilon", 1e-7))))
        upd = _pure("adagrad_update")

        def update(w, g, s, lr, wd_, c, t):
            nw, nh = upd(w, g, s[0], lr=lr, epsilon=eps, wd=wd_,
                         clip_gradient=c)
            return nw, (nh,)
        return FunctionalOptimizer(1, update, wd, clip)

    if kind in ("Signum", "SignSGD"):
        momentum = float(getattr(opt, "momentum", 0.0))
        if momentum == 0.0:
            upd = _pure("signsgd_update")

            def update(w, g, s, lr, wd_, c, t):
                return upd(w, g, lr=lr, wd=wd_, clip_gradient=c), ()
            return FunctionalOptimizer(0, update, wd, clip)
        upd = _pure("signum_update")

        def update(w, g, s, lr, wd_, c, t):
            nw, nm = upd(w, g, s[0], lr=lr, momentum=momentum, wd=wd_,
                         clip_gradient=c)
            return nw, (nm,)
        return FunctionalOptimizer(1, update, wd, clip)

    if kind == "AdaDelta":
        rho = float(opt.rho)
        eps = float(opt.epsilon)
        upd = _pure("adadelta_update")

        def update(w, g, s, lr, wd_, c, t):
            nw, na, nd = upd(w, g, s[0], s[1], lr=lr, rho=rho, epsilon=eps,
                             wd=wd_, clip_gradient=c)
            return nw, (na, nd)
        return FunctionalOptimizer(2, update, wd, clip)

    if kind == "Adamax":
        b1, b2 = float(opt.beta1), float(opt.beta2)
        upd = _pure("adamax_update")

        def update(w, g, s, lr, wd_, c, t):
            tt = t.astype(jnp.float32)
            lr_t = lr / (1.0 - b1 ** tt)
            nw, nm, nv = upd(w, g, s[0], s[1], lr=lr_t, beta1=b1, beta2=b2,
                             wd=wd_, clip_gradient=c)
            return nw, (nm, nv)
        return FunctionalOptimizer(2, update, wd, clip)

    if kind == "Ftrl":
        lamda1 = float(opt.lamda1)
        beta = float(opt.beta)
        upd = _pure("ftrl_update")

        def update(w, g, s, lr, wd_, c, t):
            nw, nz, nn = upd(w, g, s[0], s[1], lr=lr, lamda1=lamda1,
                             beta=beta, wd=wd_, clip_gradient=c)
            return nw, (nz, nn)
        return FunctionalOptimizer(2, update, wd, clip)

    raise MXNetError(
        f"no functional form for optimizer {kind}; supported: SGD, NAG, "
        "Adam, RMSProp, AdaGrad, Signum, SignSGD, AdaDelta, Adamax, Ftrl")


# ---------------------------------------------------------------------------
# SPMDTrainer
# ---------------------------------------------------------------------------

class SPMDTrainer:
    """One-program-per-step trainer over a DeviceMesh.

    Parameters
    ----------
    block : an initialized gluon (Hybrid)Block — the model.
    loss : callable applied as ``loss(out, *labels)`` inside the trace;
        a gluon Loss block works (its forward runs traced).
    optimizer : name or mxnet_tpu Optimizer instance.
    mesh : DeviceMesh (defaults to the active one, else all-devices 'dp').
    rules : ShardingRules mapping parameter names -> PartitionSpec.
    batch_spec / label_spec : PartitionSpec for each data / label input
        (defaults: dim 0 over dp/fsdp, rest replicated).

    Usage::

        mesh = parallel.make_mesh(dp=4, tp=2)
        with mesh:
            trainer = parallel.SPMDTrainer(net, loss, "sgd",
                                           {"learning_rate": 0.1})
            for data, label in batches:
                l = trainer.step(data, label)      # async; one XLA program
        trainer.sync_to_block()                    # params back to gluon
    """

    def __init__(self, block, loss: Callable, optimizer="sgd",
                 optimizer_params: Optional[dict] = None,
                 mesh: Optional[DeviceMesh] = None,
                 rules: ShardingRules = DEFAULT_RULES,
                 batch_spec: Optional[Sequence] = None,
                 label_spec: Optional[Sequence] = None,
                 n_labels: int = 1,
                 donate: bool = True,
                 remat: bool = False):
        #: remat: gradient mirroring for the fused train step — each
        #: sub-block becomes a jax.checkpoint segment, so the backward
        #: recomputes its activations instead of holding them in HBM
        #: across the whole fwd+bwd+update program
        #: (ref: MXNET_BACKWARD_DO_MIRROR role)
        self.remat = bool(remat)
        self.block = block
        self.loss = loss
        self.mesh = mesh or current_mesh() or make_mesh()
        self.rules = rules
        self._batch_spec = batch_spec
        self._label_spec = label_spec
        self.n_labels = n_labels
        self._donate = donate

        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        elif optimizer_params:
            raise MXNetError("optimizer_params must be None when optimizer "
                             "is an instance")
        self._optimizer = optimizer
        self._fopt = functional_optimizer(optimizer)

        self._plist = sorted(block.collect_params().items())
        self._mults = {
            n: (float(p.lr_mult), float(p.wd_mult)) for n, p in self._plist}
        self._trainable = {n: p.grad_req != "null" for n, p in self._plist}

        # shard parameters onto the mesh per the rules; optimizer
        # states get the ZeRO-1 layout (MXNET_ZERO_STATES, default on):
        # states of a dp-replicated parameter shard across the data
        # axes, so XLA turns the grad psum into reduce-scatter + the
        # weight refresh into all-gather (arXiv:2004.13336) and each
        # device holds 1/N of the state bytes
        from ..util import env as _envmod

        self._zero = bool(_envmod.get_bool("MXNET_ZERO_STATES"))
        self.params: Dict[str, jax.Array] = {}
        self._shardings: Dict[str, NamedSharding] = {}
        self._state_shardings: Dict[str, NamedSharding] = {}
        for n, p in self._plist:
            v = p.data().data
            spec = rules.spec_for(n, v.shape, self.mesh)
            sh = NamedSharding(self.mesh.mesh, spec)
            self._shardings[n] = sh
            sspec = zero_state_spec(
                spec, v.shape, self.mesh,
                min_size=_envmod.get_int("MXNET_ZERO_MIN_SIZE")) \
                if self._zero else spec
            self._state_shardings[n] = NamedSharding(self.mesh.mesh, sspec)
            self.params[n] = _global_put(v, sh)
        self.opt_state = {
            n: tuple(_global_put(s, self._state_shardings[n])
                     for s in self._fopt.init(v))
            for n, v in self.params.items() if self._trainable[n]}

        # replicated trainable params fuse into one flat update kernel per
        # (lr_mult, wd_mult) group; mesh-sharded params stay per-parameter
        from ..util import env

        self._has_master = {
            n: self._fopt.needs_master(v) for n, v in self.params.items()
            if self._trainable[n]}
        groups: Dict[Tuple, List[str]] = {}
        self._per_param: List[str] = []
        # default OFF: profiling showed the 1-D concat destroys conv-weight
        # tiled layouts and donation aliasing, costing far more than the
        # per-param fusions it merges (162ms vs 113ms ResNet-50 step); the
        # per-param updates fuse into the wgrad epilogue anyway
        flat_on = env.get_bool("MXNET_FUSED_OPTIMIZER")
        for n, p in self._plist:
            if not self._trainable[n]:
                continue
            if flat_on and self._shardings[n].is_fully_replicated:
                # dtype in the key: groups must be homogeneous (concat
                # would silently promote, and master-weight handling
                # differs between bf16 and fp32 params)
                key = self._mults[n] + (str(self.params[n].dtype),)
                groups.setdefault(key, []).append(n)
            else:
                self._per_param.append(n)
        self._flat_groups = [(tuple(names), lm, wm)
                             for (lm, wm, _dt), names in sorted(groups.items())]

        # per-shape fast path over _STEP_CACHE; LRU-bounded because
        # each value strong-refs a whole-step executable — an unbounded
        # dict would outlive _STEP_CACHE's own eviction (ragged last
        # batches / variable seq-len mint a new shape per epoch)
        self._step_fns: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._fwd_fn = None
        self._param_by_name = {n: p for n, p in self._plist}
        self._t = 0

    # ---- the pure step ---------------------------------------------------
    def _build_pure(self):
        plist = self._plist
        block, loss, fopt = self.block, self.loss, self._fopt
        mults, trainable = self._mults, self._trainable
        trainer = self

        from ..gluon.block import ActiveTrace

        name_of = {id(p): n for n, p in plist}

        def pure_step(params, opt_state, inputs, labels, key, lr, t):
            def loss_fn(pv):
                trace = ActiveTrace(
                    {id(p): pv[n] for n, p in plist}, train=True)
                trace.mirror = trainer.remat  # per-sub-block segments
                # the trainer's mesh scope is active for the whole
                # traced step, wherever step() was called from — code
                # consulting current_mesh() at trace time (ring/ulysses
                # attention, the fused-conv shard_map plan, sharding
                # constraints) sees THIS mesh, not the caller's ambient
                # scope
                with trainer.mesh, trace, \
                        rnd.key_provider(rnd.KeyProvider(key)):
                    out = block.forward(*inputs)
                    outs = out if isinstance(out, (list, tuple)) else (out,)
                    l = loss(outs[0], *labels)
                lval = jnp.mean(l if not isinstance(l, (list, tuple))
                                else l[0])
                # aux (BatchNorm moving stats) keyed BY NAME in the traced
                # outputs — no side-channel ordering that a retrace could
                # skew (round-1 weak #10)
                aux_named = {name_of[id(p)]: v for p, v in
                             zip(trace.aux_params, trace.aux_values)}
                return lval, aux_named

            (lval, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_state = {}, {}
            for n, _ in plist:
                if not trainable[n]:
                    new_params[n] = params[n]

            # Fused flat update: replicated trainable params concatenate
            # into ONE elementwise update kernel per (lr_mult, wd_mult)
            # group instead of one tiny fusion per parameter — profiling
            # showed the per-parameter tail costing ~17% of the ResNet-50
            # step.  Mesh-sharded params keep the per-parameter path (a
            # concat across different shardings would force gathers).
            def apply_one(n, w, g, state, lm, wm):
                """Update one (possibly flat-concatenated) weight; the fp32
                master weight, when present, is the last state element and
                is what the update math runs on (mp_* semantics)."""
                if trainer._has_master[n]:
                    w32, st = state[-1], state[:-1]
                    nw32, ns = fopt.apply(w32, g, st, lr, t,
                                          lr_mult=lm, wd_mult=wm)
                    return nw32.astype(w.dtype), ns + (nw32,)
                nw, ns = fopt.apply(w, g, state, lr, t,
                                    lr_mult=lm, wd_mult=wm)
                return nw.astype(w.dtype), tuple(
                    sv.astype(state[i].dtype) for i, sv in enumerate(ns))

            for names, lm, wm in trainer._flat_groups:
                # concat in NATIVE dtypes — upcasts happen in-register
                # inside the one fused update kernel, never materialized
                n_st = len(opt_state[names[0]])
                fw = jnp.concatenate(
                    [params[n].reshape(-1) for n in names])
                fg = jnp.concatenate(
                    [grads[n].reshape(-1) for n in names])
                fs = tuple(
                    jnp.concatenate(
                        [opt_state[n][i].reshape(-1) for n in names])
                    for i in range(n_st))
                nw, ns = apply_one(names[0], fw, fg, fs, lm, wm)
                off = 0
                for n in names:
                    p = params[n]
                    sz = int(np.prod(p.shape)) if p.shape else 1
                    sl = lax.slice(nw, (off,), (off + sz,))
                    new_params[n] = sl.reshape(p.shape).astype(p.dtype)
                    new_state[n] = tuple(
                        lax.slice(s, (off,), (off + sz,))
                        .reshape(p.shape).astype(opt_state[n][i].dtype)
                        for i, s in enumerate(ns))
                    off += sz
            for n in trainer._per_param:
                lm, wm = mults[n]
                new_params[n], new_state[n] = apply_one(
                    n, params[n], grads[n], opt_state[n], lm, wm)
            # aux state (BatchNorm moving stats) accumulates across steps:
            # fold the traced updates back into the param dict so the next
            # step's trace reads them (stop_gradient — not a learnable path)
            for n, v in aux.items():
                new_params[n] = lax.stop_gradient(v).astype(params[n].dtype)
            return new_params, new_state, lval, aux

        return pure_step

    def _opt_static_fingerprint(self) -> Tuple:
        """Hashable fingerprint of the optimizer attrs BAKED into the
        traced program (wd, momentum, betas, ... — read at functional-
        optimizer construction).  lr and rescale_grad stay out: they
        are traced arguments and must never force a recompile."""
        skip = {"lr", "rescale_grad", "num_update", "begin_num_update"}
        return tuple(sorted(
            (k, v) for k, v in self._optimizer.__dict__.items()
            if k not in skip and isinstance(v, (int, float, bool, str))))

    def _get_step(self, args, ikey):
        if ikey not in self._step_fns:
            mesh = self.mesh
            psh = self._shardings
            state_sh = {n: tuple(self._state_shardings[n] for _ in s)
                        for n, s in self.opt_state.items()}
            repl = NamedSharding(mesh.mesh, P())
            jitted = jax.jit(
                self._build_pure(),
                in_shardings=(psh, state_sh, None, None, repl, repl, repl),
                out_shardings=(psh, state_sh, repl, None),
                donate_argnums=(0, 1) if self._donate else ())
            cell = {}

            def build_lowered():
                if "l" not in cell:
                    cell["l"] = jitted.lower(*args)
                return cell["l"]

            leaves, treedef = jax.tree_util.tree_flatten(args)
            block = self.block
            # the in-process signature pins everything the closure
            # bakes in: the block INSTANCE (class+param names don't pin
            # forward math), optimizer statics, mults, layout, and the
            # concrete devices (an executable is bound to its device
            # assignment — two trainers on disjoint subsets of the same
            # topology must not share one); the PERSISTENT key adds the
            # lowered program text, which pins the actual model code
            sig = ("spmd-train-step", _block_token(block),
                   f"{type(block).__module__}.{type(block).__qualname__}",
                   tuple(n for n, _ in self._plist),
                   tuple(sorted(self._mults.items())),
                   type(self._optimizer), self._opt_static_fingerprint(),
                   tuple(self._flat_groups), self.remat,
                   layout_key(self.mesh),
                   tuple(str(d) for d in mesh.devices),
                   self._zero, self._donate,
                   treedef,
                   tuple(opt_mod.fused._leaf_aval(x) for x in leaves))
            fn = _STEP_CACHE.lookup(sig)
            if fn is None:
                # named sig view for compile provenance (same order as
                # the sig tuple above)
                components = {
                    "block": sig[1:4], "mults": sig[4],
                    "optimizer": sig[5], "statics": sig[6],
                    "flat_groups": sig[7], "remat": sig[8],
                    "layout": sig[9], "devices": sig[10],
                    "zero": sig[11], "donation": sig[12],
                    "treedef": sig[13], "avals": sig[14]}
                fn = _STEP_CACHE.compile(sig, build_lowered,
                                         self._optimizer,
                                         alias_ok=False,
                                         components=components,
                                         donate=self._donate)
            # per-trainer fast path keyed by input avals: a batch-shape
            # change rebuilds (AOT does not silently retrace), a repeat
            # shape is one dict hit.  The executable's static cost
            # rides along for mxprof's whole-step MFU.
            self._step_fns[ikey] = (fn, _STEP_CACHE.cost(sig))
            while len(self._step_fns) > _STEP_FNS_MAX:
                self._step_fns.popitem(last=False)
        else:
            self._step_fns.move_to_end(ikey)
        return self._step_fns[ikey]

    # ---- data movement ---------------------------------------------------
    def _spec_sharding(self, spec, arr):
        if spec is None:
            return shard_batch(self.mesh, extra_dims=arr.ndim - 1)
        return NamedSharding(self.mesh.mesh, spec)

    def _place(self, x, spec):
        v = x.data if isinstance(x, NDArray) else jnp.asarray(x)
        return _global_put(v, self._spec_sharding(spec, v))

    # ---- public API ------------------------------------------------------
    def step(self, *args) -> NDArray:
        """Run one training step on a global batch; returns the loss
        (async — only .asnumpy() blocks).  The last ``n_labels`` args are
        labels, the rest model inputs."""
        if _goodput._ACTIVE:
            # first post-resume step entry closes the goodput
            # preemption-recovery window (one falsy check when off)
            _goodput.on_step_entry()
        n_lab = self.n_labels
        if n_lab == 0:
            inputs, labels = args, ()
        else:
            inputs, labels = args[:-n_lab], args[-n_lab:]
        bspecs = self._batch_spec or [None] * len(inputs)
        lspecs = self._label_spec or [None] * len(labels)
        ivals = tuple(self._place(x, s) for x, s in zip(inputs, bspecs))
        lvals = tuple(self._place(x, s) for x, s in zip(labels, lspecs))
        self._t += 1
        self._optimizer._update_count(0)
        lr = jnp.asarray(self._optimizer.learning_rate, jnp.float32)
        t = jnp.asarray(self._t, jnp.int32)
        key = rnd.next_key()
        args = (self.params, self.opt_state, ivals, lvals, key, lr, t)
        ikey = tuple((tuple(v.shape), str(v.dtype))
                     for v in ivals + lvals)
        step, step_cost = self._get_step(args, ikey)
        if not _tracing.active():
            out = step(*args)
        else:
            if _tracing._ENABLED:
                for ax, size in self.mesh.axis_sizes.items():
                    _ins.step_layout_axis_size(ax).set(size)
                factor = 1
                if self._zero:
                    for ax in ("dp", "fsdp"):
                        factor *= self.mesh.size(ax)
                _ins.step_state_shard_factor().set(factor)
            with _tracing.span("spmd-step", cat="training",
                               metric=_ins.training_phase_seconds(
                                   "spmd-step")
                               if _tracing._ENABLED else None):
                out = step(*args)
            snk = _tracing._SINK
            if snk is not None and step_cost is not None:
                # whole-step program: forward+backward+update FLOPs in
                # one executable — the gspmd path's MFU counts
                # everything.  AFTER the span: this step's record only
                # closes when the NEXT spmd-step span arrives, so flops
                # reported before the span would land one record early
                # (and double the first closed record's MFU).
                snk.on_flops(_STEP_CACHE.site, step_cost)
        self.params, self.opt_state, lval, aux = out
        # rebind aux state (BatchNorm moving stats) by parameter NAME
        for n, v in aux.items():
            self._param_by_name[n].data()._data = v
        if _mxhealth._ACTIVE:
            # loss-spike detection feed: the device scalar is handed
            # off as-is; the monitor's fetch thread syncs it, the step
            # path never does
            _mxhealth.observe_loss(lval)
        from ..context import current_context

        return NDArray(lval, ctx=current_context())

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def save_checkpoint(self, path: str):
        """Sharded (tensorstore) checkpoint of params + optimizer state +
        step; resumable on a different mesh (see parallel.checkpoint)."""
        from .checkpoint import save_sharded

        save_sharded(path, self)

    def load_checkpoint(self, path: str):
        from .checkpoint import load_sharded

        load_sharded(path, self)

    def sync_to_block(self):
        """Copy the (sharded) params back into the gluon Parameters —
        call before save_parameters()/export()."""
        for n, p in self._plist:
            v = self.params[n]
            gathered = jax.device_get(v)
            for c in list(p._data or {}):
                p._data[c]._data = jnp.asarray(gathered)

    def forward(self, *inputs) -> NDArray:
        """Sharded inference with the trainer's current params."""
        if self._fwd_fn is None:
            from ..gluon.block import ActiveTrace

            plist = self._plist
            block = self.block

            def fwd(params, ivals, key):
                trace = ActiveTrace({id(p): params[n] for n, p in plist},
                                    train=False)
                # the trainer's mesh scope is active for the whole
                # traced step, wherever step() was called from — code
                # consulting current_mesh() at trace time (ring/ulysses
                # attention, the fused-conv shard_map plan, sharding
                # constraints) sees THIS mesh, not the caller's ambient
                # scope
                with trainer.mesh, trace, \
                        rnd.key_provider(rnd.KeyProvider(key)):
                    out = block.forward(*ivals)
                return out

            self._fwd_fn = jax.jit(fwd)
        bspecs = self._batch_spec or [None] * len(inputs)
        ivals = tuple(self._place(x, s) for x, s in zip(inputs, bspecs))
        out = self._fwd_fn(self.params, ivals, rnd.next_key())
        from ..context import current_context

        ctx = current_context()
        return jax.tree_util.tree_map(lambda v: NDArray(v, ctx=ctx), out)
