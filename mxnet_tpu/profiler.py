"""Profiler: chrome-trace host-side op records + XLA/TPU xplane bridge.

TPU-native counterpart of the reference profiler
(ref: src/profiler/profiler.cc, python/mxnet/profiler.py,
src/c_api/c_api_profile.cc): per-op start/stop records captured at the
dispatch site, chrome://tracing JSON dump, aggregate stats table, custom
task/event/counter API.  The device-side timeline comes from JAX's built-in
profiler (tensorboard xplane) via start_xla_trace/stop_xla_trace.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

from .util import env

__all__ = [
    "set_config", "start", "stop", "dump", "dumps", "profile_op",
    "Task", "Event", "Counter", "scope", "start_xla_trace", "stop_xla_trace",
    "append_event", "instant", "num_events",
]

_lock = threading.Lock()
_dump_lock = threading.Lock()  # serializes dump(): two concurrent
# finished=True dumps must not each clear their snapshot's prefix
# (events recorded between the snapshots would vanish from both files)
_config = {
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "filename": "profile.json",
    "aggregate_stats": False,
}
_running = False
_events: List[dict] = []
_agg: Dict[str, List[float]] = defaultdict(list)


def set_config(**kwargs):
    """Set profiler config knobs; unknown keys raise (a typo like
    ``profile_memroy`` must fail loudly, not silently no-op)."""
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise ValueError(
            f"profiler.set_config: unknown key(s) "
            f"{sorted(unknown)}; valid keys: {sorted(_config)}")
    with _lock:
        _config.update(kwargs)


def start():
    global _running
    _running = True


def stop():
    global _running
    _running = False


def is_running() -> bool:
    return _running


def append_event(ev: dict) -> bool:
    """Append one raw chrome-trace event while the profiler is running
    (the hook the telemetry tracing layer emits spans through).
    Returns whether the event was recorded."""
    if not _running:
        return False
    with _lock:
        _events.append(ev)
    return True


def num_events() -> int:
    with _lock:
        return len(_events)


def instant(name: str, domain: str = "user",
            args: Optional[dict] = None) -> bool:
    """Record an instant marker (chrome ``"ph": "i"``, thread scope)."""
    ev = {"name": name, "ph": "i", "s": "t", "cat": domain,
          "ts": time.perf_counter() * 1e6, "pid": os.getpid(),
          "tid": threading.get_ident()}
    if args:
        ev["args"] = args
    return append_event(ev)


if env.get_bool("MXNET_PROFILER_AUTOSTART"):
    start()


@contextlib.contextmanager
def profile_op(name: str):
    """Hot-path hook used by ops.registry.invoke.

    Records host dispatch time (device time lives in the xplane trace —
    dispatch is async so wall time here is launch overhead, matching the
    reference's 'engine dispatch' lane).
    """
    if not _running:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        with _lock:
            _events.append({
                "name": name, "ph": "X", "cat": "operator",
                "ts": t0 * 1e6, "dur": (t1 - t0) * 1e6,
                "pid": os.getpid(), "tid": threading.get_ident(),
            })
            _agg[name].append(t1 - t0)


@contextlib.contextmanager
def scope(name: str, category: str = "user"):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        with _lock:
            _events.append({
                "name": name, "ph": "X", "cat": category,
                "ts": t0 * 1e6, "dur": (t1 - t0) * 1e6,
                "pid": os.getpid(), "tid": threading.get_ident(),
            })


class Task:
    """ref: profiler.ProfileTask."""

    def __init__(self, name: str, domain: str = "user"):
        self.name, self.domain = name, domain
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is None:
            return
        t1 = time.perf_counter()
        with _lock:
            _events.append({"name": self.name, "ph": "X", "cat": self.domain,
                            "ts": self._t0 * 1e6, "dur": (t1 - self._t0) * 1e6,
                            "pid": os.getpid(), "tid": threading.get_ident()})
        self._t0 = None


class Event:
    """ref: profiler.ProfileEvent — an INSTANT marker, not a duration.

    ``Event("epoch").mark()`` drops a chrome-trace instant event
    (``"ph": "i"``) at the current time.  ``start()``/``stop()`` are
    kept for Task-style call sites but each records an instant marker
    (tagged with the edge in ``args``) rather than accumulating a
    duration — use ``Task`` for timed ranges.
    """

    def __init__(self, name: str, domain: str = "user"):
        self.name, self.domain = name, domain

    def mark(self, **args):
        instant(self.name, self.domain, args or None)

    def start(self):
        instant(self.name, self.domain, {"edge": "start"})

    def stop(self):
        instant(self.name, self.domain, {"edge": "stop"})


class Counter:
    """ref: profiler.ProfileCounter.

    Thread-safe: increment/decrement are atomic read-modify-writes (the
    serving layer bumps counters from admission, batcher, and worker
    threads concurrently).  Trace events are only recorded while the
    profiler is running — a hot-path counter must not grow the event
    buffer without bound in a long-lived server process; the live value
    itself is always maintained and readable via `.value`.
    """

    def __init__(self, name: str, domain: str = "user", value: int = 0):
        self.name, self.domain = name, domain
        self._value = value
        self._vlock = threading.Lock()
        self._emit(value)

    def _emit(self, v):
        if not _running:
            return
        with _lock:
            _events.append({"name": self.name, "ph": "C", "cat": self.domain,
                            "ts": time.perf_counter() * 1e6,
                            "pid": os.getpid(),
                            "args": {self.name: v}})

    @property
    def value(self):
        return self._value

    @value.setter
    def value(self, v):
        self.set_value(v)

    def set_value(self, v):
        with self._vlock:
            self._value = v
        self._emit(v)

    def increment(self, d=1):
        with self._vlock:
            self._value += d
            v = self._value
        self._emit(v)

    def decrement(self, d=1):
        self.increment(-d)

    def __iadd__(self, d):
        self.increment(d)
        return self

    def __isub__(self, d):
        self.decrement(d)
        return self


def dumps(reset: bool = False) -> str:
    """Aggregate per-op stats table (ref: AggregateStats::Dump).

    ``reset=True`` clears the AGGREGATE table only — trace events are
    untouched (their lifetime belongs to ``dump(finished=True)``).
    """
    with _lock:
        rows = []
        for name, ts in sorted(_agg.items(), key=lambda kv: -sum(kv[1])):
            n = len(ts)
            tot = sum(ts) * 1e3
            rows.append(f"{name:<40s} {n:>8d} {tot:>12.3f} "
                        f"{tot / n:>10.4f} {min(ts) * 1e3:>10.4f} {max(ts) * 1e3:>10.4f}")
        if reset:
            _agg.clear()
    header = (f"{'Name':<40s} {'Count':>8s} {'Total(ms)':>12s} "
              f"{'Mean(ms)':>10s} {'Min(ms)':>10s} {'Max(ms)':>10s}")
    return "\n".join([header] + rows)


def dump(finished: bool = True, filename: Optional[str] = None):
    """Write chrome://tracing JSON.

    ``finished=True`` (the default) CLEARS the event buffer after the
    write — a long-lived process that dumps periodically must not
    re-dump an ever-growing buffer.  Pass ``finished=False`` to keep
    accumulating into the same capture across dumps.
    """
    fn = filename or _config["filename"]
    with _dump_lock:
        with _lock:
            data = {"traceEvents": list(_events),
                    "displayTimeUnit": "ms"}
        with open(fn, "w") as f:
            json.dump(data, f)
        if finished:
            # clear only AFTER a successful write — a bad path/full
            # disk must not destroy the capture (events recorded
            # between the snapshot above and here land in the next
            # dump)
            with _lock:
                del _events[:len(data["traceEvents"])]
    return fn


def start_xla_trace(logdir: str = "/tmp/mx_xla_trace"):
    """Capture the device-side timeline via JAX's profiler (xplane,
    viewable in tensorboard-plugin-profile).

    Routed through the mxtriage capture manager: the manual bracket
    holds the SAME admission slot as ``mxtriage.deep_capture`` /
    ``POST /profilez`` / SIGUSR1 / alert-triggered captures, so two
    entry points can never stack jax profiler sessions (which corrupts
    both traces) — and the capture lands in the mxtriage index with
    its trigger recorded.  The manager owns the directory state
    (``mxtriage.active()``); there is no module-level copy."""
    from .telemetry import mxtriage

    return mxtriage.start_manual(logdir)


def stop_xla_trace():
    from .telemetry import mxtriage

    return mxtriage.stop_manual()
