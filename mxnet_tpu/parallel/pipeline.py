"""Pipeline parallelism over the 'pp' mesh axis.

Beyond-reference capability (SURVEY.md §2d: the reference's only model
parallelism is manual `group2ctx` placement).  Here a stack of identical
stages (e.g. transformer blocks) has its stacked parameters sharded over
'pp' — device i holds stage i — and microbatches stream through the ring:
each tick every device runs its stage on its current activation, then the
activations `ppermute` one hop forward.  After n_micro + n_stages - 1
ticks all microbatches have exited the last stage (GPipe schedule; bubble
= (S-1)/(M+S-1)).

The formulation is pure SPMD (shard_map + ppermute over ICI neighbours),
so XLA overlaps the activation transfer with the next tick's compute.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ._compat import shard_map_unchecked
from .mesh import DeviceMesh, current_mesh

__all__ = ["pipeline_apply", "stack_stage_params", "HeteroPipeline"]


class HeteroPipeline:
    """GPipe over HETEROGENEOUS stages — each stage has its own
    parameter pytree, its own activation shapes, and its own device.

    The SPMD ring (`pipeline_apply`) needs identical stages (stacked
    params, shape-preserving activations) because every device must run
    the same program.  Real models are not like that (ResNet stem ->
    blocks -> head), so this variant runs one jitted program PER STAGE
    on that stage's device and lets jax's async dispatch overlap the
    pipeline: issuing stage i's microbatch j returns immediately, so
    device i computes while device i+1 receives the previous microbatch
    — the dependency-engine execution model, generalizing the
    reference's `group2ctx` placement parallelism (SURVEY §2d) with
    autodiff.

    Backward is GPipe-with-rematerialization: each stage's backward
    program recomputes its forward for the VJP (activation memory per
    device stays O(one stage), the reference's mirror trade).

        pipe = HeteroPipeline([f0, f1, f2], [p0, p1, p2])
        y = pipe(x, n_microbatch=4)                       # inference
        loss, grads = pipe.value_and_grad(loss_fn, x, labels,
                                          n_microbatch=4)  # training
    """

    def __init__(self, stage_fns, stage_params, devices=None):
        if len(stage_fns) != len(stage_params):
            raise MXNetError("one params pytree per stage required")
        self.n_stages = len(stage_fns)
        if devices is None:
            devs = jax.local_devices()
            devices = [devs[i % len(devs)] for i in range(self.n_stages)]
        if len(devices) != self.n_stages:
            raise MXNetError(
                f"{len(devices)} devices for {self.n_stages} stages")
        self.devices = list(devices)
        self.params = [
            jax.device_put(p, d) for p, d in zip(stage_params, devices)]
        self._fns = list(stage_fns)
        self._fwd = [jax.jit(f) for f in stage_fns]

        def make_bwd(f):
            def bwd(p, a, g):
                _y, vjp = jax.vjp(f, p, a)  # recompute-for-backward
                return vjp(g)

            return jax.jit(bwd)

        self._bwd = [make_bwd(f) for f in stage_fns]
        self._lgrad_cache: dict = {}  # loss_fn -> jitted value_and_grad

    def _microbatches(self, x, n_microbatch):
        if x.shape[0] % n_microbatch:
            raise MXNetError(
                f"batch {x.shape[0]} not divisible by {n_microbatch}")
        m = x.shape[0] // n_microbatch
        return [x[j * m:(j + 1) * m] for j in range(n_microbatch)]

    def _forward_saved(self, x, n_microbatch):
        """Run all microbatches through all stages; returns per-stage
        INPUT activations (the remat residuals) and the outputs."""
        acts = [self._microbatches(x, n_microbatch)]
        for i in range(self.n_stages):
            dev = self.devices[i]
            ins = [jax.device_put(a, dev) for a in acts[i]]
            acts[i] = ins  # keep the device-placed copy as residual
            acts.append([self._fwd[i](self.params[i], a) for a in ins])
        return acts

    def __call__(self, x, n_microbatch=1):
        acts = self._forward_saved(jnp.asarray(x), n_microbatch)
        return jnp.concatenate(
            [jax.device_put(y, self.devices[-1]) for y in acts[-1]], 0)

    def value_and_grad(self, loss_fn, x, *labels, n_microbatch=1):
        """Mean loss over the batch + per-stage parameter grads (each on
        its stage's device).  loss_fn(y_micro, *labels_micro) -> scalar
        mean over the microbatch."""
        x = jnp.asarray(x)
        acts = self._forward_saved(x, n_microbatch)
        lab_mb = [self._microbatches(jnp.asarray(l), n_microbatch)
                  for l in labels]
        lgrad = self._lgrad_cache.get(loss_fn)
        if lgrad is None:  # jit keys on fn identity: cache per loss_fn.
            # Pass a STABLE callable, not a fresh lambda per step — each
            # new function object costs a trace+compile; the cache is
            # capped so per-step lambdas degrade to slow, not unbounded.
            if len(self._lgrad_cache) >= 8:
                self._lgrad_cache.pop(next(iter(self._lgrad_cache)))
            lgrad = jax.jit(jax.value_and_grad(loss_fn, argnums=0))
            self._lgrad_cache[loss_fn] = lgrad
        losses, gys = [], []
        for j, y in enumerate(acts[-1]):
            lv, gy = lgrad(y, *[lm[j] for lm in lab_mb])
            losses.append(lv)
            gys.append(gy)
        gparams = [None] * self.n_stages
        for i in reversed(range(self.n_stages)):
            dev = self.devices[i]
            nxt = []
            for j in range(n_microbatch):
                gp, ga = self._bwd[i](self.params[i], acts[i][j],
                                      jax.device_put(gys[j], dev))
                gparams[i] = gp if gparams[i] is None else \
                    jax.tree_util.tree_map(jnp.add, gparams[i], gp)
                nxt.append(ga)
            gys = nxt
        # microbatch-mean: losses average; grads scale by 1/M (loss_fn
        # is a per-microbatch mean, so the sum over microbatches must be
        # averaged too)
        scale = 1.0 / n_microbatch
        gparams = [jax.tree_util.tree_map(lambda a: a * scale, gp)
                   for gp in gparams]
        loss = sum(jax.device_get(l) for l in losses) * scale
        return float(loss), gparams


def stack_stage_params(params_list):
    """[{name: arr}, ...] per stage -> {name: arr[S, ...]} stacked pytree
    (the layout whose leading dim shards over 'pp')."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def _pipeline_local(stage_params, x_micro, stage_fn, axis_name):
    """Body inside shard_map.

    stage_params: pytree with leading stage dim of size 1 (this device's
        stage), i.e. {name: [1, ...]}.
    x_micro: [M_local?…] — microbatches replicated along pp: [M, B, ...].
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    sparams = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    m = x_micro.shape[0]
    ticks = m + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    state = jnp.zeros_like(x_micro[0])               # current activation
    outs = jnp.zeros_like(x_micro)                   # collected on last stage

    def body(t, carry):
        state, outs = carry
        # stage 0 ingests microbatch t (if any) instead of the ring input
        feed = x_micro[jnp.minimum(t, m - 1)]
        x = jnp.where(idx == 0, jnp.where(t < m, feed, state), state)
        y = stage_fn(sparams, x)
        # last stage emits microbatch t - (n - 1)
        out_i = t - (n - 1)
        outs = jnp.where(
            (idx == n - 1) & (out_i >= 0),
            outs.at[jnp.maximum(out_i, 0)].set(y), outs)
        state = lax.ppermute(y, axis_name, perm)
        return state, outs

    _, outs = lax.fori_loop(0, ticks, body, (state, outs))
    # only the last stage's copy is meaningful — broadcast along pp via a
    # masked psum so the result is replicated on every stage
    outs = lax.psum(jnp.where(idx == n - 1, outs, jnp.zeros_like(outs)),
                    axis_name)
    return outs


def pipeline_apply(stage_fn: Callable, stacked_params, x,
                   n_microbatch: int, *, mesh: Optional[DeviceMesh] = None,
                   axis_name: str = "pp", batch_axes=("dp", "fsdp")):
    """Run `x` [B, ...] through S pipelined stages.

    stage_fn(params_i, x) -> y with y.shape == x.shape (homogeneous
    stages — the transformer-block case).
    stacked_params: pytree with leading dim S == mesh.size('pp').
    """
    mesh = mesh or current_mesh()
    if mesh is None:
        raise MXNetError("pipeline_apply requires an active mesh")
    n = mesh.size(axis_name)
    first = jax.tree_util.tree_leaves(stacked_params)[0]
    if first.shape[0] != n:
        raise MXNetError(
            f"stacked stage dim {first.shape[0]} != mesh '{axis_name}' size {n}")
    if x.shape[0] % n_microbatch:
        raise MXNetError(
            f"batch {x.shape[0]} not divisible by n_microbatch {n_microbatch}")
    if n == 1:
        sparams = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
        return stage_fn(sparams, x)

    mb = x.reshape((n_microbatch, x.shape[0] // n_microbatch) + x.shape[1:])
    batch = tuple(a for a in batch_axes if a in mesh) or None
    x_spec = P(None, batch, *([None] * (x.ndim - 1)))
    p_spec = jax.tree_util.tree_map(
        lambda a: P(axis_name, *([None] * (a.ndim - 1))), stacked_params)
    fn = shard_map_unchecked(
        functools.partial(_pipeline_local, stage_fn=stage_fn,
                          axis_name=axis_name),
        mesh=mesh.mesh, in_specs=(p_spec, x_spec), out_specs=x_spec)
    out = fn(stacked_params, mb)
    return out.reshape(x.shape)
