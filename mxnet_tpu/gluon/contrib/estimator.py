"""Estimator: batteries-included fit() loop
(ref: python/mxnet/gluon/contrib/estimator/estimator.py).

Also the natural home of the TPU-fused train step: `fit` hybridizes the
net and drives record/backward/step per batch, with metric + checkpoint
handlers mirroring the reference's event-handler design.
"""
from __future__ import annotations

import time
from typing import List, Optional

from ... import autograd, metric as metric_mod
from ...base import MXNetError
from ...context import current_context
from ..trainer import Trainer
from ..utils import split_and_load

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "CheckpointHandler", "LoggingHandler"]


class TrainBegin:
    def train_begin(self, estimator):
        pass


class TrainEnd:
    def train_end(self, estimator):
        pass


class EpochBegin:
    def epoch_begin(self, estimator):
        pass


class EpochEnd:
    def epoch_end(self, estimator):
        pass


class BatchBegin:
    def batch_begin(self, estimator):
        pass


class BatchEnd:
    def batch_end(self, estimator):
        pass


class LoggingHandler(TrainBegin, EpochEnd, BatchEnd):
    def __init__(self, log_interval=50):
        self.log_interval = log_interval
        self._batch = 0
        self._tic = None

    def train_begin(self, estimator):
        self._tic = time.time()

    def batch_end(self, estimator):
        self._batch += 1
        if self._batch % self.log_interval == 0:
            msgs = [f"[batch {self._batch}]"]
            for m in estimator.train_metrics:
                name, value = m.get()
                msgs.append(f"{name}={value:.4f}")
            print(" ".join(msgs))

    def epoch_end(self, estimator):
        elapsed = time.time() - self._tic
        msgs = [f"[epoch {estimator.current_epoch}] time={elapsed:.1f}s"]
        for m in estimator.train_metrics:
            name, value = m.get()
            msgs.append(f"{name}={value:.4f}")
        print(" ".join(msgs))
        self._tic = time.time()


class CheckpointHandler(EpochEnd):
    def __init__(self, model_dir, model_prefix="model", save_best=False,
                 monitor=None):
        self.model_dir = model_dir
        self.model_prefix = model_prefix

    def epoch_end(self, estimator):
        import os

        os.makedirs(self.model_dir, exist_ok=True)
        path = os.path.join(
            self.model_dir,
            f"{self.model_prefix}-epoch{estimator.current_epoch}.params")
        estimator.net.save_parameters(path)


class Estimator:
    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None, val_metrics=None):
        self.net = net
        self.loss = loss
        self.train_metrics = [metric_mod.create(m) for m in
                              (train_metrics or ["accuracy"])]
        self.val_metrics = [metric_mod.create(m) for m in
                            (val_metrics or ["accuracy"])]
        self.context = context if isinstance(context, list) else \
            [context or current_context()]
        self.trainer = trainer or Trainer(
            net.collect_params(), "sgd", {"learning_rate": 0.01})
        self.current_epoch = 0

    def evaluate(self, val_data):
        for m in self.val_metrics:
            m.reset()
        for batch in val_data:
            data, label = batch[0], batch[1]
            xs = split_and_load(data, self.context)
            ys = split_and_load(label, self.context)
            for x, y in zip(xs, ys):
                out = self.net(x)
                for m in self.val_metrics:
                    m.update([y], [out])
        return [m.get() for m in self.val_metrics]

    def fit(self, train_data, val_data=None, epochs=1, event_handlers=None,
            batch_size=None):
        handlers = event_handlers or [LoggingHandler()]

        def fire(kind):
            for h in handlers:
                fn = getattr(h, kind, None)
                if fn is not None:
                    fn(self)

        fire("train_begin")
        for epoch in range(epochs):
            self.current_epoch = epoch
            for m in self.train_metrics:
                m.reset()
            fire("epoch_begin")
            for batch in train_data:
                data, label = batch[0], batch[1]
                bs = batch_size or data.shape[0]
                fire("batch_begin")
                xs = split_and_load(data, self.context)
                ys = split_and_load(label, self.context)
                losses = []
                outs = []
                with autograd.record():
                    for x, y in zip(xs, ys):
                        out = self.net(x)
                        losses.append(self.loss(out, y))
                        outs.append(out)
                for l in losses:
                    l.backward()
                self.trainer.step(bs)
                for y, out in zip(ys, outs):
                    for m in self.train_metrics:
                        m.update([y], [out])
                fire("batch_end")
            if val_data is not None:
                self.evaluate(val_data)
            fire("epoch_end")
        fire("train_end")
        return self
