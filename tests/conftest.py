"""Test harness config: force the CPU backend with 8 virtual devices.

Mirrors the reference's test strategy (SURVEY.md §4): unit tests run on a
host backend with numpy as oracle; multi-device behaviour is simulated via
XLA's virtual host devices; cpu↔tpu consistency has its own opt-in marker.

NOTE (container-specific): the axon TPU plugin is force-registered in every
python process by sitecustomize and sets jax_platforms programmatically, so
plain env vars are NOT enough — we must override via jax.config.update.
This also keeps tests runnable while the single-client TPU tunnel is busy.
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ.setdefault("MXNET_TEST_SEED", "0")

import jax

# MXNET_TEST_PLATFORM=tpu keeps the real accelerator visible for the
# opt-in on-device suite (tests/test_tpu_device.py, run via
# tools/run_tpu_tests.py); default pins the virtual-8-device CPU backend.
if os.environ.get("MXNET_TEST_PLATFORM") != "tpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from tier-1 (-m 'not slow'); the "
        "nightly lanes run these")
    # MXNET_SAN=1: importing mxnet_tpu (in every test) arms the
    # sanitizer; this plugin turns any violation into a failure of the
    # test it happened under and writes MXSAN.json at session end
    # (tools/run_nightly.py archives it).  Truthiness mirrors
    # base.get_env's bool parse WITHOUT importing the framework here
    # (that must stay lazy for sessions that don't use the sanitizer).
    _raw = os.environ.get("MXNET_SAN", "").strip().lower()
    if _raw not in ("", "0", "false", "no", "off"):
        import sys as _sys

        _tools = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools")
        if _tools not in _sys.path:
            _sys.path.insert(0, _tools)
        import mxsan_pytest

        config.pluginmanager.register(mxsan_pytest.MxsanPlugin(),
                                      "mxsan")


@pytest.fixture(autouse=True)
def _seed():
    """with_seed-style reproducibility (ref: tests/python/unittest/common.py)."""
    seed = int(os.environ["MXNET_TEST_SEED"])
    np.random.seed(seed)
    import mxnet_tpu as mx

    mx.random.seed(seed)
    yield
