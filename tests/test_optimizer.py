"""Optimizer update math vs numpy reference implementations.

Model: tests/python/unittest/test_optimizer.py in the reference (numpy
mirror of each update rule, compared step by step).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, optimizer
from mxnet_tpu.test_utils import assert_almost_equal


def _setup(shape=(4, 3), seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(*shape).astype("float32")
    g = rng.randn(*shape).astype("float32")
    return w, g


def test_sgd_plain_and_wd():
    w, g = _setup()
    opt = optimizer.create("sgd", learning_rate=0.1, wd=0.01)
    weight, grad = nd.array(w), nd.array(g)
    state = opt.create_state(0, weight)
    opt.update(0, weight, grad, state)
    ref = w - 0.1 * (g + 0.01 * w)
    assert_almost_equal(weight, ref, rtol=1e-5, atol=1e-6)


def test_sgd_momentum_multiple_steps():
    w, g = _setup()
    opt = optimizer.create("sgd", learning_rate=0.1, momentum=0.9, wd=0.0)
    weight = nd.array(w)
    state = opt.create_state(0, weight)
    mom = np.zeros_like(w)
    cur = w.copy()
    for step in range(3):
        gi = g * (step + 1)
        opt.update(0, weight, nd.array(gi), state)
        mom = 0.9 * mom - 0.1 * gi
        cur = cur + mom
        assert_almost_equal(weight, cur, rtol=1e-4, atol=1e-5)


def test_sgd_rescale_and_clip():
    w, g = _setup()
    opt = optimizer.create("sgd", learning_rate=0.1, rescale_grad=0.5,
                           clip_gradient=0.2)
    weight = nd.array(w)
    opt.update(0, weight, nd.array(g), opt.create_state(0, weight))
    ref = w - 0.1 * np.clip(g * 0.5, -0.2, 0.2)
    assert_almost_equal(weight, ref, rtol=1e-5, atol=1e-6)


def test_adam():
    w, g = _setup()
    opt = optimizer.create("adam", learning_rate=0.01)
    weight = nd.array(w)
    state = opt.create_state(0, weight)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    cur = w.copy()
    for t in range(1, 4):
        opt.update(0, weight, nd.array(g), state)
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        lr_t = 0.01 * np.sqrt(1 - 0.999 ** t) / (1 - 0.9 ** t)
        cur = cur - lr_t * m / (np.sqrt(v) + 1e-8)
        assert_almost_equal(weight, cur, rtol=1e-4, atol=1e-5)


def test_rmsprop():
    w, g = _setup()
    opt = optimizer.create("rmsprop", learning_rate=0.01, gamma1=0.9)
    weight = nd.array(w)
    state = opt.create_state(0, weight)
    n = np.zeros_like(w)
    opt.update(0, weight, nd.array(g), state)
    n = 0.9 * n + 0.1 * g * g
    ref = w - 0.01 * g / np.sqrt(n + 1e-8)
    assert_almost_equal(weight, ref, rtol=1e-4, atol=1e-4)


def test_adagrad():
    w, g = _setup()
    opt = optimizer.create("adagrad", learning_rate=0.1)
    weight = nd.array(w)
    state = opt.create_state(0, weight)
    opt.update(0, weight, nd.array(g), state)
    hist = g * g
    ref = w - 0.1 * g / np.sqrt(hist + 1e-7)
    assert_almost_equal(weight, ref, rtol=1e-4, atol=1e-5)


def test_signum():
    w, g = _setup()
    opt = optimizer.create("signum", learning_rate=0.1, momentum=0.9)
    weight = nd.array(w)
    state = opt.create_state(0, weight)
    opt.update(0, weight, nd.array(g), state)
    # reference signum: mom = beta*mom + (1-beta)*rescaled_grad; w -= lr*sign(mom)
    mom_ref = 0.9 * np.zeros_like(w) + 0.1 * g
    ref = w - 0.1 * np.sign(mom_ref)
    assert_almost_equal(weight, ref, rtol=1e-5, atol=1e-6)


def test_multi_precision_sgd():
    w, g = _setup()
    opt = optimizer.create("sgd", learning_rate=0.1, momentum=0.9,
                           multi_precision=True)
    weight = nd.array(w).astype("float16")
    grad = nd.array(g).astype("float16")
    state = opt.create_state_multi_precision(0, weight)
    opt.update_multi_precision(0, weight, grad, state)
    assert str(weight.data.dtype) == "float16"
    mom = -0.1 * g.astype(np.float16).astype(np.float32)
    ref = (w + mom).astype("float16")
    assert_almost_equal(weight.asnumpy().astype("float32"),
                        ref.astype("float32"), rtol=1e-2, atol=1e-2)


def test_lr_scheduler_integration():
    from mxnet_tpu import lr_scheduler

    sched = lr_scheduler.FactorScheduler(step=2, factor=0.5, base_lr=0.4)
    opt = optimizer.create("sgd", learning_rate=0.4, lr_scheduler=sched)
    w, g = _setup()
    weight = nd.array(w)
    lrs = []
    for _ in range(5):
        opt.update(0, weight, nd.array(g), None)
        lrs.append(opt.learning_rate)
    assert lrs[0] == pytest.approx(0.4)
    assert lrs[-1] < 0.4


def test_updater_serialization():
    w, g = _setup()
    opt = optimizer.create("adam", learning_rate=0.01)
    updater = optimizer.get_updater(opt)
    updater(0, nd.array(g), nd.array(w))
    states = updater.get_states()
    opt2 = optimizer.create("adam", learning_rate=0.01)
    updater2 = optimizer.get_updater(opt2)
    updater2.set_states(states)
    # both updaters now produce identical next steps
    w1, w2 = nd.array(w), nd.array(w)
    updater(1, nd.array(g), w1)
    updater2(1, nd.array(g), w2)
    assert_almost_equal(w1, w2, rtol=1e-6, atol=1e-7)
