"""Data iterator tests (model: tests/python/unittest/test_io.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.io import (CSVIter, DataBatch, DataDesc, NDArrayIter,
                          PrefetchingIter, ResizeIter)
from mxnet_tpu.test_utils import assert_almost_equal


def test_ndarrayiter_basic():
    data = np.arange(40, dtype="float32").reshape(10, 4)
    label = np.arange(10, dtype="float32")
    it = NDArrayIter(data, label, batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (5, 4)
    assert_almost_equal(batches[0].data[0], data[:5])
    assert_almost_equal(batches[1].label[0], label[5:])
    # reset + reiterate
    it.reset()
    assert len(list(it)) == 2


def test_ndarrayiter_pad_and_discard():
    data = np.arange(14, dtype="float32").reshape(7, 2)
    it = NDArrayIter(data, None, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 2
    assert batches[1].pad == 1
    assert batches[1].data[0].shape == (4, 2)  # padded by wrap-around
    it = NDArrayIter(data, None, batch_size=4, last_batch_handle="discard")
    assert len(list(it)) == 1


def test_ndarrayiter_shuffle_covers_all():
    data = np.arange(16, dtype="float32").reshape(16, 1)
    it = NDArrayIter(data, None, batch_size=4, shuffle=True)
    seen = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
    assert sorted(seen.tolist()) == list(range(16))


def test_ndarrayiter_dict_input():
    it = NDArrayIter({"a": np.zeros((6, 2), "float32"),
                      "b": np.ones((6, 3), "float32")}, None, batch_size=3)
    names = sorted(d.name for d in it.provide_data)
    assert names == ["a", "b"]


def test_provide_data_descs():
    it = NDArrayIter(np.zeros((8, 3, 4, 4), "float32"),
                     np.zeros(8, "float32"), batch_size=2)
    d = it.provide_data[0]
    assert isinstance(d, DataDesc)
    assert d.shape == (2, 3, 4, 4)
    assert it.provide_label[0].name == "softmax_label"


def test_csviter(tmp_path):
    data = np.random.rand(10, 6).astype("float32")
    labels = np.arange(10, dtype="float32")
    data_csv = str(tmp_path / "data.csv")
    label_csv = str(tmp_path / "label.csv")
    np.savetxt(data_csv, data, delimiter=",")
    np.savetxt(label_csv, labels, delimiter=",")
    it = CSVIter(data_csv=data_csv, data_shape=(6,), label_csv=label_csv,
                 batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    assert_almost_equal(batches[0].data[0], data[:5], rtol=1e-5, atol=1e-6)


def test_resizeiter():
    data = np.zeros((8, 2), "float32")
    base = NDArrayIter(data, None, batch_size=4)
    it = ResizeIter(base, size=5)
    assert len(list(it)) == 5  # wraps around the underlying 2 batches
    it.reset()
    assert len(list(it)) == 5


def test_prefetching_iter():
    data = np.arange(24, dtype="float32").reshape(12, 2)
    base = NDArrayIter(data, None, batch_size=4)
    it = PrefetchingIter(base)
    batches = list(it)
    assert len(batches) == 3
    assert_almost_equal(batches[0].data[0], data[:4])
    it.reset()
    assert len(list(it)) == 3


def test_image_record_iter(tmp_path):
    cv2 = pytest.importorskip("cv2", reason="needs an image encoder")


def test_image_record_iter_synthetic(tmp_path):
    # pack synthetic images with the recordio writer + image.imencode
    from mxnet_tpu import recordio
    from mxnet_tpu import image as img_mod
    from mxnet_tpu.io import ImageRecordIter

    try:
        enc = img_mod.imencode(np.zeros((8, 8, 3), np.uint8))
    except Exception:
        pytest.skip("no image encoder available in this environment")
    path = str(tmp_path / "data.rec")
    rec = recordio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    for i in range(6):
        arr = rng.randint(0, 255, size=(10, 12, 3), dtype=np.uint8)
        packed = recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), arr, quality=90)
        rec.write(packed)
    rec.close()

    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                         batch_size=3)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (3, 3, 8, 8)
    assert batches[0].label[0].shape == (3,)


def test_misc_api_modules():
    """engine/runtime/visualization/name/attribute parity surfaces."""
    import mxnet_tpu.engine as engine
    import mxnet_tpu.runtime as runtime

    with engine.bulk(10):
        y = mx.nd.ones((2, 2)) + 1
    assert y.asnumpy().sum() == 8
    prev = engine.set_bulk_size(20)
    engine.set_bulk_size(prev)

    feats = runtime.Features()
    assert feats.is_enabled("JAX")
    assert any(f.name == "TPU" for f in runtime.feature_list())

    from mxnet_tpu import symbol as sym
    from mxnet_tpu.name import Prefix

    with Prefix("mynet_"):
        s = sym.FullyConnected(sym.var("data"), num_hidden=3)
    assert s.name.startswith("mynet_")

    from mxnet_tpu.visualization import plot_network, print_summary

    net = sym.FullyConnected(sym.var("data"), num_hidden=3, name="fc")
    dot = plot_network(net)
    assert "fc" in str(dot)
    print_summary(net, shape={"data": (2, 5)})

    from mxnet_tpu.attribute import AttrScope

    with AttrScope(ctx_group="dev1") as scope:
        assert scope.get(None) == {"ctx_group": "dev1"}


def test_monitor_with_module():
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.module import Module
    from mxnet_tpu.monitor import Monitor

    x = np.random.randn(16, 5).astype("float32")
    y = np.random.randint(0, 3, 16).astype("float32")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(sym.var("data"), num_hidden=3, name="fc"),
        name="softmax")
    mod = Module(net, context=mx.cpu())
    it = NDArrayIter(x, y, batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mon = Monitor(interval=1, pattern=".*weight.*")
    mod.install_monitor(mon)
    mon.tic()
    batch = next(iter(it))
    mod.forward(batch, is_train=True)
    stats = mon.toc()
    assert any("fc_weight" in k for (_, k, _) in stats)


def test_recordio_chunked_large_records(tmp_path):
    """Regression: records longer than the 29-bit length field must be
    chunk-chained (cflag 1/2/3), not silently truncated.  A small
    _max_chunk exercises the same code path without 512MB fixtures."""
    from mxnet_tpu import recordio

    path = str(tmp_path / "chunked.rec")
    w = recordio.MXRecordIO(path, "w")
    w._max_chunk = 64
    # payloads longer than the chunk size, incl. embedded magic bytes
    magic = (0x3ED7230A).to_bytes(4, "little")
    payloads = [b"x" * 200, magic * 50 + b"tail", b"short", b"y" * 64 * 3]
    for p in payloads:
        w.write(p)
    w.close()

    r = recordio.MXRecordIO(path, "r")
    for p in payloads:
        assert r.read() == p
    assert r.read() is None
    r.close()

    # the native reader joins the same chunk chain
    from mxnet_tpu import lib
    if lib.available():
        nr = lib.NativeRecordReader(path)
        for p in payloads:
            assert nr.read() == p
        assert nr.read() is None
        nr.close()


def test_recordio_truncated_chunk_chain_raises(tmp_path):
    """EOF mid-chunk-chain must fail loud, not hand back a partial record."""
    from mxnet_tpu import lib, recordio
    from mxnet_tpu.base import MXNetError

    path = str(tmp_path / "trunc.rec")
    w = recordio.MXRecordIO(path, "w")
    w._max_chunk = 16
    w.write(b"z" * 50)  # 4 chunks: cflag 1,2,2,3
    w.close()
    # cut the file after the second chunk (2 * (8 + 16) bytes)
    with open(path, "r+b") as f:
        f.truncate(48)
    r = recordio.MXRecordIO(path, "r")
    with pytest.raises(MXNetError, match="truncated"):
        r.read()
    r.close()
    if lib.available():
        nr = lib.NativeRecordReader(path)
        with pytest.raises(MXNetError, match="truncated"):
            nr.read()
        nr.close()


def test_recordio_truncated_final_chunk_payload(tmp_path):
    """Truncation inside a chunk PAYLOAD (not between chunks) must also
    raise, matching the native reader."""
    from mxnet_tpu import recordio
    from mxnet_tpu.base import MXNetError
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    w._max_chunk = 16
    w.write(b"q" * 50)
    w.close()
    import os
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 3)
    r = recordio.MXRecordIO(path, "r")
    import pytest as _pytest
    with _pytest.raises(MXNetError, match="truncated"):
        r.read()
    r.close()


# ---------------------------------------------------------------------------
# tools/: parse_log.py + bandwidth.py (ref: tools/parse_log.py,
# tools/bandwidth/)
# ---------------------------------------------------------------------------

def test_parse_log_tool(tmp_path):
    import subprocess
    import sys

    log = tmp_path / "train.log"
    log.write_text(
        "INFO Epoch[0] Batch [50]\tSpeed: 2461.16 samples/sec\taccuracy=0.5\n"
        "INFO Epoch[0] Batch [100]\tSpeed: 2400.00 samples/sec\taccuracy=0.6\n"
        "INFO Epoch[0] Train-accuracy=0.612000\n"
        "INFO Epoch[0] Validation-accuracy=0.587000\n"
        "INFO Epoch[0] Time cost=12.345\n"
        "INFO Epoch[1] Train-accuracy=0.701000\n"
        "INFO Epoch[1] Time cost=11.000\n")
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "parse_log.py")
    r = subprocess.run([sys.executable, tool, str(log), "--format", "csv"],
                      capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert lines[0] == "epoch,speed,time-s,train-accuracy,val-accuracy"
    assert lines[1].startswith("0,2430.58,12.345,0.612,0.587")
    assert lines[2].startswith("1,,11,0.701,")
    r = subprocess.run([sys.executable, tool, str(log)],
                      capture_output=True, text=True, timeout=60)
    assert r.returncode == 0 and "| epoch" in r.stdout


def test_bandwidth_tool_mesh():
    """In-graph allreduce bandwidth across the virtual 8-device mesh."""
    import subprocess
    import sys

    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "bandwidth.py")
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [sys.executable, tool, "--sizes", "1", "--iters", "2"],
        capture_output=True, text=True, timeout=240, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "mesh-psum x8" in r.stdout


# ---------------------------------------------------------------------------
# LibSVMIter (ref: src/io/iter_libsvm.cc) + ImageIter/ImageDetIter
# (ref: python/mxnet/image/{image,detection}.py)
# ---------------------------------------------------------------------------

def test_libsvm_iter(tmp_path):
    f = tmp_path / "train.libsvm"
    f.write_text("1 0:1.5 3:2.0\n"
                 "0 1:1.0\n"
                 "1 2:0.5 3:0.5\n")
    it = mx.io.LibSVMIter(data_libsvm=str(f), data_shape=(4,),
                          batch_size=2)
    b1 = it.next()
    assert b1.data[0].stype == "csr"
    np.testing.assert_allclose(
        b1.data[0].todense().asnumpy(),
        [[1.5, 0, 0, 2.0], [0, 1.0, 0, 0]])
    np.testing.assert_allclose(b1.label[0].asnumpy(), [1, 0])
    b2 = it.next()  # wraps to fill the last batch
    assert b2.pad == 1
    np.testing.assert_allclose(
        b2.data[0].todense().asnumpy()[0], [0, 0, 0.5, 0.5])
    with pytest.raises(StopIteration):
        it.next()
    it.reset()
    assert it.next().pad == 0
    # sparse dot consumes the batch directly
    w = mx.nd.ones((4, 3))
    out = mx.nd.sparse.dot(b1.data[0], w)
    np.testing.assert_allclose(out.asnumpy()[0], [3.5, 3.5, 3.5])


def _write_img_rec(tmp_path, n=6, label_width=1, det=False):
    from mxnet_tpu import recordio as rio
    from mxnet_tpu.image import imencode

    path = str(tmp_path / "data.rec")
    rec = rio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = (rng.rand(12, 10, 3) * 255).astype(np.uint8)
        if det:
            # packed object labels: header(2) + one or two boxes of width 5
            nobj = 1 + (i % 2)
            objs = []
            for b in range(nobj):
                objs += [float(i % 3), 0.1, 0.1, 0.6, 0.7]
            label = np.asarray([2, 5] + objs, np.float32)
        else:
            label = float(i % 3) if label_width == 1 else \
                np.arange(label_width, dtype=np.float32)
        h = rio.IRHeader(0, label, i, 0)
        rec.write(rio.pack_img(h, img, quality=90))
    rec.close()
    return path


def test_image_iter_rec(tmp_path):
    try:
        from mxnet_tpu.image import imencode  # noqa: F401
        _ = imencode(np.zeros((4, 4, 3), np.uint8))
    except Exception:
        pytest.skip("no image encoder available")
    from mxnet_tpu.image import CreateAugmenter, ImageIter

    path = _write_img_rec(tmp_path)
    it = ImageIter(batch_size=4, data_shape=(3, 8, 8),
                   path_imgrec=path,
                   aug_list=CreateAugmenter((3, 8, 8)))
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 8, 8)
    assert batch.label[0].shape == (4,)
    np.testing.assert_allclose(batch.label[0].asnumpy(), [0, 1, 2, 0])
    b2 = it.next()  # 2 remaining + 2 pad
    assert b2.pad == 2
    it.reset()
    assert it.next().pad == 0


def test_image_iter_imglist(tmp_path):
    try:
        from mxnet_tpu.image import imencode
        _ = imencode(np.zeros((4, 4, 3), np.uint8))
    except Exception:
        pytest.skip("no image encoder available")
    from mxnet_tpu.image import ImageIter

    rng = np.random.RandomState(1)
    names = []
    for i in range(3):
        img = (rng.rand(9, 9, 3) * 255).astype(np.uint8)
        from mxnet_tpu.image import imencode

        (tmp_path / f"im{i}.jpg").write_bytes(imencode(img))
        names.append(f"im{i}.jpg")
    lst = tmp_path / "train.lst"
    lst.write_text("".join(f"{i}\t{float(i)}\t{n}\n"
                           for i, n in enumerate(names)))
    it = ImageIter(batch_size=3, data_shape=(3, 8, 8),
                   path_imglist=str(lst), path_root=str(tmp_path))
    b = it.next()
    assert b.data[0].shape == (3, 3, 8, 8)
    np.testing.assert_allclose(b.label[0].asnumpy(), [0, 1, 2])


def test_image_det_iter(tmp_path):
    try:
        from mxnet_tpu.image import imencode
        _ = imencode(np.zeros((4, 4, 3), np.uint8))
    except Exception:
        pytest.skip("no image encoder available")
    from mxnet_tpu.image import ImageDetIter

    path = _write_img_rec(tmp_path, det=True)
    it = ImageDetIter(batch_size=3, data_shape=(3, 8, 8),
                      path_imgrec=path)
    b = it.next()
    assert b.data[0].shape == (3, 3, 8, 8)
    lab = b.label[0].asnumpy()
    assert lab.shape == (3, 2, 5)  # max 2 objects, width 5
    # sample 0 has one object, row 1 padded with -1
    np.testing.assert_allclose(lab[0, 0], [0, 0.1, 0.1, 0.6, 0.7],
                               rtol=1e-5)
    assert (lab[0, 1] == -1).all()
    # sample 1 has two objects
    assert (lab[1, 1] != -1).any()


def test_det_augmenters_keep_boxes_aligned(tmp_path):
    """DetHorizontalFlipAug mirrors boxes with the image; force-resize
    leaves relative coords invariant (plain Augmenters are rejected)."""
    from mxnet_tpu.image import (CreateDetAugmenter, DetHorizontalFlipAug,
                                 DetForceResizeAug)
    from mxnet_tpu.ndarray import array as nd_array

    img = np.zeros((10, 20, 3), np.float32)
    img[:, :10] = 1.0  # left half bright
    boxes = np.array([[0.0, 0.1, 0.2, 0.4, 0.8]], np.float32)
    flip = DetHorizontalFlipAug(p=1.1)  # always flip
    out, fboxes = flip(nd_array(img), boxes)
    # image mirrored: bright half now on the right
    assert out.asnumpy()[0, -1, 0] == 1.0 and out.asnumpy()[0, 0, 0] == 0.0
    np.testing.assert_allclose(fboxes[0], [0.0, 0.6, 0.2, 0.9, 0.8],
                               rtol=1e-6)
    rs = DetForceResizeAug((8, 8))
    out2, rboxes = rs(nd_array(img), boxes)
    assert out2.shape == (8, 8, 3)
    np.testing.assert_allclose(rboxes, boxes)  # relative coords invariant
    import pytest as _pytest

    from mxnet_tpu.image import CenterCropAug, ImageDetIter
    with _pytest.raises(Exception, match="DetAugmenter"):
        ImageDetIter(batch_size=1, data_shape=(3, 8, 8),
                     path_imgrec="/nonexistent.rec",
                     aug_list=[CenterCropAug((8, 8))])
