"""Op parity batch: sample_*/pdf_* families, regression heads, AMP,
multi-tensor optimizer ops, LAMB/LARS, im2col/col2im, Correlation,
DeformableConvolution, fft, misc unary (ref: sample_op.cc, pdf_op.cc,
regression_output-inl.h, optimizer_op.cc, lamb.cc, correlation.cc,
deformable_convolution.cc, fft-inl.h)."""
import numpy as np
import pytest
import scipy.stats as sstats

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_sample_family_shapes_and_stats():
    mx.random.seed(0)
    mu = nd.array(np.array([0.0, 10.0], np.float32))
    sd = nd.array(np.array([1.0, 0.1], np.float32))
    s = nd.sample_normal(mu, sd, shape=(2000,))
    assert s.shape == (2, 2000)
    a = s.asnumpy()
    assert abs(a[0].mean()) < 0.15 and abs(a[1].mean() - 10) < 0.15
    assert abs(a[0].std() - 1) < 0.1 and a[1].std() < 0.2

    lam = nd.array(np.array([2.0, 20.0], np.float32))
    p = nd.sample_poisson(lam, shape=(3000,)).asnumpy()
    assert abs(p[0].mean() - 2) < 0.3 and abs(p[1].mean() - 20) < 1.0

    al = nd.array(np.array([3.0], np.float32))
    be = nd.array(np.array([2.0], np.float32))
    g = nd.sample_gamma(al, be, shape=(4000,)).asnumpy()
    assert abs(g.mean() - 6.0) < 0.5  # mean = alpha * beta (scale)

    e = nd.sample_exponential(nd.array(np.array([4.0], np.float32)),
                              shape=(4000,)).asnumpy()
    assert abs(e.mean() - 0.25) < 0.05

    nb = nd.sample_negative_binomial(
        nd.array(np.array([5.0], np.float32)),
        nd.array(np.array([0.5], np.float32)), shape=(4000,)).asnumpy()
    assert abs(nb.mean() - 5.0) < 0.6  # mean = k(1-p)/p


def test_pdf_family_matches_scipy():
    x = np.array([[0.5, 1.5, 2.5]], np.float32)
    mu = np.array([1.0], np.float32)
    sd = np.array([0.5], np.float32)
    out = nd.random_pdf_normal(nd.array(x), nd.array(mu),
                               nd.array(sd)).asnumpy()
    np.testing.assert_allclose(out[0], sstats.norm.pdf(x[0], 1.0, 0.5),
                               rtol=1e-4)
    a = np.array([2.0], np.float32)
    b = np.array([3.0], np.float32)
    out = nd.random_pdf_gamma(nd.array(x), nd.array(a),
                              nd.array(b)).asnumpy()
    np.testing.assert_allclose(out[0],
                               sstats.gamma.pdf(x[0], 2.0, scale=3.0),
                               rtol=1e-4)
    k = np.array([4.0], np.float32)
    lam = np.array([2.0], np.float32)
    xs = np.array([[0.0, 1.0, 3.0]], np.float32)
    out = nd.random_pdf_poisson(nd.array(xs), nd.array(lam),
                                is_log=True).asnumpy()
    np.testing.assert_allclose(out[0],
                               sstats.poisson.logpmf(xs[0], 2.0),
                               rtol=1e-4)


def test_uniform_normal_bare_aliases():
    mx.random.seed(1)
    u = nd.uniform(low=2.0, high=3.0, shape=(500,))
    a = u.asnumpy()
    assert a.min() >= 2.0 and a.max() <= 3.0
    n = nd.normal(loc=-1.0, scale=0.5, shape=(500,))
    assert abs(n.asnumpy().mean() + 1.0) < 0.15


def test_regression_heads_backward_semantics():
    # batch=2, num_output=3 (distinct!): reference scales the backward
    # by grad_scale/num_output (regression_output-inl.h), NOT 1/batch
    rng = np.random.RandomState(0)
    xv = rng.randn(2, 3).astype(np.float32)
    yv = rng.randn(2, 3).astype(np.float32)
    x = nd.array(xv)
    y = nd.array(yv)
    x.attach_grad()
    with mx.autograd.record():
        out = nd.LinearRegressionOutput(x, y)
    out.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), (xv - yv) / 3,
                               rtol=1e-6)
    x.grad[:] = 0
    with mx.autograd.record():
        out = nd.MAERegressionOutput(x, y)
    out.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.sign(xv - yv) / 3)
    x.grad[:] = 0
    with mx.autograd.record():
        out = nd.logistic_regression_output(x, y)  # snake alias
    out.backward()
    sig = 1 / (1 + np.exp(-xv))
    np.testing.assert_allclose(out.asnumpy(), sig, rtol=1e-5)
    np.testing.assert_allclose(x.grad.asnumpy(), (sig - yv) / 3,
                               rtol=1e-5)
    # consecutive-capitals snake alias (MAE -> mae)
    assert nd.mae_regression_output is not None


def test_svm_output_hinge_gradient():
    scores = nd.array(np.array([[2.0, 1.5, -1.0]], np.float32))
    label = nd.array(np.array([0.0], np.float32))
    scores.attach_grad()
    with mx.autograd.record():
        out = nd.SVMOutput(scores, label, margin=1.0, use_linear=True)
    out.backward()
    np.testing.assert_allclose(out.asnumpy(), scores.asnumpy())
    # class 1 violates (2.0 - 1.5 < 1), class 2 does not (2.0-(-1) > 1)
    np.testing.assert_allclose(scores.grad.asnumpy(), [[-1.0, 1.0, 0.0]])


def test_misc_unary_and_amp():
    x = nd.array(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    np.testing.assert_allclose(nd.trace(x).asnumpy(), 5.0)
    np.testing.assert_allclose(
        nd.hard_sigmoid(nd.array(np.array([-10.0, 0.0, 10.0], np.float32)))
        .asnumpy(), [0.0, 0.5, 1.0])
    h = nd.hard_swish(nd.array(np.array([-4.0, 0.0, 4.0], np.float32)))
    np.testing.assert_allclose(h.asnumpy(), [0.0, 0.0, 4.0])
    m = nd.mish(nd.array(np.array([0.0], np.float32)))
    np.testing.assert_allclose(m.asnumpy(), [0.0], atol=1e-6)
    d = nd.digamma(nd.array(np.array([1.0], np.float32)))
    np.testing.assert_allclose(d.asnumpy(), [-0.5772157], rtol=1e-4)
    assert float(nd.all_finite(x).asnumpy()[0]) == 1.0
    bad = nd.array(np.array([np.inf], np.float32))
    assert float(nd.all_finite(bad).asnumpy()[0]) == 0.0
    oks = nd.multi_all_finite(x, bad, num_arrays=2)
    assert float(oks.asnumpy()[0]) == 0.0
    c = nd.amp_cast(x, dtype="float16")
    assert "bfloat16" in str(c.dtype)
    a, b = nd.amp_multicast(c, x, num_outputs=2)
    assert a.dtype == np.float32 and b.dtype == np.float32


def test_ravel_unravel_roundtrip():
    idx = nd.array(np.array([[0, 1, 2], [3, 2, 1]], np.float32))
    flat = nd.ravel_multi_index(idx, shape=(4, 5))
    np.testing.assert_allclose(flat.asnumpy(), [3, 7, 11])
    back = nd.unravel_index(flat, shape=(4, 5))
    np.testing.assert_allclose(back.asnumpy(), idx.asnumpy())


def test_fft_ifft_interleaved_roundtrip():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 8).astype(np.float32)
    spec = nd.fft(nd.array(x))
    assert spec.shape == (2, 16)
    ref = np.fft.fft(x, axis=-1)
    np.testing.assert_allclose(spec.asnumpy()[:, 0::2], ref.real,
                               atol=1e-4)
    np.testing.assert_allclose(spec.asnumpy()[:, 1::2], ref.imag,
                               atol=1e-4)
    back = nd.ifft(spec) / 8  # reference convention: unnormalized
    np.testing.assert_allclose(back.asnumpy(), x, atol=1e-4)


def test_multi_sgd_and_mp_updates():
    w1 = nd.array(np.ones((3,), np.float32))
    w2 = nd.array(np.full((2,), 2.0, np.float32))
    g1 = nd.array(np.full((3,), 0.5, np.float32))
    g2 = nd.array(np.full((2,), 1.0, np.float32))
    # reference layout: interleaved (w0, g0, w1, g1) — optimizer_op.cc
    o1, o2 = nd.multi_sgd_update(w1, g1, w2, g2, lrs=(0.1, 0.2),
                                 wds=(0.0, 0.0), num_weights=2)
    np.testing.assert_allclose(o1.asnumpy(), 0.95 * np.ones(3))
    np.testing.assert_allclose(o2.asnumpy(), 1.8 * np.ones(2))
    ss1, ss2 = nd.multi_sum_sq(w1, w2, num_arrays=2)
    np.testing.assert_allclose(ss1.asnumpy(), [3.0])
    np.testing.assert_allclose(ss2.asnumpy(), [8.0])
    lrs = nd.array(np.array([0.1, 0.2], np.float32))
    wds = nd.array(np.array([0.0, 0.0], np.float32))
    p1, p2 = nd.preloaded_multi_sgd_update(w1, g1, w2, g2, lrs, wds,
                                           num_weights=2)
    np.testing.assert_allclose(p1.asnumpy(), o1.asnumpy())
    np.testing.assert_allclose(p2.asnumpy(), o2.asnumpy())


def test_lamb_and_lars():
    w = nd.array(np.full((4,), 2.0, np.float32))
    g = nd.array(np.full((4,), 0.1, np.float32))
    m = nd.zeros((4,))
    v = nd.zeros((4,))
    d, nm, nv = nd.lamb_update_phase1(w, g, m, v, t=1, wd=0.01)
    assert np.isfinite(d.asnumpy()).all()
    r1 = nd.array(np.array([np.linalg.norm(w.asnumpy())], np.float32))
    r2 = nd.array(np.array([np.linalg.norm(d.asnumpy())], np.float32))
    w2 = nd.lamb_update_phase2(w, d, r1, r2, lr=0.01)
    assert (w2.asnumpy() < w.asnumpy()).all()
    # LARS: local lr = eta*||w|| / (||g|| + wd*||w|| + eps)
    lrs = nd.array(np.array([1.0], np.float32))
    wss = nd.array(np.array([16.0], np.float32))
    gss = nd.array(np.array([0.04], np.float32))
    wds = nd.array(np.array([0.0], np.float32))
    out = nd.multi_lars(lrs, wss, gss, wds, eta=0.1, eps=0.0)
    np.testing.assert_allclose(out.asnumpy(), [0.1 * 4.0 / 0.2],
                               rtol=1e-5)


def test_im2col_col2im():
    rng = np.random.RandomState(0)
    x = rng.randn(1, 2, 4, 4).astype(np.float32)
    cols = nd.im2col(nd.array(x), kernel=(2, 2), stride=(2, 2))
    assert cols.shape == (1, 8, 4)  # C*k*k=8 rows, 4 patches
    # patch (0,0) equals the first 2x2 block flattened channel-major
    np.testing.assert_allclose(
        cols.asnumpy()[0, :, 0],
        x[0, :, :2, :2].reshape(2, -1).ravel(), rtol=1e-6)
    img = nd.col2im(cols, output_size=(4, 4), kernel=(2, 2),
                    stride=(2, 2))
    np.testing.assert_allclose(img.asnumpy(), x, rtol=1e-6)  # no overlap


def test_correlation_identity_peak():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 4, 6, 6).astype(np.float32)
    out = nd.Correlation(nd.array(x), nd.array(x), max_displacement=1,
                         pad_size=1)
    assert out.shape == (1, 9, 6, 6)  # 6 + 2*1 - 2*1
    a = out.asnumpy()[0]
    # zero-displacement channel (index 4) is the exact self-correlation
    np.testing.assert_allclose(a[4], (x[0] * x[0]).mean(axis=0),
                               rtol=1e-5)
    # displacement (-1, 0) channel (index 1) matches a hand shift
    np.testing.assert_allclose(
        a[1, 1:, :], (x[0, :, 1:, :] * x[0, :, :-1, :]).mean(axis=0),
        rtol=1e-5)


def test_deformable_convolution_zero_offset_equals_conv():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 7, 7).astype(np.float32)
    w = rng.randn(5, 3, 3, 3).astype(np.float32)
    off = np.zeros((2, 18, 7, 7), np.float32)
    ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         num_filter=5, pad=(1, 1), no_bias=True)
    out = nd.DeformableConvolution(nd.array(x), nd.array(off),
                                   nd.array(w), kernel=(3, 3),
                                   num_filter=5, pad=(1, 1), no_bias=True)
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), rtol=1e-3,
                               atol=1e-3)


def test_deformable_convolution_gradient():
    rng = np.random.RandomState(3)
    x = nd.array(rng.randn(1, 2, 5, 5).astype(np.float32))
    off = nd.array((rng.randn(1, 8, 4, 4) * 0.1).astype(np.float32))
    w = nd.array(rng.randn(3, 2, 2, 2).astype(np.float32))
    for t in (x, off, w):
        t.attach_grad()
    with mx.autograd.record():
        out = nd.DeformableConvolution(x, off, w, kernel=(2, 2),
                                       num_filter=3, no_bias=True)
        loss = (out * out).sum()
    loss.backward()
    for t in (x, off, w):
        g = t.grad.asnumpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_correlation_reference_geometry():
    x = nd.array(np.random.RandomState(4).randn(1, 2, 6, 6)
                 .astype(np.float32))
    out = nd.Correlation(x, x, max_displacement=1, pad_size=0)
    assert out.shape == (1, 9, 4, 4)  # H + 2*pad - 2*d
    out = nd.Correlation(x, x, max_displacement=1, pad_size=1)
    assert out.shape == (1, 9, 6, 6)
    with pytest.raises(mx.MXNetError, match="non-positive"):
        nd.Correlation(x, x, max_displacement=4, pad_size=0)


def test_pdf_ops_are_differentiable():
    mu = nd.array(np.array([1.0], np.float32))
    sd = nd.array(np.array([0.5], np.float32))
    xs = nd.array(np.array([[0.5, 1.5]], np.float32))
    mu.attach_grad()
    sd.attach_grad()
    with mx.autograd.record():
        ll = nd.random_pdf_normal(xs, mu, sd, is_log=True).sum()
    ll.backward()
    # d/dmu sum logN(x; mu, sd) = sum (x-mu)/sd^2 = (-0.5 + 0.5)/0.25 = 0
    np.testing.assert_allclose(mu.grad.asnumpy(), [0.0], atol=1e-5)
    assert abs(float(sd.grad.asnumpy()[0])) > 0


def test_optional_array_input_by_keyword_routes_to_inputs():
    """state=NDArray passed by keyword must be an array input, never a
    frozen attr (registry keyword->positional routing)."""
    rng = np.random.RandomState(5)
    x = nd.array(rng.randn(4, 2, 3).astype(np.float32))
    P = __import__("mxnet_tpu.ops.rnn", fromlist=["rnn_param_size"]) \
        .rnn_param_size("lstm", 3, 5, 1, False)
    params = nd.array((rng.randn(P) * 0.1).astype(np.float32))
    h0 = nd.array(np.zeros((1, 2, 5), np.float32))
    c0 = nd.array(np.zeros((1, 2, 5), np.float32))
    out_kw = nd.RNN(x, params, state=h0, state_cell=c0, state_size=5,
                    mode="lstm", state_outputs=False)
    out_pos = nd.RNN(x, params, h0, c0, state_size=5, mode="lstm",
                     state_outputs=False)
    np.testing.assert_allclose(out_kw.asnumpy(), out_pos.asnumpy())
    # gap: state_cell by keyword with state omitted -> zeros default fills
    out_gap = nd.RNN(x, params, state_cell=c0, state_size=5, mode="lstm",
                     state_outputs=False)
    np.testing.assert_allclose(out_gap.asnumpy(), out_pos.asnumpy())


def test_symbolic_rnn_dropout_is_live():
    """p>0 must actually drop between layers when training (RNN is
    train-aware + keyed in the symbolic executor)."""
    import mxnet_tpu as mx

    data = mx.sym.var("data")
    out = mx.sym.RNN(data, state_size=6, num_layers=2, mode="lstm",
                     p=0.9, state_outputs=False, name="l")
    x = nd.array(np.random.RandomState(0).randn(4, 3, 2)
                 .astype(np.float32))
    shapes, _, _ = out.infer_shape(data=(4, 3, 2))
    P = dict(zip(out.list_arguments(), shapes))["l_parameters"]
    w = nd.array((np.random.RandomState(1).randn(*P) * 0.3)
                 .astype(np.float32))
    exe = out.bind(mx.cpu(), {"data": x, "l_parameters": w},
                   grad_req="null")
    train_o = exe.forward(is_train=True)[0].asnumpy()
    eval_o = exe.forward(is_train=False)[0].asnumpy()
    # dropout at 0.9 between layers must change the training output
    assert not np.allclose(train_o, eval_o)
    # and eval mode is deterministic
    np.testing.assert_allclose(exe.forward(is_train=False)[0].asnumpy(),
                               eval_o)


def test_symbol_optional_gap_is_loud():
    import mxnet_tpu as mx

    data = mx.sym.var("data")
    cell = mx.sym.var("c0")
    with pytest.raises(mx.MXNetError, match="omitted"):
        mx.sym.RNN(data, state_cell=cell, state_size=4, mode="lstm")


@pytest.mark.parametrize("op_case", [
    "upsampling_nearest", "upsampling_bilinear", "bilinear_sampler",
    "grid_generator", "im2col", "col2im", "correlation", "hard_sigmoid",
    "hard_swish", "mish", "trace", "digamma", "softmax_activation",
])
def test_new_op_numeric_gradients(op_case):
    """Finite-difference gradient checks for the round's differentiable
    op additions (the reference test_operator.py discipline)."""
    from mxnet_tpu.test_utils import check_numeric_gradient

    import zlib

    rng = np.random.RandomState(zlib.crc32(op_case.encode()))

    def data(*shape):
        return nd.array(rng.randn(*shape).astype(np.float32) * 0.5)

    if op_case == "upsampling_nearest":
        check_numeric_gradient(
            lambda x: nd.UpSampling(x, scale=2, sample_type="nearest"),
            [data(1, 2, 3, 3)])
    elif op_case == "upsampling_bilinear":
        check_numeric_gradient(
            lambda x: nd.UpSampling(x, scale=2, sample_type="bilinear"),
            [data(1, 2, 3, 3)])
    elif op_case == "bilinear_sampler":
        grid = nd.array((rng.rand(1, 2, 4, 4) * 1.2 - 0.6)
                        .astype(np.float32))
        check_numeric_gradient(
            lambda x: nd.BilinearSampler(x, grid), [data(1, 2, 5, 5)])
    elif op_case == "grid_generator":
        check_numeric_gradient(
            lambda t: nd.GridGenerator(t, transform_type="affine",
                                       target_shape=(3, 3)),
            [data(2, 6)])
    elif op_case == "im2col":
        check_numeric_gradient(
            lambda x: nd.im2col(x, kernel=(2, 2), stride=(1, 1)),
            [data(1, 2, 4, 4)])
    elif op_case == "col2im":
        check_numeric_gradient(
            lambda c: nd.col2im(c, output_size=(4, 4), kernel=(2, 2),
                                stride=(2, 2)),
            [data(1, 8, 4)])
    elif op_case == "correlation":
        a, b = data(1, 2, 4, 4), data(1, 2, 4, 4)
        check_numeric_gradient(
            lambda x, y: nd.Correlation(x, y, max_displacement=1,
                                        pad_size=1), [a, b])
    elif op_case == "hard_sigmoid":
        check_numeric_gradient(lambda x: nd.hard_sigmoid(x + 3.0),
                               [data(3, 4)])
    elif op_case == "hard_swish":
        check_numeric_gradient(lambda x: nd.hard_swish(x + 8.0),
                               [data(3, 4)])
    elif op_case == "mish":
        check_numeric_gradient(lambda x: nd.mish(x), [data(3, 4)])
    elif op_case == "trace":
        check_numeric_gradient(lambda x: nd.trace(x), [data(4, 4)])
    elif op_case == "digamma":
        check_numeric_gradient(lambda x: nd.digamma(x + 3.0),
                               [data(3, 3)])
    elif op_case == "softmax_activation":
        check_numeric_gradient(
            lambda x: nd.SoftmaxActivation(x, mode="channel"),
            [data(2, 3, 2, 2)])
