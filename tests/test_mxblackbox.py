"""mxblackbox (ISSUE 17): always-on crash forensics — per-rank event
journals, crash bundles on every abnormal exit, cross-rank incident
reconstruction.

Fast tier-1 lanes: the journal (ring bound, spill/rotation,
torn-line-tolerant reader, signal-safety hand-off — the PR 10 SIGUSR2
self-deadlock regression), the bundle writer (meta-last commit
protocol, index bounds, supervisor scrape with WTERMSIG-resolved exit
records), the postmortem merger (clock alignment on sync marks,
first-failure attribution order, coordinated exits never attributed),
the excepthook chain, the elastic.guard bundle seams, and the
disabled-path 3% overhead gate.  The slow lane is the chaos
known-answer e2e (``tools/postmortem.py --selftest`` runs the same
check as the nightly blackbox stage).
"""
import gc
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.resilience import elastic
from mxnet_tpu.resilience.elastic import (RC_PEER_FAILED, RC_WINDDOWN,
                                          PeerFailed, Supervisor)
from mxnet_tpu.resilience.preemption import Preempted
from mxnet_tpu.telemetry import instruments as _ins, mxblackbox
from mxnet_tpu.telemetry.mxblackbox import (EventJournal, bundle,
                                            postmortem, read_index,
                                            signal_name)
from mxnet_tpu.util import env as _env

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counter_value(name, **labels):
    fam = _ins._family(name)
    for values, child in fam.children():
        if dict(zip(fam.labelnames, values)) == labels:
            return child.value
    return 0.0


@pytest.fixture()
def bb(tmp_path, monkeypatch):
    """A fresh, enabled mxblackbox scoped to a tmp dir; module state
    restored afterwards so the rest of the suite sees the default
    (disabled) fast path."""
    d = str(tmp_path / "bb")
    monkeypatch.setenv("MXNET_BLACKBOX_DIR", d)
    saved = (mxblackbox._JOURNAL, mxblackbox._ACTIVE,
             mxblackbox._LAST_BUNDLE)
    mxblackbox._JOURNAL = None
    mxblackbox.enable(hooks=False)
    yield d
    j = mxblackbox._JOURNAL
    if j is not None:
        j.close()
    (mxblackbox._JOURNAL, mxblackbox._ACTIVE,
     mxblackbox._LAST_BUNDLE) = saved


# ---------------------------------------------------------------------------
# the event journal
# ---------------------------------------------------------------------------

class TestJournal:
    def test_ring_bounded_tail_newest_last(self, tmp_path):
        j = EventJournal(directory=None, who="t", ring=32)
        for i in range(100):
            j.emit("alert", f"e{i}", step=i)
        assert len(j) == 32
        t = j.tail(5)
        assert [e["msg"] for e in t] == [f"e{i}" for i in
                                         range(95, 100)]
        assert t[-1]["step"] == 99
        assert t[-1]["rank"] is None
        assert t[-1]["t_unix"] > 0 and t[-1]["t_mono"] > 0

    def test_spill_roundtrip_and_torn_tail_skipped(self, tmp_path):
        j = EventJournal(directory=str(tmp_path), who="r3", rank=3,
                         gen=2)
        for i in range(5):
            j.emit("retry", f"e{i}")
        j.close()
        path = j.spill_path()
        assert path.endswith("journal-r3.jsonl")
        # a hard kill can tear only the LAST line of a single-write
        # append — the reader must skip it and keep everything else
        with open(path, "ab") as f:
            f.write(b'{"category": "torn", "msg"')
        got = EventJournal.read_spill(path)
        assert [e["msg"] for e in got] == [f"e{i}" for i in range(5)]
        assert all(e["rank"] == 3 and e["gen"] == 2 for e in got)
        assert EventJournal.read_spill(path, tail=2)[0]["msg"] == "e3"
        assert EventJournal.read_spill(
            str(tmp_path / "nope.jsonl")) == []

    def test_spill_rotates_once_past_cap(self, tmp_path):
        j = EventJournal(directory=str(tmp_path), who="t",
                         spill_max_bytes=1)  # floors at 64 KiB
        big = "x" * 1024
        for i in range(80):
            j.emit("alert", big, i=i)
        j.close()
        assert os.path.exists(j.spill_path() + ".1")
        # post-rotation entries land in the fresh file
        assert EventJournal.read_spill(j.spill_path())

    def test_unserializable_field_keeps_ring_entry(self, tmp_path):
        j = EventJournal(directory=str(tmp_path), who="t")
        j.emit("health", "obj", detail=threading.Lock())
        j.close()
        assert len(j) == 1
        # repr-serialized rather than dropped
        got = EventJournal.read_spill(j.spill_path())
        assert len(got) == 1 and "lock" in got[0]["detail"]


class TestSignalSafety:
    def test_journal_lock_is_nonreentrant_leaf(self):
        """THE PR 10 regression pin: the journal lock must stay a
        plain (non-reentrant) ``threading.Lock`` — an RLock would let
        an inline signal-handler emit 'work' in the interrupted
        frame and silently reintroduce the self-deadlock class this
        design exists to prevent."""
        j = EventJournal(directory=None, who="t")
        assert type(j._lock) is type(threading.Lock())
        assert j._lock.acquire(blocking=False)
        try:
            # non-reentrant: a second acquire from the SAME thread
            # would block — exactly why the signal path must not
            # take it inline
            assert not j._lock.acquire(blocking=False)
        finally:
            j._lock.release()

    def test_emit_from_signal_while_lock_held_defers_to_drainer(self):
        """A signal that interrupts a frame HOLDING the journal lock
        (i.e. mid-``emit``) must not deadlock: the handler enqueues
        and returns with the lock still held; the daemon drainer
        performs the real emit after release, with the clocks stamped
        at signal time."""
        j = EventJournal(directory=None, who="t")
        fired = []
        old = signal.signal(signal.SIGUSR2,
                            lambda s, f: (j.emit_from_signal(
                                "crash", "from handler", signum=s),
                                fired.append(time.monotonic())))
        try:
            with j._lock:  # the interrupted frame is mid-emit
                t_sig = time.monotonic()
                os.kill(os.getpid(), signal.SIGUSR2)
                deadline = time.monotonic() + 5
                while not fired and time.monotonic() < deadline:
                    time.sleep(0.005)
                # the handler RETURNED while the lock was still held
                assert fired
                # and the real emit has not happened yet (no lock
                # taken inline) — peek lock-free, we hold the lock
                assert len(j._ring) == 0
            deadline = time.monotonic() + 5
            while len(j) == 0 and time.monotonic() < deadline:
                time.sleep(0.005)
            (entry,) = j.tail(1)
            assert entry["msg"] == "from handler"
            assert entry["category"] == "crash"
            # clocks were stamped in the handler, not at drain time
            assert abs(entry["t_mono"] - t_sig) < 1.0
        finally:
            signal.signal(signal.SIGUSR2, old)

    def test_drainer_is_daemon(self):
        j = EventJournal(directory=None, who="t")
        j.emit_from_signal("crash", "x")
        deadline = time.monotonic() + 5
        while len(j) == 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(j) == 1
        assert j._drainer.daemon
        assert j._drainer.name == "mx-blackbox-journal"


# ---------------------------------------------------------------------------
# module seams: enable/disable, rank requalification, metrics
# ---------------------------------------------------------------------------

class TestModule:
    def test_disabled_is_noop(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MXNET_BLACKBOX_DIR", str(tmp_path / "n"))
        saved = (mxblackbox._JOURNAL, mxblackbox._ACTIVE)
        mxblackbox._JOURNAL = None
        mxblackbox.disable()
        try:
            assert mxblackbox.emit("alert", "x") is None
            assert mxblackbox.write_crash_bundle("crash") is None
            mxblackbox.emit_from_signal("crash", "x")
            assert mxblackbox._JOURNAL is None  # nothing materialized
            assert not os.path.exists(str(tmp_path / "n"))
        finally:
            mxblackbox._JOURNAL, mxblackbox._ACTIVE = saved

    def test_emit_bumps_category_metric(self, bb):
        before = _counter_value("mx_blackbox_events_total",
                                category="retry")
        entry = mxblackbox.emit("retry", "exhausted", site="s")
        assert entry["site"] == "s"
        assert _counter_value("mx_blackbox_events_total",
                              category="retry") == before + 1

    def test_journal_requalifies_on_rank(self, bb, monkeypatch):
        """mxblackbox auto-enables BEFORE dist.init() knows the rank;
        once the rank lands (tracing.set_rank) the singleton must
        recreate itself rank-qualified — the supervisor scrape looks
        the dead rank's spill up BY rank — carrying the pre-rank
        history into the new ring."""
        from mxnet_tpu.telemetry import tracing

        monkeypatch.setattr(tracing, "_RANK", None)
        mxblackbox.emit("elastic", "pre-rank event")
        j0 = mxblackbox._JOURNAL
        assert j0._who.startswith("p")
        monkeypatch.setattr(tracing, "_RANK", 7)
        mxblackbox.emit("elastic", "post-rank event")
        j1 = mxblackbox._JOURNAL
        assert j1 is not j0 and j1._who == "r7"
        msgs = [e["msg"] for e in j1.tail(10)]
        assert "pre-rank event" in msgs and "post-rank event" in msgs
        assert os.path.exists(os.path.join(bb, "journal-r7.jsonl"))
        # requalification happens ONCE — the next emit reuses it
        mxblackbox.emit("elastic", "again")
        assert mxblackbox._JOURNAL is j1

    def test_knobs_registered(self):
        for name in ("MXNET_BLACKBOX", "MXNET_BLACKBOX_DIR",
                     "MXNET_BLACKBOX_RING", "MXNET_BLACKBOX_SPILL_MB",
                     "MXNET_BLACKBOX_TAIL", "MXNET_BLACKBOX_HISTORY",
                     "MXNET_BLACKBOX_GEN",
                     "MXNET_BLACKBOX_STDERR_TAIL_KB"):
            assert _env.is_declared(name), name


# ---------------------------------------------------------------------------
# crash bundles
# ---------------------------------------------------------------------------

class TestBundle:
    def test_bundle_layout_and_meta_last_commit(self, tmp_path):
        j = EventJournal(directory=str(tmp_path / "b"), who="r0",
                         rank=0)
        for i in range(3):
            j.emit("checkpoint", f"save step {i}", step=i)
        try:
            raise ValueError("boom")
        except ValueError as e:
            d = bundle.write_bundle(
                "crash", reason="uncaught ValueError",
                base_dir=str(tmp_path / "b"), rank=0, step=2, exc=e,
                journal=j, exit_record={"rc": 1})
        j.close()
        assert d is not None and os.path.isdir(d)
        for name in ("meta.json", "journal.json", "mxprof.json",
                     "goodput.json", "alerts.json",
                     "heartbeats.json"):
            assert os.path.exists(os.path.join(d, name)), name
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        assert meta["category"] == "crash" and meta["rank"] == 0
        assert meta["step"] == 2 and meta["exit"] == {"rc": 1}
        assert meta["exception"]["type"] == "ValueError"
        assert "boom" in meta["exception"]["traceback"]
        assert "knob_fingerprint" in meta["config"]
        with open(os.path.join(d, "journal.json")) as f:
            tail = json.load(f)
        assert [e["msg"] for e in tail] == ["save step 0",
                                            "save step 1",
                                            "save step 2"]
        idx = read_index(str(tmp_path / "b"), rank=0)
        assert idx and idx[-1]["dir"] == d
        assert idx[-1]["category"] == "crash"

    def test_index_bounded_and_metaless_dir_skipped(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("MXNET_BLACKBOX_HISTORY", "3")
        base = str(tmp_path / "b")
        for i in range(5):
            bundle.write_bundle("health", reason=f"b{i}",
                                base_dir=base, rank=1, step=i)
        idx = read_index(base, rank=1)
        assert len(idx) == 3
        assert [e["step"] for e in idx] == [2, 3, 4]
        # an interrupted write (no meta.json) is never a bundle
        os.makedirs(os.path.join(base, "crash-99999999-x-r1-9"))
        loaded = postmortem.load_bundles(base)
        assert len(loaded) == 5
        assert all("meta" in b for b in loaded)

    def test_supervisor_scrape_reads_spill_and_stderr(self, tmp_path):
        """The scrape path: the dead rank cannot be asked, but its
        append-only spill survives it — and the exit record keeps the
        SIGNAL so an OOM SIGKILL never reads like a chaos die."""
        base = str(tmp_path / "b")
        j = EventJournal(directory=base, who="r2", rank=2)
        j.emit("elastic", "generation start")
        j.emit("checkpoint", "save step 4", step=4)
        j.close()
        exit_record = {"rc": -9, "signal": 9,
                       "signal_name": "SIGKILL",
                       "supervisor_sigkill": False,
                       "classified": "killed:SIGKILL"}
        d = bundle.write_supervisor_bundle(
            base, 2, exit_record, gen=1,
            stderr_path="gen1-rank2.stderr",
            stderr_tail="Killed\n",
            heartbeat={"age_s": 9.7, "step": 4})
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        assert meta["category"] == "scrape" and meta["rank"] == 2
        assert meta["step"] == 4  # from the last spill entry
        assert meta["exit"]["classified"] == "killed:SIGKILL"
        with open(os.path.join(d, "journal.json")) as f:
            events = json.load(f)
        assert [e["msg"] for e in events] == ["generation start",
                                              "save step 4"]
        with open(os.path.join(d, "stderr.txt")) as f:
            assert f.read() == "Killed\n"
        with open(os.path.join(d, "heartbeats.json")) as f:
            assert json.load(f)["2"]["age_s"] == 9.7

    def test_signal_name(self):
        assert signal_name(9) == "SIGKILL"
        assert signal_name(15) == "SIGTERM"
        assert signal_name(None) is None
        assert signal_name(0) is None


# ---------------------------------------------------------------------------
# last-gasp hooks
# ---------------------------------------------------------------------------

class TestHooks:
    def test_excepthook_writes_bundle_and_chains(self, bb,
                                                 monkeypatch):
        chained = []
        monkeypatch.setattr(mxblackbox, "_PREV_EXCEPTHOOK",
                            lambda *a: chained.append(a))
        try:
            raise ValueError("unhandled boom")
        except ValueError as e:
            mxblackbox._excepthook(ValueError, e, e.__traceback__)
        assert len(chained) == 1  # the previous hook always runs
        d = mxblackbox.last_bundle()
        assert d is not None
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        assert meta["category"] == "crash"
        assert meta["reason"] == "uncaught ValueError"
        assert "unhandled boom" in meta["exception"]["traceback"]

    def test_excepthook_skips_keyboardinterrupt(self, bb,
                                                monkeypatch):
        chained = []
        monkeypatch.setattr(mxblackbox, "_PREV_EXCEPTHOOK",
                            lambda *a: chained.append(a))
        before = mxblackbox.last_bundle()
        mxblackbox._excepthook(KeyboardInterrupt,
                               KeyboardInterrupt(), None)
        assert len(chained) == 1  # chains even when not bundling
        assert mxblackbox.last_bundle() == before


# ---------------------------------------------------------------------------
# elastic integration: guard bundles + supervisor exit records
# ---------------------------------------------------------------------------

class TestElasticSeams:
    def test_guard_peer_failed_writes_bundle(self, bb):
        codes = []
        with elastic.guard(exit_fn=codes.append):
            raise PeerFailed("peer gone", what="allreduce")
        assert codes == [RC_PEER_FAILED]
        d = mxblackbox.last_bundle()
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        assert meta["category"] == "peer_failed"
        assert meta["exit"] == {"rc": RC_PEER_FAILED}
        cats = [e["category"] for e in mxblackbox.recent(10)]
        assert "elastic" in cats  # the observation was journaled too

    def test_guard_preempted_writes_bundle(self, bb):
        codes = []
        with elastic.guard(exit_fn=codes.append):
            raise Preempted("wind-down")
        assert codes == [RC_WINDDOWN]
        d = mxblackbox.last_bundle()
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        assert meta["category"] == "preempted"
        assert meta["exit"] == {"rc": RC_WINDDOWN}

    def test_exit_records_keep_wtermsig(self):
        """The WTERMSIG satellite: a chaos die (rc 1), the
        supervisor's own grace-expiry SIGKILL (hung), an EXTERNAL
        SIGKILL (the OOM killer), and the reserved rcs must all
        classify differently."""

        class P:
            def __init__(self, rc):
                self.returncode = rc

        workers = [{"rank": 0, "proc": P(0)},
                   {"rank": 1, "proc": P(1)},
                   {"rank": 2, "proc": P(-9)},
                   {"rank": 3, "proc": P(-9)},
                   {"rank": 4, "proc": P(RC_PEER_FAILED)},
                   {"rank": 5, "proc": P(RC_WINDDOWN)},
                   {"rank": 6, "proc": P(-11)}]
        recs = Supervisor._exit_records(workers, killed=[3])
        assert recs["0"]["classified"] == "clean"
        assert recs["1"]["classified"] == "died"
        assert recs["1"]["signal"] is None
        assert recs["2"]["classified"] == "killed:SIGKILL"
        assert recs["2"]["signal"] == 9
        assert recs["2"]["supervisor_sigkill"] is False
        assert recs["3"]["classified"] == "hung"
        assert recs["3"]["supervisor_sigkill"] is True
        assert recs["4"]["classified"] == "peer_failed"
        assert recs["5"]["classified"] == "winddown"
        assert recs["6"]["classified"] == "killed:SIGSEGV"
        assert recs["6"]["signal_name"] == "SIGSEGV"


# ---------------------------------------------------------------------------
# postmortem: clock alignment + first-failure attribution
# ---------------------------------------------------------------------------

def _jev(rank, cat, msg, t, step=None, **fields):
    e = {"t_unix": t, "t_mono": t, "rank": rank, "step": step,
         "category": cat, "msg": msg}
    e.update(fields)
    return e


class TestPostmortem:
    def _two_rank_bundles(self, skew=5.0):
        """rank 1's clock runs ``skew`` seconds AHEAD of rank 0's;
        both share the 'generation start' and 'save step 2' sync
        marks.  rank 1 is chaos-killed at true time 103.3 (its clock:
        108.3); rank 0 observes and exits peer_failed at 105.5."""
        r0 = [_jev(0, "elastic", "generation start", 100.0),
              _jev(0, "checkpoint", "save step 2", 102.0, step=2),
              _jev(0, "elastic", "peer failure observed: allreduce",
                   105.5)]
        r1 = [_jev(1, "elastic", "generation start", 100.0 + skew),
              _jev(1, "checkpoint", "save step 2", 102.0 + skew,
                   step=2),
              _jev(1, "chaos",
                   "fault fired at site 'elastic.worker' call #4",
                   103.3 + skew, action="die", nth=4)]
        return [
            {"meta": {"category": "peer_failed", "rank": 0,
                      "t_unix": 105.6, "dir": "/nope",
                      "exit": {"rc": RC_PEER_FAILED}},
             "journal": r0},
            {"meta": {"category": "chaos", "rank": 1, "step": 4,
                      "t_unix": 103.4 + skew, "dir": "/nope",
                      "exit": {"rc": 1}},
             "journal": r1},
        ]

    def test_clock_alignment_on_sync_marks(self):
        rep = postmortem.reconstruct(self._two_rank_bundles(skew=5.0),
                                     epoch=1)
        assert rep["clock"]["offsets_s"]["0"] == 0.0
        assert abs(rep["clock"]["offsets_s"]["1"] + 5.0) < 1e-6
        assert rep["clock"]["aligned_on"]["1"] == 2
        # the merged timeline is causally ordered on ALIGNED time:
        # rank 1's death (true 103.3) precedes rank 0's observation
        # (105.5) despite its raw stamp reading 108.3
        tl = rep["timeline"]
        i_die = next(i for i, e in enumerate(tl)
                     if e["category"] == "chaos")
        i_obs = next(i for i, e in enumerate(tl)
                     if "peer failure" in e["msg"])
        assert i_die < i_obs
        assert abs(tl[i_die]["t_aligned"] - 103.3) < 1e-6

    def test_first_failure_attribution_with_step_backfill(self):
        """The journal chaos fire carries the call count, not the
        step; the same rank's chaos BUNDLE knows the step — the
        attribution must name rank 1 / chaos / step 4, never the
        peer_failed victim."""
        rep = postmortem.reconstruct(self._two_rank_bundles(),
                                     t_detect_unix=104.0, epoch=1)
        ff = rep["first_failure"]
        assert ff["rank"] == 1 and ff["category"] == "chaos"
        assert ff["step"] == 4  # backfilled from the bundle meta
        assert ff["source"] == "journal"
        assert rep["attributed"] is True
        assert abs(rep["detection"]["lag_s"] - 0.7) < 1e-3
        assert rep["incident_id"].startswith("inc-")
        assert "-e1-r1-" in rep["incident_id"]

    def test_coordinated_exits_never_attributed(self):
        """peer_failed/preempted/winddown bundles are victims — with
        no direct evidence the fallback is the exit records, then the
        supervisor's failed list (category 'unknown',
        attributed=False)."""
        b = [{"meta": {"category": "peer_failed", "rank": 0,
                       "t_unix": 10.0, "dir": "/nope"},
              "journal": [_jev(0, "elastic",
                               "peer failure observed: x", 10.0)]}]
        rep = postmortem.reconstruct(
            b, exits={"1": {"rc": -9, "signal": 9,
                            "classified": "killed:SIGKILL"},
                      "0": {"rc": RC_PEER_FAILED, "signal": None}},
            failed_ranks=[1], epoch=2)
        ff = rep["first_failure"]
        assert ff["rank"] == 1 and ff["source"] == "exit"
        assert rep["attributed"] is True
        # nothing at all: supervisor classification only
        rep2 = postmortem.reconstruct([], failed_ranks=[2], epoch=2)
        assert rep2["first_failure"]["category"] == "unknown"
        assert rep2["attributed"] is False

    def test_scrape_bundle_category_from_exit_classification(self):
        b = [{"meta": {"category": "scrape", "rank": 2, "step": 6,
                       "t_unix": 50.0, "dir": "/nope",
                       "exit": {"rc": -9, "signal": 9,
                                "classified": "killed:SIGKILL"}},
              "journal": [_jev(2, "checkpoint", "save step 6", 49.0,
                               step=6)]}]
        rep = postmortem.reconstruct(b, epoch=1)
        ff = rep["first_failure"]
        assert ff["category"] == "killed:SIGKILL"
        assert ff["rank"] == 2 and ff["step"] == 6
        # the failure time is the last journal sign of life, not the
        # scrape's own (detection-side) stamp
        assert abs(ff["t_unix"] - 49.0) < 1e-6

    def test_run_epoch_writes_incident_and_bumps_metric(self,
                                                        tmp_path):
        base = str(tmp_path / "b")
        j = EventJournal(directory=base, who="r1", rank=1)
        # explicit step: an omitted step falls back to the live mxprof
        # counter, which another test's recorder may have advanced
        j.emit("chaos", "fault fired at site 's' call #2",
               step=2, action="die", nth=2)
        d = bundle.write_bundle("chaos", reason="chaos die",
                                base_dir=base, rank=1, step=2,
                                journal=j, exit_record={"rc": 1})
        j.close()
        assert d is not None
        before = _counter_value("mx_incident_total", category="chaos")
        rep = postmortem.run_epoch(base, 1, t_detect_unix=time.time(),
                                   failed_ranks=[1])
        assert rep is not None
        path = os.path.join(base, "INCIDENT-epoch1.json")
        assert rep["path"] == path and os.path.exists(path)
        with open(path) as f:
            disk = json.load(f)
        assert disk["first_failure"]["rank"] == 1
        assert disk["first_failure"]["step"] == 2
        assert _counter_value("mx_incident_total",
                              category="chaos") == before + 1
        # run_epoch is best-effort: a broken input is None, never a
        # raise into the supervisor's recovery path
        assert postmortem.run_epoch(None, 1) is None


# ---------------------------------------------------------------------------
# the disabled-path zero-overhead gate (mxprof-style)
# ---------------------------------------------------------------------------

def test_blackbox_disabled_overhead_within_3pct_of_step():
    """With mxblackbox imported but DISABLED, a training step's worth
    of seam hits (the call shape every feed uses: one ``_ACTIVE``
    check, plus the ``emit()`` early return for seams that call
    through) must cost under 3% of a real step — always-on forensics
    may not tax a job that never crashes."""
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.Dense(4, in_units=16)
    net.initialize()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 1e-3, "momentum": 0.9})
    x = nd.array(np.random.rand(8, 16).astype("float32"))

    def one_step():
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        tr.step(8)

    for _ in range(5):
        one_step()

    saved = mxblackbox._ACTIVE
    mxblackbox.disable()

    def best_window(loops, reps, fn):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(loops):
                fn()
            best = min(best, time.perf_counter() - t0)
        return best

    def per_step_seams():
        # ~the densest per-step seam traffic: 8 flag checks (alert,
        # health, chaos, retry, checkpoint x2, compile, elastic) of
        # which 2 call through into emit()'s early return
        for _ in range(6):
            if mxblackbox._ACTIVE:
                raise AssertionError("disabled")
        mxblackbox.emit("health", "x", step=1)
        mxblackbox.emit("checkpoint", "save", step=1)

    gc.disable()
    try:
        t_step = best_window(20, 5, one_step) / 20
        t_attr = best_window(2000, 7, per_step_seams) / 2000
    finally:
        gc.enable()
        mxblackbox._ACTIVE = saved
    assert t_attr <= 0.03 * t_step, \
        (f"per-step seam traffic with mxblackbox imported-but-"
         f"disabled costs {t_attr * 1e6:.2f}us vs step "
         f"{t_step * 1e6:.1f}us — {t_attr / t_step * 100:.2f}% "
         f"exceeds the 3% budget")


# ---------------------------------------------------------------------------
# the chaos known-answer e2e (nightly blackbox stage)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_e2e_chaos_incident_names_rank_category_step(tmp_path):
    """THE ISSUE 17 acceptance: a deterministic chaos kill of rank 1
    at step 4 under the Supervisor yields an INCIDENT.json whose
    first-failure attribution names rank 1 / chaos / step 4, with the
    incident id stamped into the epoch record, the COMMIT marker, and
    (through resume) the goodput recovery window."""
    d = str(tmp_path / "job")
    out = str(tmp_path / "report.json")
    cmd = [sys.executable, os.path.join(_REPO, "tools",
                                        "elastic_run.py"),
           "--workers", "2", "--demo", "--cpu", "--mode", "replace",
           "--steps", "8", "--ckpt-every", "2", "--hb-timeout", "8",
           "--collective-timeout", "6", "--grace", "12", "--dir", d,
           "--out", out, "--chaos", "elastic.worker@4:die:rank=1"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("MXNET_CHAOS", None)
    env.pop("MXNET_CHAOS_SPEC", None)
    p = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=420, env=env)
    assert p.returncode == 0, p.stdout[-3000:] + p.stderr[-2000:]
    with open(out) as f:
        rep = json.load(f)
    assert rep["ok"] and rep["restarts"] == 1
    epoch = rep["epochs"][0]
    with open(os.path.join(d, "blackbox",
                           "INCIDENT-epoch1.json")) as f:
        inc = json.load(f)
    ff = inc["first_failure"]
    assert ff["rank"] == 1
    assert ff["category"] == "chaos"
    assert ff["step"] == 4
    assert inc["attributed"] is True
    assert inc["detection"]["lag_s"] is not None
    assert sorted(inc["ranks"]) == [0, 1]
    # the chaos die (plain rc 1) classifies as died, NOT as a kill
    assert epoch["exits"]["1"]["classified"] == "died"
    assert epoch["exits"]["1"]["signal"] is None
    # the id flows: epoch record -> COMMIT marker -> resume journal
    assert epoch["incident_id"] == inc["incident_id"]
    commit = elastic.read_commit(d)
    assert commit["incident"] == inc["incident_id"]
    restores = [e for e in EventJournal.read_spill(
        os.path.join(d, "blackbox", "journal-r0.jsonl"))
        if e["msg"].startswith("restore step")]
    assert restores and restores[-1]["incident"] == inc["incident_id"]
    # both failure-side bundles committed: the dying rank's own chaos
    # bundle AND the supervisor's scrape of it
    cats = {b["meta"]["category"]
            for b in postmortem.load_bundles(
                os.path.join(d, "blackbox"))}
    assert {"chaos", "peer_failed", "scrape"} <= cats


@pytest.mark.slow
def test_postmortem_selftest_cli(tmp_path):
    """``tools/postmortem.py --selftest`` (what the nightly blackbox
    stage runs) passes its own gate and writes the artifact."""
    out = str(tmp_path / "INCIDENT.json")
    p = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools",
                                      "postmortem.py"),
         "--selftest", "--out", out],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert p.returncode == 0, p.stdout[-3000:] + p.stderr[-2000:]
    with open(out) as f:
        art = json.load(f)
    assert art["gate_ok"] is True
    assert all(art["checks"].values()), art["checks"]
    assert art["first_failure"]["rank"] == 1
