"""On-demand deep capture: one bounded ``jax.profiler`` window.

The always-on layers (mxprof, mxhealth) are cheap because they stay at
step granularity; the *op-level* XLA timeline is expensive and used to
require manually bracketing ``profiler.start_xla_trace`` around the
right code.  This module makes the deep capture an on-demand,
admission-gated action every surface can invoke through ONE path:

    mxtriage.deep_capture(steps=3)      # training: step-boundary window
    mxtriage.deep_capture(seconds=2.0)  # serving / any process
    POST /profilez                      # the HTTP front end
    kill -USR1 <pid>                    # from outside
    alerts.Rule(..., action="deep_capture")   # a firing alert

Admission: at most ONE capture per process may be armed or recording —
a second request answers ``CaptureBusy`` (HTTP 409) instead of
stacking jax profiler sessions (which corrupts both traces).  Alert
triggers are additionally rate-limited (MXNET_TRIAGE_ALERT_INTERVAL_S)
so a flapping rule cannot turn the profiler into a DoS on its own
process.

Every capture lands in its own directory under MXNET_TRIAGE_DIR:
the xplane trace, an ``mxprof.json`` aggregate snapshot of the same
window, and a ``meta.json`` recording the trigger, the firing rule,
and the step span — and is indexed in ``index.json`` beside them, so
"what captured this and why" is answerable weeks later.

``steps=N`` windows arm on the next mxprof step boundary and stop N
boundaries later (the flight recorder's step listeners drive both
edges); a watchdog (MXNET_TRIAGE_STEP_TIMEOUT_S) force-stops a window
whose boundaries never arrive.  ``seconds=S`` windows start
immediately and a timer stops them.  The legacy manual bracket
(``profiler.start_xla_trace``/``stop_xla_trace``) is refolded onto
:func:`start_manual`/:func:`stop_manual` — same admission slot, same
index.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Optional

from ...base import MXNetError
from ...util import env as _env
from .. import instruments as _ins
from .. import tracing as _tracing

__all__ = ["CaptureBusy", "CaptureManager", "manager"]

_SEQ = itertools.count(1)


class CaptureBusy(MXNetError):
    """A deep capture is already armed or recording in this process
    (the admission gate; retry after it completes)."""


# ---------------------------------------------------------------------------
# profiler backend (separated so tests stub it; jax imports stay lazy)
# ---------------------------------------------------------------------------

def _start_backend(logdir: str) -> None:
    import jax

    jax.profiler.start_trace(logdir)


def _stop_backend() -> None:
    import jax

    jax.profiler.stop_trace()


def _current_step() -> Optional[int]:
    """The mxprof flight recorder's step counter right now — stamped
    into every capture's meta so even a seconds-window (alert, http)
    capture records WHICH training steps it covered."""
    try:
        from .. import mxprof

        return mxprof.recorder()._step
    except Exception:  # noqa: BLE001 — meta stays None-steps, capture proceeds
        return None


class _Session:
    """One capture's lifecycle state (owned by the manager's lock)."""

    def __init__(self, trigger: str, mode: str, want, out_dir: str,
                 rule: Optional[str], severity: Optional[str]):
        self.trigger = trigger
        self.mode = mode              # "steps" | "seconds" | "manual"
        self.want = want              # N steps / S seconds / None
        self.dir = out_dir
        self.rule = rule
        self.severity = severity
        self.t_request = time.time()
        self.t_start: Optional[float] = None
        self.step_begin: Optional[int] = None
        self.step_end: Optional[int] = None
        self.started = False
        self.status = "pending"
        self.done = threading.Event()
        self.meta: Optional[dict] = None
        # serializes the WINDOW EDGES (backend start vs stop): a step
        # listener starting the trace must not race a watchdog that
        # already closed the window — the loser would leave the jax
        # profiler running forever, poisoning every later capture
        self.edge = threading.Lock()


class CaptureManager:
    """The process capture slot + artifact index.  One instance per
    process (:func:`manager`); tests build private ones with stubbed
    backends."""

    def __init__(self, base_dir: Optional[str] = None,
                 start_backend=None, stop_backend=None):
        self._lock = threading.Lock()
        self._session: Optional[_Session] = None
        self._last_alert_t: Optional[float] = None
        self._base_dir = base_dir
        self._start = start_backend or _start_backend
        self._stop = stop_backend or _stop_backend

    # ---- paths -------------------------------------------------------

    def base_dir(self) -> str:
        return self._base_dir or _env.get_str("MXNET_TRIAGE_DIR") \
            or "mxtriage"

    @staticmethod
    def _who() -> str:
        """Rank-qualified process identity for artifact names on a
        SHARED base dir (same lesson as mxprof's default dump path:
        containerized multi-host ranks all run as pid 1, so pid alone
        collides — the job rank, once dist stamped it, does not)."""
        rank = _tracing._RANK
        return f"r{rank}" if rank is not None else f"p{os.getpid()}"

    def _new_dir(self, trigger: str) -> str:
        stamp = time.strftime("%Y%m%d-%H%M%S")
        d = os.path.join(
            self.base_dir(),
            f"deep-{stamp}-{trigger}-{self._who()}-{next(_SEQ)}")
        os.makedirs(d, exist_ok=True)
        return d

    # ---- admission ---------------------------------------------------

    def active(self) -> Optional[dict]:
        """The in-flight capture's public view, or None."""
        with self._lock:
            s = self._session
            if s is None:
                return None
            return {"trigger": s.trigger, "mode": s.mode,
                    "dir": s.dir, "status": s.status,
                    "started": s.started}

    def _admit(self, trigger: str, mode: str, want, rule, severity,
               out_dir: Optional[str] = None) -> _Session:
        # take the slot under the lock; do the directory IO OUTSIDE it
        # (a slow filesystem must not serialize every admission probe)
        with self._lock:
            if self._session is not None:
                _ins.triage_suppressed_total("busy").inc()
                raise CaptureBusy(
                    f"deep capture already in flight "
                    f"({self._session.trigger}, {self._session.dir}); "
                    f"one capture per process")
            s = self._session = _Session(trigger, mode, want, "",
                                         rule, severity)
        try:
            s.dir = out_dir or self._new_dir(trigger)
        except OSError:
            with self._lock:
                self._session = None  # an unwritable base dir must
            raise                     # not wedge the slot
        _ins.triage_capture_active().set(1)
        return s

    # ---- the one public verb -----------------------------------------

    def deep_capture(self, steps: Optional[int] = None,
                     seconds: Optional[float] = None,
                     trigger: str = "manual",
                     rule: Optional[str] = None,
                     severity: Optional[str] = None,
                     block: bool = True,
                     timeout: Optional[float] = None) -> Optional[dict]:
        """Run one bounded deep capture; returns the capture's
        ``meta.json`` dict (``block=True``) or the armed session's
        public view.  Raises :class:`CaptureBusy` when the slot is
        taken and :class:`MXNetError` on a nonsensical window."""
        if steps is not None and seconds is not None:
            raise MXNetError("deep_capture: pass steps= OR seconds=, "
                             "not both")
        if steps is None and seconds is None:
            seconds = _env.get_float("MXNET_TRIAGE_SECONDS")
        if steps is not None and steps <= 0:
            raise MXNetError(f"deep_capture: steps must be >= 1, "
                             f"got {steps}")
        if seconds is not None and seconds <= 0:
            raise MXNetError(f"deep_capture: seconds must be > 0, "
                             f"got {seconds}")

        if steps is not None:
            s = self._admit(trigger, "steps", int(steps), rule,
                            severity)
            self._arm_steps(s)
            wait_s = timeout if timeout is not None else (
                _env.get_float("MXNET_TRIAGE_STEP_TIMEOUT_S") + 10.0)
        else:
            s = self._admit(trigger, "seconds", float(seconds), rule,
                            severity)
            if not self._begin(s):
                return s.meta
            t = threading.Thread(
                target=self._seconds_runner, args=(s,),
                name="mxtriage-capture-window", daemon=True)
            t.start()
            wait_s = timeout if timeout is not None else seconds + 30.0
        if not block:
            return self.active()
        s.done.wait(wait_s)
        return s.meta

    # ---- manual bracket (profiler.start_xla_trace refold) ------------

    def start_manual(self, logdir: Optional[str] = None,
                     trigger: str = "manual") -> str:
        """Open-ended capture: starts now, runs until
        :meth:`stop_manual`.  Returns the artifact directory."""
        s = self._admit(trigger, "manual", None, None, None,
                        out_dir=logdir)
        os.makedirs(s.dir, exist_ok=True)
        if not self._begin(s):
            raise MXNetError(
                f"deep capture backend failed to start "
                f"({s.meta and s.meta.get('error')})")
        return s.dir

    def stop_manual(self) -> Optional[str]:
        """Close the manual capture; returns its directory (None when
        no manual capture is open)."""
        with self._lock:
            s = self._session
        if s is None or s.mode != "manual":
            return None
        self._finish(s, "complete")
        return s.dir

    # ---- alert trigger (rate-limited, never blocks the ticker) -------

    def trigger_from_alert(self, rule: str,
                           severity: Optional[str] = None,
                           value=None) -> str:
        """Entry point for ``action="deep_capture"`` alert rules.
        Non-blocking: the capture runs on a daemon thread.  Returns
        ``"started"`` or ``"suppressed:<reason>"``."""
        interval = _env.get_float("MXNET_TRIAGE_ALERT_INTERVAL_S")
        now = time.monotonic()
        with self._lock:
            if self._session is not None:
                reason = "busy"
            elif self._last_alert_t is not None and \
                    now - self._last_alert_t < interval:
                reason = "rate-limited"
            else:
                reason = None
                self._last_alert_t = now
        if reason is not None:
            _ins.triage_suppressed_total(reason).inc()
            return f"suppressed:{reason}"

        def run():
            try:
                self.deep_capture(trigger="alert", rule=rule,
                                  severity=severity, block=True)
            except CaptureBusy:
                pass  # lost the admission race; already counted
            except Exception:  # noqa: BLE001 — diagnostics never kill the host
                pass

        threading.Thread(target=run, name="mxtriage-alert-capture",
                         daemon=True).start()
        return "started"

    # ---- window edges ------------------------------------------------

    def _begin(self, s: _Session) -> bool:
        """Start the profiler backend for ``s``; on failure the slot is
        released and the session finishes with status ``error``.
        Holds the session's edge lock and re-checks the status under
        it: a window the watchdog (or a racing finish) already closed
        must NOT start the backend — nothing would ever stop it."""
        with s.edge:
            with self._lock:
                if self._session is not s or s.status != "pending":
                    return False
            try:
                self._start(s.dir)
            except Exception as e:  # noqa: BLE001 — backend may be busy already
                _ins.triage_suppressed_total("error").inc()
                self._finish(s, "error", error=repr(e),
                             backend_up=False)
                return False
            s.started = True
        s.t_start = time.time()
        if s.step_begin is None:
            s.step_begin = _current_step()
        return True

    def _seconds_runner(self, s: _Session) -> None:
        time.sleep(s.want)
        self._finish(s, "complete")

    def _arm_steps(self, s: _Session) -> None:
        """steps=N: start at the NEXT mxprof step boundary, stop N
        boundaries later.  Enables the flight recorder (idempotent) —
        the boundaries come from its step listeners, and the capture's
        mxprof.json should attribute the same window anyway.

        Listener (de)registration goes through the MODULE helpers so
        it always targets the live recorder — ``mxprof.enable(ring=N)``
        mid-capture swaps recorders (carrying the listener set), and a
        removal against the stale object would leak the listener."""
        from .. import mxprof

        mxprof.enable()

        def on_step(step: int) -> None:
            # runs on the training thread at a step boundary: the only
            # work is the two window edges, each once per capture
            if not s.started:
                if not self._begin(s):
                    mxprof.remove_step_listener(on_step)
                    return
                s.step_begin = step
                return
            if step - s.step_begin >= s.want:
                mxprof.remove_step_listener(on_step)
                s.step_end = step
                self._finish(s, "complete")

        mxprof.add_step_listener(on_step)

        def watchdog():
            wait = _env.get_float("MXNET_TRIAGE_STEP_TIMEOUT_S")
            if not s.done.wait(wait):
                mxprof.remove_step_listener(on_step)
                self._finish(s, "timeout")

        threading.Thread(target=watchdog, name="mxtriage-watchdog",
                         daemon=True).start()

    def _finish(self, s: _Session, status: str,
                error: Optional[str] = None,
                backend_up: Optional[bool] = None) -> None:
        """Close ``s``: stop the backend, write meta + mxprof snapshot,
        index the artifact, release the slot.  Idempotent — the
        watchdog and the step listener may race to close the same
        window."""
        with self._lock:
            if self._session is not s or s.status not in ("pending",):
                return
            s.status = status
        if backend_up is None:
            # the stop edge: taken under the session's edge lock so a
            # mid-flight _begin start completes (or aborts on the
            # status flip above) before we decide whether to stop.
            # _begin's own failure path passes backend_up=False and
            # never reaches here — it already HOLDS the edge lock.
            with s.edge:
                backend_up = s.started
                if backend_up:
                    try:
                        self._stop()
                    except Exception as e:  # noqa: BLE001
                        if error is None:
                            error = repr(e)
            if backend_up and s.step_end is None:
                s.step_end = _current_step()
        meta = {
            "trigger": s.trigger,
            "mode": s.mode,
            "requested": ({"steps": s.want} if s.mode == "steps" else
                          {"seconds": s.want} if s.mode == "seconds"
                          else {}),
            "rule": s.rule,
            "severity": s.severity,
            "step_begin": s.step_begin,
            "step_end": s.step_end,
            "status": status,
            "when": time.strftime("%Y-%m-%d %H:%M:%S"),
            "t_request": s.t_request,
            "t_start": s.t_start,
            "t_stop": time.time(),
            "pid": os.getpid(),
            "rank": _tracing._RANK,
            "dir": s.dir,
        }
        if error is not None:
            meta["error"] = error
        if status != "error":
            try:
                from .. import mxprof

                meta["mxprof"] = os.path.basename(mxprof.dump(
                    os.path.join(s.dir, "mxprof.json"),
                    live_hbm=False))
            except Exception:  # noqa: BLE001 — the trace alone still has value
                meta["mxprof"] = None
        try:
            tmp = os.path.join(s.dir, f".meta-{os.getpid()}.tmp")
            with open(tmp, "w") as f:
                json.dump(meta, f, indent=1)
            os.replace(tmp, os.path.join(s.dir, "meta.json"))
            self._index(meta)
        except OSError:
            pass  # an unwritable dir must not wedge the slot
        s.meta = meta
        with self._lock:
            self._session = None
        _ins.triage_capture_active().set(0)
        if status != "error":
            _ins.triage_captures_total(s.trigger).inc()
        s.done.set()

    # ---- the index ---------------------------------------------------

    def index_path(self) -> str:
        """Per-rank index file once dist is initialized: the index is
        read-modify-write, so ranks sharing a base dir must not
        interleave rewrites of one file (entries would vanish)."""
        rank = _tracing._RANK
        name = "index.json" if rank is None else f"index-rank{rank}.json"
        return os.path.join(self.base_dir(), name)

    def index(self) -> list:
        try:
            with open(self.index_path()) as f:
                return json.load(f)["captures"]
        except (OSError, ValueError, KeyError):
            return []

    def _index(self, meta: dict) -> None:
        """Append one capture record to index.json (bounded,
        atomic rewrite).  The index lives beside the capture dirs —
        and beside any mxprof dumps written to the same tree — so one
        listing answers 'what captured here and why'."""
        keep = _env.get_int("MXNET_TRIAGE_HISTORY") or 64
        entries = self.index()
        entries.append({k: meta.get(k) for k in (
            "dir", "trigger", "rule", "severity", "status",
            "step_begin", "step_end", "when", "pid", "rank")})
        entries = entries[-keep:]
        path = self.index_path()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"captures": entries}, f, indent=1)
        os.replace(tmp, path)


_manager_lock = threading.Lock()
_MANAGER: Optional[CaptureManager] = None


def manager() -> CaptureManager:
    """The process capture manager (created on first use)."""
    global _MANAGER
    with _manager_lock:
        if _MANAGER is None:
            _MANAGER = CaptureManager()
        return _MANAGER


def _reset(m: Optional[CaptureManager] = None) -> None:
    """Swap the process manager (tests)."""
    global _MANAGER
    with _manager_lock:
        _MANAGER = m
