"""Named-axis device meshes.

TPU-native replacement for the reference's flat device lists
(`ctx=[mx.gpu(i) for i in range(n)]`, kvstore 'device'; SURVEY.md §2d).
A DeviceMesh arranges the slice's chips into a logical nd-grid with named
axes; shardings over those axes tell XLA where to insert collectives, which
then ride ICI (in-slice) or DCN (cross-slice).

Canonical axis names (any subset, any order):
    dp    data parallel (batch split; grads psum over this axis)
    fsdp  fully-sharded data parallel (batch split + param/optimizer shard)
    tp    tensor parallel (weight matrices split; activations all-reduced)
    pp    pipeline parallel (layer stages; ppermute between neighbours)
    sp    sequence/context parallel (ring attention over this axis)
    ep    expert parallel (MoE experts split; all_to_all dispatch)
"""
from __future__ import annotations

import contextlib
import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax
from jax.sharding import Mesh

from ..base import MXNetError

__all__ = ["DeviceMesh", "make_mesh", "current_mesh", "get_mesh",
           "replica_mesh", "layout_key", "AXIS_NAMES"]

AXIS_NAMES = ("dp", "fsdp", "tp", "pp", "sp", "ep")


class DeviceMesh:
    """A jax.sharding.Mesh plus framework conveniences.

    Axes with size 1 are kept in the mesh (they cost nothing and keep
    PartitionSpecs stable as you scale an axis up), so model code can be
    written once against the full axis vocabulary.
    """

    def __init__(self, axes: Dict[str, int],
                 devices: Optional[Sequence] = None):
        if not axes:
            raise MXNetError("DeviceMesh needs at least one axis")
        self.axis_sizes = dict(axes)
        devices = list(devices) if devices is not None else jax.devices()
        need = int(np.prod(list(axes.values())))
        if need > len(devices):
            raise MXNetError(
                f"mesh {axes} needs {need} devices, only {len(devices)} "
                "available")
        grid = np.array(devices[:need]).reshape(tuple(axes.values()))
        self.mesh = Mesh(grid, tuple(axes.keys()))

    # ---- introspection ---------------------------------------------------
    @property
    def axis_names(self) -> Tuple[str, ...]:
        return self.mesh.axis_names

    def size(self, axis: Optional[str] = None) -> int:
        if axis is None:
            return self.mesh.size
        return self.axis_sizes.get(axis, 1)

    @property
    def devices(self):
        return list(self.mesh.devices.flat)

    def __contains__(self, axis: str) -> bool:
        return axis in self.axis_sizes

    # ---- scoping ---------------------------------------------------------
    def __enter__(self):
        _STATE.stack.append(self)
        self.mesh.__enter__()
        return self

    def __exit__(self, *exc):
        _STATE.stack.pop()
        return self.mesh.__exit__(*exc)

    def __repr__(self):
        ax = ", ".join(f"{k}={v}" for k, v in self.axis_sizes.items())
        return f"DeviceMesh({ax})"


class _MeshState(threading.local):
    def __init__(self):
        self.stack: List[DeviceMesh] = []


_STATE = _MeshState()


def make_mesh(axes: Union[Dict[str, int], Sequence[Tuple[str, int]], None] = None,
              devices: Optional[Sequence] = None,
              **axis_kw: int) -> DeviceMesh:
    """Build a DeviceMesh.

    make_mesh(dp=8)                       # pure data parallel
    make_mesh(dp=4, tp=2)                 # 2-way tensor parallel inside DP
    make_mesh({"dp": 2, "sp": 4})         # ring-attention mesh

    With no sizes given, all devices go onto a 1-D 'dp' mesh.
    """
    if axes is None:
        axes = {}
    elif not isinstance(axes, dict):
        axes = dict(axes)
    axes = {**axes, **axis_kw}
    if not axes:
        axes = {"dp": len(devices) if devices is not None else
                jax.device_count()}
    return DeviceMesh(axes, devices)


_REPLICA_MESHES: Dict[Tuple, DeviceMesh] = {}
_REPLICA_LOCK = threading.Lock()


def replica_mesh(devices: Sequence) -> DeviceMesh:
    """The 1-D data-parallel mesh over an explicit replica device list —
    the layout the unified SPMD training step (Trainer/SpmdUpdater)
    compiles under.  Cached per device tuple: every trainer with the
    same replica layout shares one Mesh object, so jit programs keyed on
    the mesh share executables too."""
    devs = tuple(devices)
    if len(set(devs)) != len(devs):
        raise MXNetError(
            f"replica_mesh: duplicate devices in {devs} — each replica "
            "must own a distinct device")
    with _REPLICA_LOCK:
        m = _REPLICA_MESHES.get(devs)
        if m is None:
            m = _REPLICA_MESHES[devs] = DeviceMesh({"dp": len(devs)},
                                                   devices=devs)
    return m


def layout_key(mesh: DeviceMesh) -> Tuple:
    """Hashable fingerprint of a mesh's layout for executable cache
    keys: axis names/sizes, device kinds, and the process span.  Two
    meshes with the same fingerprint compile to interchangeable
    programs (device *identity* is deliberately excluded so a restarted
    process with the same topology warm-starts from the persistent
    compile cache)."""
    devs = list(mesh.mesh.devices.flat)
    kinds = tuple(sorted({getattr(d, "device_kind", d.platform)
                          for d in devs}))
    return (tuple(mesh.axis_sizes.items()), kinds,
            len({d.process_index for d in devs}), len(devs))


def current_mesh() -> Optional[DeviceMesh]:
    """The innermost active `with mesh:` scope, or None."""
    return _STATE.stack[-1] if _STATE.stack else None


def get_mesh() -> DeviceMesh:
    m = current_mesh()
    if m is None:
        raise MXNetError("no DeviceMesh active; use `with make_mesh(...):`")
    return m
