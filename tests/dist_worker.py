"""Multi-process worker used by test_dist.py (not itself a test module).

Modeled on the reference's tests/nightly/dist_sync_kvstore.py: launched N
times (by tools/launch.py or the test harness) with the DMLC_* env
contract; each worker asserts dist_sync semantics and prints DIST_OK.
"""
import os
import sys

# force the CPU backend before any jax backend touch (the axon TPU plugin
# is process-global in this container; N workers cannot share one chip).
# The hybrid lane gives each process FOUR virtual devices (a 2-host pod
# slice in miniature); other modes keep 2.  Script-mode only: pytest
# IMPORTS this module (for hybrid_loss_and_data), and mutating the
# parent's XLA_FLAGS there would shrink its conftest-pinned 8-device
# backend.
_IS_SCRIPT = __name__ == "__main__"
_N_LOCAL = 4 if (_IS_SCRIPT and len(sys.argv) > 1
                 and sys.argv[1] == "hybrid") else 2
if _IS_SCRIPT:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={_N_LOCAL}")
import jax  # noqa: E402

if _IS_SCRIPT:
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402
from mxnet_tpu.parallel import dist  # noqa: E402


def mode_kvstore():
    """dist_sync push/pull/pushpull/row_sparse_pull across workers."""
    dist.init()
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert nw == int(os.environ["DMLC_NUM_WORKER"]), (nw, os.environ)

    # push/pull: store ends at sum over workers (no updater => overwrite
    # with the DCN-allreduced value)
    kv.init("a", nd.zeros((4, 3)))
    kv.push("a", nd.ones((4, 3)) * (rank + 1))
    out = nd.zeros((4, 3))
    kv.pull("a", out=out)
    expect = sum(r + 1 for r in range(nw))
    np.testing.assert_allclose(out.asnumpy(), expect * np.ones((4, 3)),
                               rtol=1e-6)

    # updater path: SGD lr=1 => weight -= sum(grads); every worker applies
    # the same allreduced grad so stores stay consistent
    kv2 = mx.kv.create("dist_sync")
    kv2.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
    kv2.init(0, nd.zeros((2, 2)))
    kv2.push(0, nd.ones((2, 2)) * (rank + 1))
    w = nd.zeros((2, 2))
    kv2.pull(0, out=w)
    np.testing.assert_allclose(w.asnumpy(), -expect * np.ones((2, 2)),
                               rtol=1e-6)

    # row_sparse grads across workers
    from mxnet_tpu.ndarray import sparse
    kv.init("rs", nd.zeros((6, 2)))
    g = sparse.row_sparse_array(
        (np.ones((1, 2), np.float32), [rank % 6]), shape=(6, 2))
    kv.push("rs", g)
    rs_out = sparse.zeros("row_sparse", (6, 2))
    kv.row_sparse_pull("rs", out=rs_out,
                       row_ids=nd.array([rank % 6], dtype="int32"))
    np.testing.assert_allclose(rs_out.todense().asnumpy()[rank % 6], [1, 1])

    kv.barrier()
    print(f"DIST_OK rank={rank}/{nw}", flush=True)


def mode_train():
    """2-process data-parallel MLP convergence via Trainer(dist_sync)."""
    dist.init()
    from mxnet_tpu.gluon import nn, loss as gloss, Trainer

    rank, nw = dist.rank(), dist.num_workers()
    np.random.seed(0)
    mx.random.seed(0)
    # same init on every worker (same seed), different data shards
    net = nn.Sequential()
    net.add(nn.Dense(16, activation="relu", in_units=4))
    net.add(nn.Dense(2, in_units=16))
    net.initialize(mx.initializer.Xavier())

    rng = np.random.RandomState(42)
    X = rng.randn(256, 4).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.int32)
    shard = slice(rank * 128 // nw * 2, (rank + 1) * 128 // nw * 2)
    Xs, ys = X[shard], y[shard]

    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1}, kvstore="dist_sync")
    lfn = gloss.SoftmaxCrossEntropyLoss()
    first = last = None
    for epoch in range(30):
        with mx.autograd.record():
            out = net(nd.array(Xs))
            loss = lfn(out, nd.array(ys))
        loss.backward()
        trainer.step(len(Xs) * nw)
        last = float(loss.mean().asnumpy())
        if first is None:
            first = last
    assert last < first * 0.5, (first, last)

    # weights must be bit-identical across workers after sync training
    w = net[0].weight.data().asnumpy()
    gathered = dist.allgather_np(w)
    for r in range(1, gathered.shape[0]):
        np.testing.assert_allclose(gathered[r], gathered[0], rtol=0, atol=0)
    print(f"DIST_OK rank={rank}/{nw} loss {first:.4f}->{last:.4f}",
          flush=True)


def mode_spmd():
    """Unified SPMD step across processes (ISSUE 9): ONE mesh program
    spanning every worker's devices, optimizer states ZeRO-sharded
    job-wide, the executable warm-started from the shared persistent
    compile cache.  Prints per-rank compile accounting for the parent
    to assert the cold/warm contract."""
    import hashlib
    import json

    dist.init()
    from mxnet_tpu.gluon.parameter import Parameter
    from mxnet_tpu.gluon.trainer import Trainer
    from mxnet_tpu.optimizer import spmd as spmd_mod

    rank, nw = dist.rank(), dist.num_workers()
    ctx = [mx.cpu(i) for i in range(_N_LOCAL)]
    shapes = [(32, 8), (64,), (16, 4)]
    init_rng = np.random.RandomState(7)  # same init on every worker
    params = []
    for i, shp in enumerate(shapes):
        p = Parameter(f"w{i}", shape=shp)
        p.initialize(ctx=ctx)
        p.set_data(nd.array(init_rng.randn(*shp).astype("f4")))
        params.append(p)
    tr = Trainer(params, "sgd",
                 {"learning_rate": 0.05, "momentum": 0.9},
                 kvstore="dist_sync", update_on_kvstore=False, spmd=True)
    for step in range(3):
        grng = np.random.RandomState(100 + step)
        for p in params:
            g = grng.randn(*p.shape).astype("f4")
            for r, gnd in enumerate(p.list_grad()):
                # distinct per GLOBAL replica: the in-graph reduce must
                # sum all of them identically on every shard
                scale = rank * _N_LOCAL + r + 1
                gnd._data = nd.array(g * scale, ctx=gnd.ctx).data
        tr.step(1)
    assert tr._spmd_active, "SPMD path disengaged on the dist job"
    u = tr._spmd_updater
    assert u.shard_factor() == nw * _N_LOCAL, u.shard_factor()

    # replicas bit-identical across the whole job
    h = hashlib.sha256()
    for p in params:
        for d in p.list_data():
            arr = np.ascontiguousarray(d.asnumpy())
            h.update(arr.tobytes())
    for p in params:
        r0 = p.list_data()[0].asnumpy()
        for d in p.list_data()[1:]:
            np.testing.assert_allclose(d.asnumpy(), r0, rtol=0, atol=0)
        gathered = dist.allgather_np(r0)
        for r in range(1, gathered.shape[0]):
            np.testing.assert_allclose(gathered[r], gathered[0],
                                       rtol=0, atol=0)

    stats = spmd_mod.compile_stats()
    print("SPMD_STATS " + json.dumps(
        {"rank": rank, "compiles": stats["count"],
         "cache_loads": stats["cache_loads"],
         "params_sha": h.hexdigest()}), flush=True)
    print(f"DIST_OK rank={rank}/{nw}", flush=True)


def mode_peerloss():
    """Failure detection: a worker whose peer died must abort loudly, not
    hang (ref role: ps-lite Van heartbeat timeout -> SURVEY.md §5)."""
    dist.init()
    rank = dist.rank()
    if rank == 1:
        # die without ever reaching the barrier
        print("DIST_OK rank=1 (exiting early, simulating peer death)",
              flush=True)
        os._exit(0)
    import time

    t0 = time.time()
    try:
        dist.barrier("peerloss", timeout=8)
    except mx.MXNetError as e:
        took = time.time() - t0
        assert "timed out" in str(e) and "unreachable" in str(e), e
        assert took < 60, took  # aborted promptly, did not deadlock
        print(f"DIST_OK rank=0 peer-loss detected in {took:.1f}s",
              flush=True)
        # normal exit would hang ~100s in the coordination service's
        # shutdown barrier (the peer can never arrive) -> fast abort
        dist.abort(code=0)
    raise AssertionError("barrier with a dead peer did not abort")


def hybrid_loss_and_data():
    """Shared fixture for the hybrid DCN+ICI lane: a deterministic tiny
    MLP (pure-jax params) + global batch, used by both the workers and
    the single-process oracle in test_dist.py."""
    import jax.numpy as jnp

    rng = np.random.RandomState(7)
    params = {
        "w1": jnp.asarray(rng.randn(4, 8).astype(np.float32) * 0.5),
        "b1": jnp.asarray(np.zeros(8, np.float32)),
        "w2": jnp.asarray(rng.randn(8, 3).astype(np.float32) * 0.5),
        "b2": jnp.asarray(np.zeros(3, np.float32)),
    }
    X = rng.randn(16, 4).astype(np.float32)
    y = rng.randint(0, 3, (16,)).astype(np.int32)

    def loss(p, xb, yb):
        h = jnp.tanh(xb @ p["w1"] + p["b1"])
        logits = h @ p["w2"] + p["b2"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(logp[jnp.arange(xb.shape[0]), yb])

    return params, X, y, loss


def mode_hybrid():
    """The pod topology in miniature (2 hosts x 4 chips): inside each
    process the gradient's batch reduction is an IN-GRAPH psum over a
    4-device dp mesh (the ICI stand-in, inserted by GSPMD); across the
    2 processes the per-process gradients ride the dist_sync KVStore
    (gloo = the DCN stand-in).  Rank 0 prints the final gradient so the
    parent test can assert equality with its single-process 8-device
    oracle."""
    import json

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mxnet_tpu import parallel

    dist.init()
    rank, nw = dist.rank(), dist.num_workers()
    params, X, y, loss = hybrid_loss_and_data()
    shard = X.shape[0] // nw
    Xs, ys = X[rank * shard:(rank + 1) * shard], \
        y[rank * shard:(rank + 1) * shard]

    # the ICI mesh must be built over THIS process's addressable chips
    # (jax.devices() is global after jax.distributed init — rank>0 would
    # otherwise get rank 0's devices and produce non-addressable grads)
    with parallel.make_mesh(dp=_N_LOCAL,
                            devices=jax.local_devices()) as mesh:
        xd = jax.device_put(jnp.asarray(Xs),
                            NamedSharding(mesh.mesh, P("dp")))
        yd = jax.device_put(jnp.asarray(ys),
                            NamedSharding(mesh.mesh, P("dp")))
        grads = jax.jit(jax.grad(loss))(params, xd, yd)

    # DCN hop: push per-process grads through dist_sync (sum across
    # workers), then renormalize the two half-batch means to the global
    # mean: sum_r mean_r / nw == mean over the global batch
    kv = mx.kv.create("dist_sync")
    out = {}
    for i, name in enumerate(sorted(grads)):
        g = mx.nd.array(np.asarray(grads[name]))
        kv.init(i, mx.nd.zeros(g.shape))
        kv.push(i, g)
        pulled = mx.nd.zeros(g.shape)
        kv.pull(i, out=pulled)
        out[name] = (pulled.asnumpy() / nw).tolist()

    # every worker must end with the identical global gradient
    flat = np.concatenate([np.asarray(v, np.float32).ravel()
                           for _, v in sorted(out.items())])
    gathered = dist.allgather_np(flat)
    for r in range(1, gathered.shape[0]):
        np.testing.assert_allclose(gathered[r], gathered[0],
                                   rtol=0, atol=0)
    if rank == 0:
        print("HYBRID_GRADS " + json.dumps(out), flush=True)
    print(f"DIST_OK rank={rank}/{nw}", flush=True)


if __name__ == "__main__":
    {"kvstore": mode_kvstore, "train": mode_train, "spmd": mode_spmd,
     "peerloss": mode_peerloss, "hybrid": mode_hybrid}[sys.argv[1]]()
