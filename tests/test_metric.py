"""Metric tests (model: tests/python/unittest/test_metric.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import metric, nd


def test_accuracy():
    m = metric.Accuracy()
    pred = nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = nd.array([1, 0, 0])
    m.update([label], [pred])
    name, value = m.get()
    assert name == "accuracy"
    assert value == pytest.approx(2.0 / 3.0)
    m.reset()
    assert np.isnan(m.get()[1])


def test_topk_accuracy():
    m = metric.TopKAccuracy(top_k=2)
    pred = nd.array([[0.1, 0.2, 0.7], [0.7, 0.2, 0.1]])
    label = nd.array([1, 2])
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(0.5)


def test_f1_and_mcc():
    pred = nd.array([[0.7, 0.3], [0.2, 0.8], [0.1, 0.9], [0.6, 0.4]])
    label = nd.array([0, 1, 0, 1])
    f1 = metric.F1()
    f1.update([label], [pred])
    # tp=1 (idx1), fp=1 (idx2), fn=1 (idx3) -> precision=recall=0.5, f1=0.5
    assert f1.get()[1] == pytest.approx(0.5)
    mcc = metric.MCC()
    mcc.update([label], [pred])
    assert -1.0 <= mcc.get()[1] <= 1.0


def test_mse_mae_rmse():
    pred = nd.array([[1.0], [2.0], [3.0]])
    label = nd.array([[2.0], [2.0], [5.0]])
    for name, expect in [("mse", (1 + 0 + 4) / 3.0),
                         ("mae", (1 + 0 + 2) / 3.0),
                         ("rmse", np.sqrt((1 + 0 + 4) / 3.0))]:
        m = metric.create(name)
        m.update([label], [pred])
        assert m.get()[1] == pytest.approx(expect), name


def test_perplexity_and_cross_entropy():
    pred = nd.array([[0.25, 0.75], [0.9, 0.1]])
    label = nd.array([1, 0])
    ce = metric.CrossEntropy()
    ce.update([label], [pred])
    expect = -(np.log(0.75) + np.log(0.9)) / 2
    assert ce.get()[1] == pytest.approx(expect, rel=1e-5)
    pp = metric.Perplexity(ignore_label=None)
    pp.update([label], [pred])
    assert pp.get()[1] == pytest.approx(np.exp(expect), rel=1e-5)


def test_composite_and_custom():
    comp = metric.CompositeEvalMetric([metric.Accuracy(), metric.MSE()])
    pred = nd.array([[0.3, 0.7]])
    label = nd.array([1])
    comp.update([label], [pred])
    names, values = comp.get()
    assert "accuracy" in names[0]

    cm = metric.CustomMetric(lambda l, p: float(np.mean(l)), name="mymetric")
    cm.update([nd.array([1.0, 3.0])], [nd.array([0.0, 0.0])])
    assert cm.get()[1] == pytest.approx(2.0)


def test_create_from_string_and_loss():
    m = metric.create("acc")
    assert isinstance(m, metric.Accuracy)
    loss = metric.Loss()
    loss.update(None, [nd.array([1.0, 3.0])])
    assert loss.get()[1] == pytest.approx(2.0)
