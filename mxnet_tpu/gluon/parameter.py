"""Gluon Parameter / ParameterDict.

TPU-native counterpart of python/mxnet/gluon/parameter.py: deferred shape
init, grad_req, per-context replicas, list_ctx/data/grad, and trainer
hookup.  A Parameter owns one NDArray per context (data-parallel replicas);
under a sharded mesh (kvstore 'xla' / parallel module) the single replica
is a sharded jax array instead.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray.ndarray import NDArray, array as nd_array
from .. import initializer as init_mod

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Raised when data() is called before shape is known (ref: same name)."""


class Parameter:
    def __init__(self, name: str, grad_req: str = "write", shape=None,
                 dtype="float32", lr_mult: float = 1.0, wd_mult: float = 1.0,
                 init=None, allow_deferred_init: bool = False,
                 differentiable: bool = True, stype="default",
                 grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._data: Optional[Dict[Context, NDArray]] = None
        self._grad: Optional[Dict[Context, NDArray]] = None
        self._deferred_init = None  # (initializer, ctx_list, default_init)
        self._trainer = None

    # ---- shape -----------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        unknown_ok = all(s1 in (0, None) or s1 == s2
                         for s1, s2 in zip(self._shape, new_shape)) \
            and len(self._shape) == len(new_shape)
        if not unknown_ok:
            raise MXNetError(
                f"cannot change shape of Parameter {self.name} from "
                f"{self._shape} to {tuple(new_shape)}")
        self._shape = tuple(new_shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {req!r}")
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._grad = None
            elif self._grad is None:
                self._init_grad()
        for ctxnd in (self._data or {}).values():
            ctxnd._ag_grad_req = req

    def _shape_is_known(self) -> bool:
        return self._shape is not None and all(
            s is not None and s > 0 for s in self._shape)

    # ---- init ------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit: bool = False):
        default_init = default_init or init_mod.Uniform(0.07)
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if not self._shape_is_known():
            if self.allow_deferred_init:
                self._deferred_init = (init, list(ctx), default_init)
                return
            raise MXNetError(
                f"cannot initialize Parameter {self.name}: unknown shape "
                f"{self._shape} and allow_deferred_init=False")
        self._finish_init(init, list(ctx), default_init)

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        if not self._shape_is_known():
            raise DeferredInitializationError(
                f"Parameter {self.name} has unknown shape {self._shape}")
        init, ctx, default_init = self._deferred_init
        self._deferred_init = None
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx_list: List[Context], default_init):
        buf = np.zeros(self._shape, dtype=np.float32)
        initializer = init_mod.create(init) if init is not None else \
            (init_mod.create(self.init) if self.init is not None else default_init)
        if init is not None or self.init is not None:
            initializer.init_array(self.name, buf)
        else:
            initializer(init_mod.InitDesc(self.name), buf)
        self._data = {}
        for c in ctx_list:
            self._data[c] = nd_array(buf, ctx=c, dtype=self.dtype)
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        self._grad = {}
        for c, d in self._data.items():
            d.attach_grad(self._grad_req)
            self._grad[c] = d.grad

    # ---- access ----------------------------------------------------------
    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"Parameter {self.name} has not finished deferred init")
            raise MXNetError(
                f"Parameter {self.name} has not been initialized. "
                "Call .initialize() first")
        if ctx is not None and ctx not in self._data:
            raise MXNetError(
                f"Parameter {self.name} was not initialized on context {ctx}; "
                f"it lives on {list(self._data)}")

    def data(self, ctx: Optional[Context] = None) -> NDArray:
        self._check_initialized(ctx)
        if ctx is None:
            ctx = next(iter(self._data))
        return self._data[ctx]

    def list_data(self) -> List[NDArray]:
        self._check_initialized()
        return list(self._data.values())

    def grad(self, ctx: Optional[Context] = None) -> NDArray:
        self._check_initialized(ctx)
        if self._grad is None:
            raise MXNetError(f"Parameter {self.name} has grad_req='null'")
        if ctx is None:
            ctx = next(iter(self._data))
        return self._data[ctx].grad

    def list_grad(self) -> List[NDArray]:
        self._check_initialized()
        return [d.grad for d in self._data.values()]

    def list_ctx(self) -> List[Context]:
        self._check_initialized()
        return list(self._data)

    def zero_grad(self):
        if self._data is None:
            return
        for d in self._data.values():
            d.zero_grad()

    def set_data(self, data):
        """Set value on all contexts (ref: Parameter.set_data)."""
        self.shape = data.shape
        if self._data is None:
            if self._deferred_init is not None:
                self._finish_deferred_init()
            else:
                raise MXNetError(
                    f"Parameter {self.name} has not been initialized")
        for c in list(self._data):
            src = data if isinstance(data, NDArray) else nd_array(data)
            newd = src.as_in_context(c)
            self._data[c]._data = newd.data.astype(self._data[c].data.dtype)

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._check_initialized()
        cur = self.data()
        self._data = {c: cur.as_in_context(c).copy() if c != cur.ctx else cur
                      for c in ctx}
        if self._grad_req != "null":
            self._init_grad()

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is None:
            return
        for c in list(self._data):
            self._data[c] = self._data[c].astype(dtype)
        if self._grad_req != "null":
            self._init_grad()

    def var(self):
        from ..symbol.symbol import var

        return var(self.name, shape=self._shape, dtype=self.dtype)

    def __repr__(self):
        return f"Parameter {self.name} (shape={self._shape}, dtype={self.dtype})"


class Constant(Parameter):
    """Non-learnable parameter (ref: gluon/parameter.py::Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, np.ndarray):
            value = np.asarray(value, dtype="float32")
        self.value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype if value.dtype != np.float64 else "float32",
                         init=init_mod.Constant(0))
        self._value_arr = value

    def _finish_init(self, init, ctx_list, default_init):
        self._data = {c: nd_array(self._value_arr, ctx=c, dtype=self.dtype)
                      for c in ctx_list}


class ParameterDict:
    """Ordered name->Parameter mapping with a shared prefix
    (ref: gluon/parameter.py::ParameterDict)."""

    def __init__(self, prefix: str = "", shared: Optional["ParameterDict"] = None):
        self._prefix = prefix
        self._params: Dict[str, Parameter] = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def get(self, name: str, **kwargs) -> Parameter:
        """Create-or-retrieve (shared lookup first)."""
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            param = Parameter(full, **kwargs)
            self._params[full] = param
        else:
            for k, v in kwargs.items():
                if k == "shape" and v is not None:
                    param.shape = v if not isinstance(v, int) else (v,)
                elif k == "init" and v is not None and param.init is None:
                    param.init = v
        return param

    def get_constant(self, name: str, value=None) -> Constant:
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            if value is None:
                raise MXNetError(f"no constant named {full} and no value given")
            param = Constant(full, value)
            self._params[full] = param
        return param

    def _get_impl(self, full_name):
        if full_name in self._params:
            return self._params[full_name]
        if self._shared is not None:
            p = self._shared._get_impl(full_name)
            if p is not None:
                self._params[full_name] = p
            return p
        return None

    def update(self, other: "ParameterDict"):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit: bool = False):
        default = init_mod.create(init) if init is not None else init_mod.Uniform(0.07)
        for p in self._params.values():
            p.initialize(None, ctx, default_init=default,
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self._params.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, fname: str, strip_prefix: str = ""):
        from ..serialization import save_ndarrays

        out = {}
        for name, p in self._params.items():
            key = name[len(strip_prefix):] if name.startswith(strip_prefix) else name
            out[key] = p.data().as_in_context(cpu())
        save_ndarrays(fname, out)

    def load(self, fname: str, ctx=None, allow_missing: bool = False,
             ignore_extra: bool = False, restore_prefix: str = ""):
        from ..serialization import load_ndarrays

        loaded = load_ndarrays(fname)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self._params:
                if name not in loaded:
                    raise MXNetError(f"Parameter {name} missing in file {fname}")
        for name, value in loaded.items():
            if name not in self._params:
                if ignore_extra:
                    continue
                raise MXNetError(f"Parameter {name} in file is not in this dict")
            p = self._params[name]
            if p._data is None:
                p.shape = value.shape
                p.initialize(ctx=ctx or [current_context()],
                             default_init=init_mod.Zero())
            p.set_data(value)

    # mapping protocol
    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __len__(self):
        return len(self._params)

    def __repr__(self):
        lines = "\n".join(f"  {p}" for p in self._params.values())
        return f"ParameterDict '{self._prefix}' (\n{lines}\n)"
