"""tools/perf_compare.py (ISSUE 10 satellite): the nightly bench-JSON
regression gate — >10% throughput drop or a new trace-integrity
failure vs the committed artifacts fails the run."""
import importlib.util
import json
import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    spec = importlib.util.spec_from_file_location(
        "perf_compare_under_test",
        os.path.join(_REPO, "tools", "perf_compare.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


pc = _load()


def _scaling(tp2=1.28, check_ok=True, parity_ok=True, mfu=None,
             data_wait=None):
    row2 = {"path": "spmd", "processes": 2, "global_throughput": tp2}
    if mfu is not None:
        row2["mfu"] = {"mean": mfu}
    if data_wait is not None:
        row2["data_wait_s"] = data_wait
    return {"sweep": [
        {"path": "spmd", "processes": 1, "global_throughput": 1.0,
         "trace_check_ok": True,
         "merged_trace": {"check_ok": check_ok}},
        row2,
    ], "parity": {"ok": parity_ok}}


def _health(gate_ok=True, skip_ok=True):
    return {"gate_ok": gate_ok,
            "stages": {"clean_run": {"ok": True},
                       "nonfinite_skip": {"ok": skip_ok}}}


def _resilience(gate_ok=True, bit_consistent=True, die_shrink_ok=True,
                mttr=2.5):
    runs = {name: {"ok": True, "mttr_s": mttr}
            for name in ("die_replace", "die_shrink", "hang_replace",
                         "hang_shrink")}
    runs["die_shrink"]["ok"] = die_shrink_ok
    elastic_ok = all(r["ok"] for r in runs.values())
    return {"gate_ok": gate_ok and bit_consistent and elastic_ok,
            "recovery": {"resume_bit_consistent": bit_consistent,
                         "recovery_time_to_first_step_s": 0.02},
            "breaker": {"breaker_opened": True,
                        "breaker_recovered": True,
                        "healthz_always_up": True,
                        "process_survived": True},
            "elastic": {"ok": elastic_ok, "runs": runs}}


def _goodput(gate_ok=True, preempt_ok=True, ratio=0.85):
    return {"gate_ok": gate_ok and preempt_ok,
            "stages": {
                "clean_run": {"ok": True, "goodput_ratio": ratio},
                "preemption": {"ok": preempt_ok},
                "multi_rank_merge": {
                    "ok": True, "job": {"goodput_ratio": 0.7}}}}


def _autotune(io_ok=True, train_ok=True):
    return {"gate_ok": io_ok and train_ok,
            "scenarios": {
                "mlp_train": {"ok": train_ok, "delta": 0.05},
                "io_bound": {"ok": io_ok, "delta": 0.01}}}


class TestCompareArtifact:
    def test_within_tolerance_ok(self):
        res = pc.compare_artifact("SCALING.json", _scaling(1.28),
                                  _scaling(1.20), tolerance=0.10)
        assert res["ok"] and not res["regressions"]

    def test_throughput_regression_fails(self):
        res = pc.compare_artifact("SCALING.json", _scaling(1.28),
                                  _scaling(1.0), tolerance=0.10)
        assert not res["ok"]
        assert "global_throughput" in res["regressions"][0]

    def test_improvement_never_fails(self):
        res = pc.compare_artifact("SCALING.json", _scaling(1.0),
                                  _scaling(10.0), tolerance=0.10)
        assert res["ok"]

    def test_new_integrity_failure_fails(self):
        res = pc.compare_artifact("SCALING.json", _scaling(),
                                  _scaling(check_ok=False),
                                  tolerance=0.10)
        assert not res["ok"]
        assert "merged_trace.check_ok" in \
            res["new_integrity_failures"][0]

    def test_preexisting_false_is_not_new(self):
        res = pc.compare_artifact("SCALING.json",
                                  _scaling(check_ok=False),
                                  _scaling(check_ok=False),
                                  tolerance=0.10)
        assert res["ok"]

    def test_fresh_only_check_lane_still_gates(self):
        base = _scaling()
        del base["parity"]
        res = pc.compare_artifact("SCALING.json", base,
                                  _scaling(parity_ok=False),
                                  tolerance=0.10)
        assert not res["ok"]
        assert "parity.ok" in res["new_integrity_failures"][0]

    def test_metric_only_on_one_side_skipped(self):
        base = {"sweep": [{"path": "spmd", "processes": 4,
                           "global_throughput": 9.0}]}
        res = pc.compare_artifact("SCALING.json", base, _scaling(),
                                  tolerance=0.10)
        assert res["ok"] and res["metrics"] == []

    def test_fused_and_compile_cache_extractors(self):
        fused_b = {"sizes": {"100": {"speedup": 2.3}}}
        fused_f = {"sizes": {"100": {"speedup": 1.5}}}
        res = pc.compare_artifact("FUSED_BENCH.json", fused_b, fused_f,
                                  tolerance=0.10)
        assert not res["ok"]
        cc_b = {"serving": {"speedup": 4.0}, "fused": {"speedup": 4.0},
                "gate_ok": True}
        cc_f = {"serving": {"speedup": 3.9}, "fused": {"speedup": 3.8},
                "gate_ok": False}
        res = pc.compare_artifact("COMPILE_CACHE.json", cc_b, cc_f,
                                  tolerance=0.10)
        assert not res["ok"]
        assert "gate_ok" in res["new_integrity_failures"][0]

    def test_mfu_regression_fails_even_with_flat_throughput(self):
        """ISSUE 11 satellite: an attribution regression (MFU drop)
        fails the gate even when samples/s look unchanged."""
        res = pc.compare_artifact("SCALING.json",
                                  _scaling(mfu=0.40),
                                  _scaling(mfu=0.20),
                                  tolerance=0.10)
        assert not res["ok"]
        assert "mfu" in res["regressions"][0]

    def test_mfu_collapse_to_zero_still_gates(self):
        """0.0 is a collapse, not an absent lane — the falsy-zero trap
        must not drop it from the extractor."""
        res = pc.compare_artifact("SCALING.json",
                                  _scaling(mfu=0.40),
                                  _scaling(mfu=0.0),
                                  tolerance=0.10)
        assert not res["ok"]
        assert "mfu" in res["regressions"][0]

    def test_data_wait_growth_fails(self):
        res = pc.compare_artifact("SCALING.json",
                                  _scaling(data_wait=0.10),
                                  _scaling(data_wait=0.50),
                                  tolerance=0.10)
        assert not res["ok"]
        assert "data_wait_s" in res["regressions"][0]

    def test_data_wait_noise_under_floor_passes(self):
        """Microsecond-scale data-wait growth on an idle box must not
        flap the gate: the absolute floor (0.05s) gates out timer
        noise that is huge in relative terms."""
        res = pc.compare_artifact("SCALING.json",
                                  _scaling(data_wait=0.0001),
                                  _scaling(data_wait=0.002),
                                  tolerance=0.10)
        assert res["ok"]

    def test_data_wait_improvement_passes(self):
        res = pc.compare_artifact("SCALING.json",
                                  _scaling(data_wait=0.50),
                                  _scaling(data_wait=0.10),
                                  tolerance=0.10)
        assert res["ok"]

    def test_health_strict_never_grandfathered(self):
        """HEALTH.json lanes are strict: a false verdict fails even
        when the committed baseline was ALREADY false."""
        res = pc.compare_artifact("HEALTH.json",
                                  _health(gate_ok=False),
                                  _health(gate_ok=False),
                                  tolerance=0.10)
        assert not res["ok"]
        assert "strict health lane" in \
            res["new_integrity_failures"][0]

    def test_health_stage_lane_gates(self):
        res = pc.compare_artifact("HEALTH.json", _health(),
                                  _health(skip_ok=False),
                                  tolerance=0.10)
        assert not res["ok"]
        assert "stages.nonfinite_skip.ok" in \
            res["new_integrity_failures"][0]

    def test_health_clean_passes(self):
        res = pc.compare_artifact("HEALTH.json", _health(), _health(),
                                  tolerance=0.10)
        assert res["ok"]

    def test_goodput_strict_never_grandfathered(self):
        """GOODPUT.json lanes follow the HEALTH policy: a false stage
        fails even when the committed baseline was ALREADY false."""
        res = pc.compare_artifact("GOODPUT.json",
                                  _goodput(preempt_ok=False),
                                  _goodput(preempt_ok=False),
                                  tolerance=0.10)
        assert not res["ok"]
        assert any("stages.preemption.ok" in f
                   for f in res["new_integrity_failures"])

    def test_goodput_ratio_gates_through_stage_not_pct_lane(self):
        """The ratio gates via the strict clean_run.ok check (absolute
        floor inside the report), NOT a relative-tolerance lane — the
        chaos scenarios' ratios are noise-dominated by design and a
        %-drop lane would flake the nightly."""
        res = pc.compare_artifact("GOODPUT.json", _goodput(ratio=0.9),
                                  _goodput(ratio=0.6),
                                  tolerance=0.10)
        assert res["ok"]  # both runs' stages ok: no flake on noise
        base = _goodput()
        fresh = _goodput()
        fresh["stages"]["clean_run"]["ok"] = False  # floor breached
        res = pc.compare_artifact("GOODPUT.json", base, fresh,
                                  tolerance=0.10)
        assert not res["ok"]
        assert any("stages.clean_run.ok" in f
                   for f in res["new_integrity_failures"])

    def test_goodput_clean_passes(self):
        res = pc.compare_artifact("GOODPUT.json", _goodput(),
                                  _goodput(), tolerance=0.10)
        assert res["ok"]

    def test_resilience_strict_never_grandfathered(self):
        """RESILIENCE.json follows the HEALTH/GOODPUT policy: every
        lane is strict — a recovery failure fails even when the
        committed baseline was already failing."""
        bad = _resilience(gate_ok=False, bit_consistent=False)
        res = pc.compare_artifact("RESILIENCE.json", bad, bad,
                                  tolerance=0.10)
        assert not res["ok"]
        assert any("recovery.resume_bit_consistent" in f
                   for f in res["new_integrity_failures"])
        assert any("gate_ok" in f
                   for f in res["new_integrity_failures"])

    def test_resilience_elastic_cells_gate(self):
        """Each (die|hang)x(replace|shrink) recovery cell is its own
        strict lane — one broken mode fails the nightly even when the
        aggregate flags happen to read true, and a cell that only
        exists in the fresh run (first --elastic nightly) still
        gates."""
        base = _resilience()
        fresh = _resilience(die_shrink_ok=False)
        fresh["gate_ok"] = True  # aggregate lies; the cell must gate
        fresh["elastic"]["ok"] = True
        res = pc.compare_artifact("RESILIENCE.json", base, fresh,
                                  tolerance=0.10)
        assert not res["ok"]
        assert any("elastic.die_shrink.ok" in f
                   for f in res["new_integrity_failures"])
        # fresh-only elastic stage (baseline predates --elastic)
        old = _resilience()
        del old["elastic"]
        res = pc.compare_artifact("RESILIENCE.json", old,
                                  _resilience(die_shrink_ok=False),
                                  tolerance=0.10)
        assert not res["ok"]

    def test_resilience_clean_passes_with_no_mttr_pct_lane(self):
        """MTTR gates absolutely inside the bench, not as a relative
        lane — a noisier-but-within-budget recovery must not flake
        the nightly."""
        base = _resilience(mttr=2.0)
        fresh = _resilience(mttr=9.0)  # 4.5x "slower", still in budget
        res = pc.compare_artifact("RESILIENCE.json", base, fresh,
                                  tolerance=0.10)
        assert res["ok"]
        assert not res["metrics"]  # no metric lanes at all: checks only

    def test_autotune_strict_never_grandfathered(self):
        """AUTOTUNE.json (ISSUE 16) follows the HEALTH/GOODPUT policy:
        a stored winner that no longer beats the measured defaults
        fails even when the committed artifact was already failing."""
        bad = _autotune(io_ok=False)
        res = pc.compare_artifact("AUTOTUNE.json", bad, bad,
                                  tolerance=0.10)
        assert not res["ok"]
        assert any("scenarios.io_bound.ok" in f
                   for f in res["new_integrity_failures"])
        assert any("gate_ok" in f
                   for f in res["new_integrity_failures"])

    def test_autotune_clean_passes_with_no_pct_lane(self):
        """The objective deltas are noise-dominated quick-sweep goodput
        ratios: the signal is ordinal (tuned >= default, per-scenario
        ok), never a relative-% metric lane."""
        res = pc.compare_artifact("AUTOTUNE.json", _autotune(),
                                  _autotune(), tolerance=0.10)
        assert res["ok"]
        assert not res["metrics"]

    def test_autotune_in_default_artifacts(self):
        assert "AUTOTUNE.json" in pc.DEFAULT_ARTIFACTS
        assert "AUTOTUNE.json" in pc.EXTRACTORS

    def test_serving_extractor(self):
        b = {"unbatched": {"qps": 588.7}, "batched": {"qps": 987.9},
             "batched_over_unbatched": 1.68}
        f = {"unbatched": {"qps": 600.0}, "batched": {"qps": 700.0},
             "batched_over_unbatched": 1.17}
        res = pc.compare_artifact("SERVING_BENCH.json", b, f,
                                  tolerance=0.10)
        assert not res["ok"]
        names = [r["metric"] for r in res["metrics"]
                 if r.get("regression")]
        assert "batched.qps" in names


class TestCli:
    def _dirs(self, tmp_path, base, fresh):
        bd, fd = tmp_path / "base", tmp_path / "fresh"
        bd.mkdir(), fd.mkdir()
        for d, payload in ((bd, base), (fd, fresh)):
            for name, doc in payload.items():
                (d / name).write_text(json.dumps(doc))
        return str(bd), str(fd)

    def test_clean_run_rc0_and_report(self, tmp_path):
        bd, fd = self._dirs(tmp_path,
                            {"SCALING.json": _scaling()},
                            {"SCALING.json": _scaling(1.25)})
        out = str(tmp_path / "rep.json")
        rc = pc.main(["--baseline-dir", bd, "--fresh-dir", fd,
                      "--artifacts", "SCALING.json", "--out", out])
        assert rc == 0
        rep = json.load(open(out))
        assert rep["ok"] and "SCALING.json" in rep["artifacts"]

    def test_regression_rc1(self, tmp_path):
        bd, fd = self._dirs(tmp_path,
                            {"SCALING.json": _scaling()},
                            {"SCALING.json": _scaling(0.5)})
        assert pc.main(["--baseline-dir", bd, "--fresh-dir", fd,
                        "--artifacts", "SCALING.json"]) == 1

    def test_missing_artifact_skips_not_fails(self, tmp_path):
        bd, fd = self._dirs(tmp_path, {},
                            {"SCALING.json": _scaling()})
        out = str(tmp_path / "rep.json")
        rc = pc.main(["--baseline-dir", bd, "--fresh-dir", fd,
                      "--artifacts", "SCALING.json", "--out", out])
        assert rc == 0
        assert json.load(open(out))["artifacts"]["SCALING.json"][
            "skipped"]

    def test_unknown_artifact_usage_error(self):
        assert pc.main(["--artifacts", "NOPE.json"]) == 2

    def test_git_baseline_against_head(self):
        """The nightly invocation shape: committed artifacts vs the
        work tree.  Committed == work tree unless a bench just ran, so
        this asserts the plumbing, not a verdict."""
        rc = pc.main(["--ref", "HEAD", "--fresh-dir", _REPO,
                      "--artifacts", "FUSED_BENCH.json"])
        assert rc in (0, 1)


def _scaling_attr(tp=1.3, gar=0.5, knob=0):
    """A SCALING doc whose row carries the mxtriage attribution lanes
    (what scaling_bench._phase_report now embeds)."""
    d = _scaling(tp)
    row = d["sweep"][1]
    row["phase_seconds"] = {
        "grad-allreduce": {"seconds": gar, "count": 3},
        "forward": {"seconds": 1.0, "count": 3}}
    row["data_wait_s"] = 0.01
    row["compiles"] = 1
    row["knobs"] = {"MXNET_SPMD_BUCKET_BYTES": knob}
    row["knob_fingerprint"] = f"kf-{knob}"
    row["hlo_fingerprints"] = ["aaa"]
    return d


class TestSuspects:
    """Regression attribution (ISSUE 13): a failing lane emits a
    ranked suspects section instead of failing mutely."""

    def test_badput_category_shift_ranked_as_suspect(self, tmp_path):
        """ISSUE 14: scaling rows embed goodput_ratio/badput_seconds;
        a category that grew (and a ratio that collapsed) must rank
        among the suspects of a failing lane."""
        def doc(tp, retry_s, ratio):
            d = _scaling_attr(tp=tp)
            row = d["sweep"][1]
            row["goodput_ratio"] = ratio
            row["badput_seconds"] = {"retry_backoff": retry_s,
                                     "comm_stall": 0.1}
            return d

        bd, fd = tmp_path / "b", tmp_path / "f"
        bd.mkdir(), fd.mkdir()
        (bd / "SCALING.json").write_text(
            json.dumps(doc(1.3, 0.0, 0.9)))
        (fd / "SCALING.json").write_text(
            json.dumps(doc(0.8, 2.0, 0.3)))
        out = str(tmp_path / "rep.json")
        rc = pc.main(["--baseline-dir", str(bd), "--fresh-dir",
                      str(fd), "--artifacts", "SCALING.json",
                      "--out", out])
        assert rc == 1
        rep = json.load(open(out))
        kinds = {(s["kind"], s["name"]) for s in rep["suspects"]}
        assert ("badput", "retry_backoff") in kinds
        assert ("goodput", "goodput_ratio") in kinds
        # unchanged comm_stall is not a suspect
        assert ("badput", "comm_stall") not in kinds

    def test_failing_lane_emits_ranked_suspects(self, tmp_path):
        bd, fd = tmp_path / "b", tmp_path / "f"
        bd.mkdir(), fd.mkdir()
        (bd / "SCALING.json").write_text(
            json.dumps(_scaling_attr(tp=1.3, gar=0.5)))
        (fd / "SCALING.json").write_text(
            json.dumps(_scaling_attr(tp=0.8, gar=1.5, knob=4096)))
        out = str(tmp_path / "rep.json")
        rc = pc.main(["--baseline-dir", str(bd), "--fresh-dir",
                      str(fd), "--artifacts", "SCALING.json",
                      "--out", out])
        assert rc == 1
        rep = json.load(open(out))
        sus = rep["suspects"]
        # top suspect names the regressed phase; the knob change rides
        # along with its old -> new values
        assert sus[0]["kind"] == "phase"
        assert sus[0]["name"] == "grad-allreduce"
        assert sus[0]["rank"] == 1
        assert sus[0]["artifact"] == "SCALING.json"
        knob = next(s for s in sus if s["kind"] == "knob")
        assert knob["name"] == "MXNET_SPMD_BUCKET_BYTES"
        per = rep["artifacts"]["SCALING.json"]
        assert per["suspects"][0]["name"] == "grad-allreduce"
        assert any("program fingerprints stable" in c
                   for c in per["context"])

    def test_clean_run_has_empty_suspects_array(self, tmp_path):
        """ISSUE 16: the top-level suspects array is a STABLE schema —
        always present (empty on a clean run) so tools/autotune.py
        --from-suspects parses an artifact, not a sometimes-there
        debugging extra.  Per-artifact suspect sections still only
        appear on failing lanes."""
        bd, fd = tmp_path / "b", tmp_path / "f"
        bd.mkdir(), fd.mkdir()
        (bd / "SCALING.json").write_text(json.dumps(_scaling_attr()))
        (fd / "SCALING.json").write_text(json.dumps(_scaling_attr()))
        out = str(tmp_path / "rep.json")
        assert pc.main(["--baseline-dir", str(bd), "--fresh-dir",
                        str(fd), "--artifacts", "SCALING.json",
                        "--out", out]) == 0
        rep = json.load(open(out))
        assert rep["suspects"] == []
        assert "suspects" not in rep["artifacts"]["SCALING.json"]

    def test_suspects_array_schema(self, tmp_path):
        """Every merged suspect carries the fields the autotune
        feedback channel consumes: kind, name, score, rank, artifact —
        ranked best-first from 1."""
        bd, fd = tmp_path / "b", tmp_path / "f"
        bd.mkdir(), fd.mkdir()
        (bd / "SCALING.json").write_text(
            json.dumps(_scaling_attr(tp=1.3, gar=0.5)))
        (fd / "SCALING.json").write_text(
            json.dumps(_scaling_attr(tp=0.8, gar=1.5, knob=4096)))
        out = str(tmp_path / "rep.json")
        assert pc.main(["--baseline-dir", str(bd), "--fresh-dir",
                        str(fd), "--artifacts", "SCALING.json",
                        "--out", out]) == 1
        sus = json.load(open(out))["suspects"]
        assert isinstance(sus, list) and sus
        for i, s in enumerate(sus):
            assert isinstance(s["kind"], str)
            assert isinstance(s["name"], str)
            assert isinstance(s["score"], (int, float))
            assert s["rank"] == i + 1
            assert s["artifact"] == "SCALING.json"

    def test_failing_lane_without_aggregates_still_reports(
            self, tmp_path):
        """Old-format artifacts (no embedded aggregates): the gate
        still fails normally, suspects just come back empty."""
        bd, fd = tmp_path / "b", tmp_path / "f"
        bd.mkdir(), fd.mkdir()
        (bd / "FUSED_BENCH.json").write_text(
            json.dumps({"sizes": {"100": {"speedup": 2.0}}}))
        (fd / "FUSED_BENCH.json").write_text(
            json.dumps({"sizes": {"100": {"speedup": 1.0}}}))
        out = str(tmp_path / "rep.json")
        rc = pc.main(["--baseline-dir", str(bd), "--fresh-dir",
                      str(fd), "--artifacts", "FUSED_BENCH.json",
                      "--out", out])
        assert rc == 1
        rep = json.load(open(out))
        assert rep["artifacts"]["FUSED_BENCH.json"]["suspects"] == []
