"""Convolution / pooling Gluon layers
(ref: python/mxnet/gluon/nn/conv_layers.py: _Conv, Conv1D/2D/3D,
Conv1DTranspose/2D/3D, _Pooling, Max/Avg/GlobalMax/GlobalAvg pools,
ReflectionPad2D)."""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from .basic_layers import Activation

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tup(x, n):
    if isinstance(x, (list, tuple)):
        return tuple(x)
    return (x,) * n


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, prefix=None, params=None):
        super().__init__(prefix, params)
        self._channels = channels
        self._in_channels = in_channels
        ndim = len(kernel_size)
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = adj
        self._op_name = op_name
        with self.name_scope():
            if op_name == "Convolution":
                wshape = (channels, in_channels // groups if in_channels else 0) \
                    + tuple(kernel_size)
            else:  # Deconvolution weight is (in, out/groups, *k)
                wshape = (in_channels, channels // groups if channels else 0) \
                    + tuple(kernel_size)
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(channels,),
                                            init=bias_initializer)
            self.act = Activation(activation, prefix=activation + "_") \
                if activation is not None else None

    def _infer_param_shapes(self, x, *args):
        c_axis = 1 if self._kwargs.get("layout", "NCHW")[1] == "C" else x.ndim - 1
        in_c = int(x.shape[c_axis])
        w = list(self.weight.shape)
        if self._op_name == "Convolution":
            w[1] = in_c // self._kwargs["num_group"]
        else:
            w[0] = in_c
            if w[1] == 0:
                w[1] = self._channels // self._kwargs["num_group"]
        self.weight.shape = tuple(w)

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        out = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, prefix=None, params=None):
        super().__init__(channels, _tup(kernel_size, 1), _tup(strides, 1),
                         _tup(padding, 1), _tup(dilation, 1), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         prefix=prefix, params=params)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, prefix=None,
                 params=None):
        super().__init__(channels, _tup(kernel_size, 2), _tup(strides, 2),
                         _tup(padding, 2), _tup(dilation, 2), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         prefix=prefix, params=params)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, prefix=None, params=None):
        super().__init__(channels, _tup(kernel_size, 3), _tup(strides, 3),
                         _tup(padding, 3), _tup(dilation, 3), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         prefix=prefix, params=params)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, prefix=None,
                 params=None):
        super().__init__(channels, _tup(kernel_size, 1), _tup(strides, 1),
                         _tup(padding, 1), _tup(dilation, 1), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=_tup(output_padding, 1),
                         prefix=prefix, params=params)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, prefix=None, params=None):
        super().__init__(channels, _tup(kernel_size, 2), _tup(strides, 2),
                         _tup(padding, 2), _tup(dilation, 2), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=_tup(output_padding, 2),
                         prefix=prefix, params=params)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, prefix=None,
                 params=None):
        super().__init__(channels, _tup(kernel_size, 3), _tup(strides, 3),
                         _tup(padding, 3), _tup(dilation, 3), groups, layout,
                         in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=_tup(output_padding, 3),
                         prefix=prefix, params=params)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=None, prefix=None,
                 params=None):
        super().__init__(prefix, params)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid",
            "layout": layout}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, prefix=None, params=None):
        super().__init__(_tup(pool_size, 1), _tup(strides, 1) if strides else None,
                         _tup(padding, 1), ceil_mode, False, "max", layout,
                         prefix=prefix, params=params)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, prefix=None, params=None):
        super().__init__(_tup(pool_size, 2), _tup(strides, 2) if strides else None,
                         _tup(padding, 2), ceil_mode, False, "max", layout,
                         prefix=prefix, params=params)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, prefix=None, params=None):
        super().__init__(_tup(pool_size, 3), _tup(strides, 3) if strides else None,
                         _tup(padding, 3), ceil_mode, False, "max", layout,
                         prefix=prefix, params=params)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, prefix=None,
                 params=None):
        super().__init__(_tup(pool_size, 1), _tup(strides, 1) if strides else None,
                         _tup(padding, 1), ceil_mode, False, "avg", layout,
                         count_include_pad, prefix=prefix, params=params)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 prefix=None, params=None):
        super().__init__(_tup(pool_size, 2), _tup(strides, 2) if strides else None,
                         _tup(padding, 2), ceil_mode, False, "avg", layout,
                         count_include_pad, prefix=prefix, params=params)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 prefix=None, params=None):
        super().__init__(_tup(pool_size, 3), _tup(strides, 3) if strides else None,
                         _tup(padding, 3), ceil_mode, False, "avg", layout,
                         count_include_pad, prefix=prefix, params=params)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", prefix=None, params=None):
        super().__init__((1,), None, (0,), False, True, "max", layout,
                         prefix=prefix, params=params)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", prefix=None, params=None):
        super().__init__((1, 1), None, (0, 0), False, True, "max", layout,
                         prefix=prefix, params=params)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", prefix=None, params=None):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "max",
                         layout, prefix=prefix, params=params)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", prefix=None, params=None):
        super().__init__((1,), None, (0,), False, True, "avg", layout,
                         prefix=prefix, params=params)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", prefix=None, params=None):
        super().__init__((1, 1), None, (0, 0), False, True, "avg", layout,
                         prefix=prefix, params=params)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", prefix=None, params=None):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "avg",
                         layout, prefix=prefix, params=params)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, prefix=None, params=None):
        super().__init__(prefix, params)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = tuple(padding)

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)
