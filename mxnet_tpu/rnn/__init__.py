"""Legacy mx.rnn namespace (ref: python/mxnet/rnn/): symbolic RNN cells
for BucketingModule workflows + bucketed sentence IO.  New code should
prefer gluon.rnn (imperative/hybridizable) or the fused `RNN` op; this
surface exists so reference training scripts run unmodified."""
from .rnn_cell import (BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       SequentialRNNCell, BidirectionalCell, DropoutCell,
                       ResidualCell, FusedRNNCell)
from .io import BucketSentenceIter, encode_sentences

__all__ = ["BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "BidirectionalCell", "DropoutCell",
           "ResidualCell", "FusedRNNCell", "BucketSentenceIter",
           "encode_sentences"]
