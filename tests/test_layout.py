"""NHWC (channels-last) layout support vs the NCHW default.

TPU rationale: XLA's layout assignment makes NHWC the natural conv layout
on the MXU; the framework keeps weights in (O, I/g, *k) for EVERY data
layout so checkpoints are layout-independent (ref: Convolution layout param
in src/operator/nn/convolution-inl.h).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _rand(*shape):
    return np.random.RandomState(0).rand(*shape).astype("float32")


def test_convolution_nhwc_matches_nchw():
    x = _rand(2, 8, 10, 10)
    w = _rand(16, 8, 3, 3)
    b = _rand(16)
    o1 = nd.Convolution(nd.array(x), nd.array(w), nd.array(b), kernel=(3, 3),
                        stride=(2, 2), pad=(1, 1), num_filter=16).asnumpy()
    o2 = nd.Convolution(nd.array(x.transpose(0, 2, 3, 1)), nd.array(w),
                        nd.array(b), kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                        num_filter=16, layout="NHWC").asnumpy()
    np.testing.assert_allclose(o1, o2.transpose(0, 3, 1, 2), rtol=1e-5,
                               atol=1e-5)


def test_deconvolution_nhwc_matches_nchw():
    x = _rand(2, 8, 10, 10)
    w = _rand(8, 4, 3, 3)
    o1 = nd.Deconvolution(nd.array(x), nd.array(w), None, kernel=(3, 3),
                          stride=(2, 2), pad=(1, 1), num_filter=4,
                          no_bias=True).asnumpy()
    o2 = nd.Deconvolution(nd.array(x.transpose(0, 2, 3, 1)), nd.array(w),
                          None, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                          num_filter=4, no_bias=True,
                          layout="NHWC").asnumpy()
    np.testing.assert_allclose(o1, o2.transpose(0, 3, 1, 2), rtol=1e-5,
                               atol=1e-5)


@pytest.mark.parametrize("pool_type", ["max", "avg"])
def test_pooling_nhwc_matches_nchw(pool_type):
    x = _rand(2, 8, 11, 11)
    kw = dict(kernel=(3, 3), stride=(2, 2), pad=(1, 1), pool_type=pool_type,
              pooling_convention="full")
    o1 = nd.Pooling(nd.array(x), **kw).asnumpy()
    o2 = nd.Pooling(nd.array(x.transpose(0, 2, 3, 1)), layout="NHWC",
                    **kw).asnumpy()
    np.testing.assert_allclose(o1, o2.transpose(0, 3, 1, 2), rtol=1e-5,
                               atol=1e-5)


def test_global_pooling_nhwc():
    x = _rand(2, 8, 5, 5)
    o1 = nd.Pooling(nd.array(x), global_pool=True, pool_type="avg",
                    kernel=(1, 1)).asnumpy()
    o2 = nd.Pooling(nd.array(x.transpose(0, 2, 3, 1)), global_pool=True,
                    pool_type="avg", kernel=(1, 1), layout="NHWC").asnumpy()
    np.testing.assert_allclose(o1, o2.transpose(0, 3, 1, 2), rtol=1e-6,
                               atol=1e-6)


def test_resnet_nhwc_matches_nchw():
    from mxnet_tpu.gluon.model_zoo import vision
    x = _rand(2, 3, 32, 32)
    outs = {}
    for lay in ["NCHW", "NHWC"]:
        np.random.seed(0)
        mx.random.seed(0)
        net = vision.resnet18_v1(classes=10, layout=lay)
        net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
        xi = x if lay == "NCHW" else x.transpose(0, 2, 3, 1)
        with mx.autograd.pause():
            outs[lay] = net(nd.array(xi)).asnumpy()
    np.testing.assert_allclose(outs["NCHW"], outs["NHWC"], rtol=1e-4,
                               atol=2e-4)


@pytest.mark.slow  # ~22s: full resnet NHWC train step; nightly
def test_resnet_nhwc_trains():
    """One SPMDTrainer step in NHWC — the bench.py configuration."""
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet18_v1(classes=10, layout="NHWC")
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    with mx.autograd.pause():
        net(nd.zeros((1, 16, 16, 3), ctx=mx.cpu()))
    images = _rand(4, 16, 16, 3)
    labels = np.array([0, 1, 2, 3], np.int32)
    with parallel.make_mesh(dp=1):
        trainer = parallel.SPMDTrainer(
            net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.05})
        l0 = float(trainer.step(images, labels).asnumpy())
        for _ in range(5):
            loss = trainer.step(images, labels)
        l1 = float(loss.asnumpy())
    assert np.isfinite(l0) and np.isfinite(l1)
    assert l1 < l0, (l0, l1)
