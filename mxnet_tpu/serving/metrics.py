"""Per-model serving metrics, riding the telemetry registry.

Every counter/gauge here is a `telemetry` registry child labeled
`{model, version}` — so one Prometheus scrape (`GET /metrics` on the
HTTP front end) sees every model's requests/rejections/cache hits, and
request latency lands in a fixed-bucket histogram
(`mx_serving_request_latency_seconds`).  The JSON `snapshot()` keeps
its original dict shape (QPS, p50/p99 latency, batch occupancy, queue
depth...) so existing dashboards and tests read on unchanged.

While the profiler is capturing, updates are mirrored as chrome-trace
counter lanes (`"ph": "C"`) under the "serving" category — the same
lanes the seed emitted through `profiler.Counter`.

Construction RESETS the label set's children: a new `_ModelEntry` for
the same (model, version) is a lifecycle restart (the Prometheus
counter-reset convention), which also keeps per-test counts hermetic.
Corollary: the registry has ONE time series per (model, version) per
process — two repositories serving the same model version in one
process share (and reset) each other's series, exactly as two scrape
targets behind one exporter would.  Run one repository per process.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

from .. import profiler as _prof
from ..analysis import sanitizer as _mxsan
from ..telemetry import instruments as _ins
from ..telemetry import tracing as _tracing

# completed-request latencies kept for percentile estimates; a bounded
# ring so a long-lived server's memory stays flat
_LATENCY_RING = 4096


def _percentile(sorted_vals, q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class ModelMetrics:
    """One model-version's serving counters + latency ring."""

    COUNTERS = (
        "requests", "completed", "failed", "rejected",
        "deadline_expired", "batches", "batched_rows", "padded_rows",
        "cache_hits", "cache_misses", "queue_depth",
        # resilience: transient-executor retries that exhausted their
        # budget, 503s shed by an open circuit breaker, and drain
        # deadlines that abandoned queued work at shutdown
        "retries_exhausted", "breaker_rejected", "drain_timeouts",
    )
    # queue_depth is the one point-in-time value in the tuple — it maps
    # to a gauge family; everything else is a monotone counter
    _GAUGES = ("queue_depth",)

    def __init__(self, model: str, version: int):
        self.model, self.version = model, version
        self._c: Dict[str, object] = {}
        for name in self.COUNTERS:
            if name in self._GAUGES:
                child = _ins.serving_queue_depth(model, version)
            else:
                child = _ins.serving_counter(name, model, version)
            child.reset()
            self._c[name] = child
        self._latency_hist = _ins.serving_request_latency(model, version)
        self._latency_hist.reset()  # lifecycle restart covers the
        # histogram too — requests_total=0 with a populated latency
        # series would desync every rate-vs-histogram readout
        self._lock = threading.Lock()
        # (done_t, latency_s); mxsan: every access holds self._lock
        self._lat = _mxsan.track(
            deque(maxlen=_LATENCY_RING),
            f"serving.metrics[{model}/v{version}]._lat")
        self._started = time.perf_counter()

    def _lane(self, name: str) -> str:
        return f"serving/{self.model}/v{self.version}/{name}"

    def bump(self, name: str, d: int = 1) -> None:
        c = self._c[name]
        if not _prof._running:
            c.inc(d)
            return
        # chrome counter lane while capturing: inc and emit under one
        # lock so concurrent bumps cannot interleave into (later ts,
        # smaller value) samples — the trace integrity gate asserts
        # cumulative lanes are monotone in timestamp order
        with self._lock:
            v = c.inc(d)
            _tracing.counter_event(self._lane(name), v, cat="serving")

    def gauge(self, name: str, v: int) -> None:
        if not _prof._running:
            self._c[name].set(v)
            return
        with self._lock:
            self._c[name].set(v)
            _tracing.counter_event(self._lane(name), v, cat="serving")

    def value(self, name: str) -> int:
        return int(self._c[name].value)

    def observe_latency(self, seconds: float) -> None:
        self._latency_hist.observe(seconds)
        with self._lock:
            self._lat.append((time.perf_counter(), seconds))

    def snapshot(self) -> dict:
        with self._lock:
            lat = list(self._lat)
        now = time.perf_counter()
        vals = sorted(s for _, s in lat)
        # QPS over the ring's span (a full ring measures the recent
        # window; a part-full ring measures since startup)
        span = (now - (lat[0][0] if len(lat) == self._lat.maxlen
                       else self._started)) or 1e-9
        batched = self.value("batched_rows")
        padded = self.value("padded_rows")
        snap = {name: self.value(name) for name in self.COUNTERS}
        snap.update({
            "model": self.model,
            "version": self.version,
            "qps": round(len(lat) / span, 3),
            "p50_latency_ms": None if not vals else
            round(_percentile(vals, 0.50) * 1e3, 3),
            "p99_latency_ms": None if not vals else
            round(_percentile(vals, 0.99) * 1e3, 3),
            # fraction of launched rows that were real requests (the
            # rest was bucket padding); 1.0 = no padding waste
            "batch_occupancy": None if not padded else
            round(batched / padded, 4),
            "mean_batch_rows": None if not snap["batches"] else
            round(batched / snap["batches"], 2),
        })
        return snap
