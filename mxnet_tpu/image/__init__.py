"""Image IO + augmenters (ref: python/mxnet/image/image.py).

The reference decodes with OpenCV; this container has no OpenCV, so
decode/encode route through TensorFlow's CPU image codecs (installed),
with a raw-npy fallback.  Augmenter classes mirror the reference's
CreateAugmenter family; heavy ImageNet-scale decode belongs to the
native pipeline.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, array as nd_array

__all__ = ["imread", "imdecode", "imdecode_np", "imencode", "imresize",
           "resize_short", "fixed_crop", "center_crop", "random_crop",
           "color_normalize", "CreateAugmenter", "Augmenter",
           "ResizeAug", "ForceResizeAug", "RandomCropAug", "CenterCropAug",
           "HorizontalFlipAug", "CastAug", "ColorNormalizeAug",
           "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
           "RandomOrderAug"]

_TF = None


def _tf():
    global _TF
    if _TF is None:
        import tensorflow as tf

        tf.config.set_visible_devices([], "GPU")
        _TF = tf
    return _TF


def _cv2():
    try:
        import cv2

        return cv2
    except ImportError:
        return None


def imdecode_np(buf: bytes, iscolor: int = 1) -> np.ndarray:
    """Decode JPEG/PNG bytes to an HWC uint8 numpy array (RGB).
    Prefers OpenCV (the reference's codec, ~10x faster than the TF
    fallback) when installed."""
    if len(buf) >= 6 and buf[:6] == b"\x93NUMPY":
        import io

        return np.load(io.BytesIO(buf))
    cv2 = _cv2()
    if cv2 is not None:
        img = cv2.imdecode(np.frombuffer(buf, np.uint8),
                           cv2.IMREAD_COLOR if iscolor
                           else cv2.IMREAD_GRAYSCALE)
        if img is not None:
            if iscolor:
                img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
            else:
                img = img[..., None]
            return img
    tf = _tf()
    img = tf.io.decode_image(buf, channels=3 if iscolor else 1,
                             expand_animations=False)
    return img.numpy()


def imdecode(buf, flag: int = 1, to_rgb: int = 1, out=None) -> NDArray:
    """ref: image.py::imdecode (flag 1=color, 0=gray)."""
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    return nd_array(imdecode_np(bytes(buf), flag))


def imencode(img: np.ndarray, quality: int = 95, fmt: str = ".jpg") -> bytes:
    if isinstance(img, NDArray):
        img = img.asnumpy()
    img = np.ascontiguousarray(img).astype(np.uint8)
    cv2 = _cv2()
    # cv2 fast path only for layouts whose channel semantics are clear
    # (grayscale / RGB); RGBA etc fall through to the TF encoders
    if cv2 is not None and fmt in (".jpg", ".jpeg", ".png") and (
            img.ndim == 2 or img.shape[-1] in (1, 3)):
        bgr = cv2.cvtColor(img, cv2.COLOR_RGB2BGR) if img.ndim == 3 \
            and img.shape[-1] == 3 else img
        params = [cv2.IMWRITE_JPEG_QUALITY, quality] \
            if fmt != ".png" else []
        ok, buf = cv2.imencode(".png" if fmt == ".png" else ".jpg", bgr,
                               params)
        if ok:
            return buf.tobytes()
    tf = _tf()
    if fmt in (".jpg", ".jpeg"):
        return tf.io.encode_jpeg(img, quality=quality).numpy()
    if fmt == ".png":
        return tf.io.encode_png(img).numpy()
    raise MXNetError(f"unsupported image format {fmt}")


def imread(filename: str, flag: int = 1, to_rgb: int = 1) -> NDArray:
    """ref: image.py::imread."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag, to_rgb)


def imresize(src, w: int, h: int, interp: int = 1) -> NDArray:
    from ..gluon.data.vision.transforms import _resize_np

    a = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    return nd_array(_resize_np(a, (w, h)))


def resize_short(src, size: int, interp: int = 2) -> NDArray:
    a = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = a.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(a, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2) -> NDArray:
    a = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    out = a[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(out, size[0], size[1], interp)
    return nd_array(out)


def center_crop(src, size, interp=2):
    a = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = a.shape[:2]
    new_w, new_h = size
    x0 = max((w - new_w) // 2, 0)
    y0 = max((h - new_h) // 2, 0)
    out = fixed_crop(a, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    a = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = a.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = np.random.randint(0, w - new_w + 1)
    y0 = np.random.randint(0, h - new_h + 1)
    out = fixed_crop(a, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None) -> NDArray:
    a = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    a = a.astype("float32") - np.asarray(mean, dtype="float32")
    if std is not None:
        a = a / np.asarray(std, dtype="float32")
    return nd_array(a)


class Augmenter:
    """ref: image.py::Augmenter."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if np.random.rand() < self.p:
            return nd_array(src.asnumpy()[:, ::-1].copy())
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + np.random.uniform(-self.brightness, self.brightness)
        return nd_array(src.asnumpy().astype("float32") * alpha)


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        a = src.asnumpy().astype("float32")
        alpha = 1.0 + np.random.uniform(-self.contrast, self.contrast)
        gray = a.mean()
        return nd_array(gray + alpha * (a - gray))


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        a = src.asnumpy().astype("float32")
        alpha = 1.0 + np.random.uniform(-self.saturation, self.saturation)
        gray = (a * np.array([0.299, 0.587, 0.114])).sum(-1, keepdims=True)
        return nd_array(gray + alpha * (a - gray))


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in np.random.permutation(self.ts):
            src = t(src)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """ref: image.py::CreateAugmenter — the standard augmenter pipeline."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    jitters = []
    if brightness > 0:
        jitters.append(BrightnessJitterAug(brightness))
    if contrast > 0:
        jitters.append(ContrastJitterAug(contrast))
    if saturation > 0:
        jitters.append(SaturationJitterAug(saturation))
    if jitters:
        auglist.append(RandomOrderAug(jitters))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist
