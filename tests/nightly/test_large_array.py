"""Large-tensor boundary tests (ref: tests/nightly/test_large_array.py —
arrays past 2^31 ELEMENTS, the int32-offset boundary).

Layout mirrors the reference's regime: total size crosses 2^31 while
every DIMENSION stays under 2^31 (its LARGE_X * SMALL_Y shapes) — the
regime all indexing ops support on the x32 jax default.  Per-dimension
sizes past 2^31 are a narrower surface: static slicing works at any
offset, but dynamic indexing (take/gather) is capped per-dim by int32
index arithmetic — asserted and documented here (docs/sparse.md notes
the same class of ceiling; the reference gates the equivalent behind its
USE_INT64_TENSOR_SIZE build flag, SURVEY §5 config tiers).

int8 keeps each big array ~2.1GB so the lane runs in a dev-box RAM
budget (~8GB peak) while still crossing the element-count boundary.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd

# 2^16 * (2^15 + 8) = 2^31 + 2^19 elements > 2^31, dims < 2^31
ROWS, COLS = 2 ** 16, 2 ** 15 + 8
TOTAL = ROWS * COLS


def test_total_elements_cross_2g_slice_and_reduce():
    x = nd.zeros((ROWS, COLS), dtype="int8")
    assert x.size == TOTAL > 2 ** 31
    # plant values in the far corner (beyond flat offset 2^31)
    x[ROWS - 1, COLS - 4:] = 3
    tail = x[ROWS - 1, COLS - 8:].asnumpy()
    np.testing.assert_array_equal(tail, [0, 0, 0, 0, 3, 3, 3, 3])
    # row-reduction touching every element; int64 accumulator via dtype
    s = x.sum(axis=1)
    assert s.shape == (ROWS,)
    assert int(s[ROWS - 1].asnumpy()) == 12
    assert int(s[0].asnumpy()) == 0


def test_take_rows_beyond_2g_flat_offset():
    x = nd.zeros((ROWS, COLS), dtype="int8")
    x[ROWS - 1, 0] = 7
    got = nd.take(x, nd.array([0, ROWS - 1], dtype="int32"))
    assert got.shape == (2, COLS)
    assert int(got[1, 0].asnumpy()) == 7
    assert int(got[0, 0].asnumpy()) == 0


def test_argmax_and_broadcast_at_scale():
    x = nd.zeros((ROWS, COLS), dtype="int8")
    x[ROWS - 2, COLS - 2] = 5
    am = nd.argmax(x.reshape((ROWS, COLS)), axis=0)
    assert int(am[COLS - 2].asnumpy()) == ROWS - 2
    y = nd.broadcast_add(x, nd.ones((1, COLS), dtype="int8"))
    assert int(y[ROWS - 2, COLS - 2].asnumpy()) == 6
    assert int(y[0, 0].asnumpy()) == 1


def test_single_dim_beyond_2g_static_slice():
    """>2^31 in ONE dim: allocation + static slicing work at any offset
    (slice bounds are python ints, not device int32)."""
    n = 2 ** 31 + 64
    x = nd.zeros((n,), dtype="int8")
    assert x.shape == (n,)
    tail = x[n - 4:n].asnumpy()
    np.testing.assert_array_equal(tail, [0, 0, 0, 0])
    mid = x[2 ** 31: 2 ** 31 + 4]
    assert mid.shape == (4,)
    # Ellipsis is a basic key and must keep working on big arrays
    assert x[...].shape == (n,)
    assert x[..., 5:9].shape == (4,)


def test_single_dim_beyond_2g_writes():
    """Basic-key writes on a >2^31 dim must be CORRECT at every offset:
    raw jnp silently DROPS even small-offset writes here (int32 clamp
    overflow) and raises OverflowError past 2^31 — the NDArray update
    path routes through static slice+concat instead.  Advanced-key
    writes refuse loudly rather than corrupt."""
    import pytest

    n = 2 ** 31 + 64
    x = nd.zeros((n,), dtype="int8")
    x[5] = 1                       # raw jnp silently no-ops this one
    x[n - 3] = 2                   # raw jnp raises OverflowError here
    x[2 ** 31 + 4:2 ** 31 + 8] = 3
    assert int(x[5].asnumpy()) == 1
    assert int(x[4].asnumpy()) == 0
    assert int(x[n - 3].asnumpy()) == 2
    np.testing.assert_array_equal(
        x[2 ** 31 + 2:2 ** 31 + 10].asnumpy(),
        [0, 0, 3, 3, 3, 3, 0, 0])
    with pytest.raises(mx.MXNetError, match="2\\^31"):
        x[nd.array([1, 2], dtype="int32")] = 9
    # advanced READS refuse too (they would silently return garbage)
    with pytest.raises(mx.MXNetError, match="2\\^31"):
        x[nd.array([1, 2], dtype="int32")]
    # empty slices stay valid no-ops, not errors
    assert x[10:5].shape == (0,)
    x[10:5] = 4
    assert int(x[5].asnumpy()) == 1


def test_unnarrowed_big_axis_write_chunks():
    """A write that does NOT narrow the >2^31 axis (x[:, 1] = v) must go
    through the chunked band path — one scatter across the whole axis
    would hit the int32 clamp overflow."""
    n = 2 ** 31 + 64
    x = nd.zeros((n, 2), dtype="int8")
    x[:, 1] = 1
    assert int(x[5, 1].asnumpy()) == 1
    assert int(x[n - 7, 1].asnumpy()) == 1
    assert int(x[5, 0].asnumpy()) == 0
    assert int(x[n - 7, 0].asnumpy()) == 0


def test_reshape_transpose_roundtrip_at_scale():
    x = nd.zeros((ROWS, COLS), dtype="int8")
    x[123, 456] = 9
    y = x.reshape((COLS, ROWS))
    z = y.reshape((ROWS, COLS))
    assert int(z[123, 456].asnumpy()) == 9
    t = nd.transpose(x, axes=(1, 0))
    assert int(t[456, 123].asnumpy()) == 9
