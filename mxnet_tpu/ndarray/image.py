"""mx.nd.image — the image-op namespace over the _image_* registry ops
(ref: python/mxnet/ndarray/image.py generated namespace).  The random_*
variants thread a PRNG key from the global provider, like nd.Dropout."""
from __future__ import annotations

from .. import random as _random
from ..ops.registry import invoke

__all__ = ["to_tensor", "normalize", "resize", "crop", "flip_left_right",
           "flip_up_down", "random_flip_left_right", "random_flip_up_down",
           "random_brightness", "random_contrast", "random_saturation"]


def to_tensor(data):
    return invoke("_image_to_tensor", data)


def normalize(data, mean=(0.0,), std=(1.0,)):
    return invoke("_image_normalize", data, mean=tuple(mean),
                  std=tuple(std))


def resize(data, size=None, keep_ratio=False, interp=1):
    return invoke("_image_resize", data, size=size, keep_ratio=keep_ratio,
                  interp=interp)


def crop(data, x, y, width, height):
    return invoke("_image_crop", data, x0=x, y0=y, width=width,
                  height=height)


def flip_left_right(data):
    return invoke("_image_flip_left_right", data)


def flip_up_down(data):
    return invoke("_image_flip_up_down", data)


def random_flip_left_right(data):
    return invoke("_image_random_flip_left_right", data,
                  _random.next_key())


def random_flip_up_down(data):
    return invoke("_image_random_flip_up_down", data, _random.next_key())


def random_brightness(data, min_factor=0.5, max_factor=1.5):
    return invoke("_image_random_brightness", data, _random.next_key(),
                  min_factor=min_factor, max_factor=max_factor)


def random_contrast(data, min_factor=0.5, max_factor=1.5):
    return invoke("_image_random_contrast", data, _random.next_key(),
                  min_factor=min_factor, max_factor=max_factor)


def random_saturation(data, min_factor=0.5, max_factor=1.5):
    return invoke("_image_random_saturation", data, _random.next_key(),
                  min_factor=min_factor, max_factor=max_factor)
