#!/usr/bin/env python
"""Per-run health verdict: exercise the mxhealth detection paths and
the alert engine against known-answer scenarios, write HEALTH.json.

The nightly runs this (tools/run_nightly.py, health stage) and
perf_compare gates on it with STRICT lanes — a health stage that stops
detecting is never grandfathered.  Stages:

  * ``clean_run``       — a small healthy training run must come out
                          verdict "healthy" with finite norms sampled;
  * ``nonfinite_record``/``nonfinite_raise``/``nonfinite_skip`` — a
                          chaos-seeded NaN gradient at a chosen step
                          must be detected on EXACTLY that step under
                          each policy; ``skip_step``'s params must be
                          bit-identical (np.array_equal) to an
                          uninterrupted twin trained without the
                          corrupted batch;
  * ``alert_engine``    — a synthetic metric scenario must fire after
                          its for-duration and clear on recovery;
  * ``straggler``       — the merged-trace straggler detector must
                          flag a known straggling rank (synthetic skew
                          table; pass ``--traces r0.json r1.json`` to
                          analyze real per-rank dumps instead).

    python tools/health_report.py --out HEALTH.json
    python tools/health_report.py --no-gate        # tier-1 smoke
    python tools/health_report.py --traces r0.json r1.json

Exit: 0 when gate_ok (or --no-gate), 1 otherwise.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

STEPS = 6
INJECT_AT = 3


def _train(policy, inject_at=None, drop=None, steps=STEPS,
           lr=1e-3):
    """One tiny fused-path run under mxhealth; returns (monitor,
    raised_exc, params)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import Trainer, nn
    from mxnet_tpu.resilience import chaos
    from mxnet_tpu.telemetry import mxhealth

    np.random.seed(0)
    mx.random.seed(0)
    net = nn.Dense(4, in_units=16)
    net.initialize()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": lr, "momentum": 0.9})
    batches = [nd.array(np.random.rand(8, 16).astype("float32"))
               for _ in range(steps)]
    mon = mxhealth.enable(policy=policy, every=1, fresh=True)
    err = None
    scope = chaos.inject("trainer.numerics", at=inject_at) \
        if inject_at else None
    try:
        if scope is not None:
            scope.__enter__()
        for i, x in enumerate(batches):
            if drop is not None and i + 1 == drop:
                continue  # the twin simply never sees this batch
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
            try:
                tr.step(8)
            except mxhealth.NonFiniteGradient as e:
                err = e
                break
    finally:
        if scope is not None:
            scope.__exit__(None, None, None)
    mxhealth.flush()
    params = [p.data().asnumpy()
              for p in net.collect_params().values()]
    return mon, err, params


def stage_clean_run():
    import math

    mon, err, _ = _train("record")
    rep = mon.report()
    ok = (err is None and rep["verdict"] == "healthy"
          and rep["samples_fetched"] == STEPS
          and rep["last_sample"] is not None
          and math.isfinite(rep["last_sample"]["grad_norm"]))
    return {"ok": ok, "verdict": rep["verdict"],
            "steps": rep["steps_observed"],
            "last_sample": rep["last_sample"]}


def stage_nonfinite(policy):
    import numpy as np

    mon, err, params = _train(policy, inject_at=INJECT_AT)
    evs = mon.events("nonfinite")
    detected_at = [e["step"] for e in evs]
    out = {"policy": policy, "injected_at": INJECT_AT,
           "detected_at": detected_at}
    if policy == "raise":
        out["ok"] = (err is not None and err.step == INJECT_AT
                     and detected_at[:1] == [INJECT_AT])
        out["raised_step"] = getattr(err, "step", None)
        return out
    detected_exact = bool(detected_at) and detected_at[0] == INJECT_AT
    if policy == "skip_step":
        # one detection, one skip, nothing after (the guard kept the
        # NaN out of the params, so later steps are clean) — and the
        # params are bit-identical to a twin that never saw the
        # corrupted batch
        _, _, twin = _train(policy, drop=INJECT_AT)
        bit_ok = len(params) == len(twin) and all(
            np.array_equal(a, b) for a, b in zip(params, twin))
        out.update({
            "ok": (detected_exact and detected_at == [INJECT_AT]
                   and mon.report()["skipped_steps"] == 1 and bit_ok),
            "skipped_steps": mon.report()["skipped_steps"],
            "bit_consistent_with_twin": bit_ok})
        return out
    # record: detection starts at the injected step (and cascades —
    # the NaN params keep producing NaN grads, which is the point of
    # the policy spectrum)
    out["ok"] = detected_exact and err is None
    return out


def stage_alert_engine():
    from mxnet_tpu.telemetry import alerts, instruments as _ins

    clock = [0.0]
    eng = alerts.AlertEngine(clock=lambda: clock[0])
    g = _ins.serving_queue_depth("health-report", 1)
    g.set(0)
    eng.add_rule("synthetic_queue", metric="mx_serving_queue_depth",
                 labels={"model": "health-report"}, op=">",
                 threshold=5, for_=2.0, severity="warning",
                 description="synthetic HEALTH.json scenario")
    fired_early = bool(eng.tick())
    g.set(10)
    pending_only = not eng.tick()         # true but inside for-window
    clock[0] = 3.0
    fired = [e for e in eng.tick() if e["state"] == "firing"]
    firing_gauge = _ins.alerts_firing("synthetic_queue",
                                      "warning").value
    g.set(0)
    resolved = [e for e in eng.tick() if e["state"] == "resolved"]
    cleared_gauge = _ins.alerts_firing("synthetic_queue",
                                       "warning").value
    ok = (not fired_early and pending_only and len(fired) == 1
          and firing_gauge == 1.0 and len(resolved) == 1
          and cleared_gauge == 0.0)
    return {"ok": ok, "events": eng.events()}


def stage_straggler(trace_paths):
    from mxnet_tpu.telemetry import mxhealth

    if trace_paths:
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        import trace_report as tr

        loaded = [tr.load_trace(p) for p in trace_paths]
        _, info, errs = tr.merge_loaded(loaded)
        found = mxhealth.stragglers_from_merge(info)
        return {"ok": not errs, "traces": list(trace_paths),
                "merge_violations": errs, "stragglers": found}
    # synthetic known-answer skew table: rank 1 is 2x slower on the
    # backward — the detector must flag exactly it
    info = {"skew": [
        {"cat": "training", "name": "backward",
         "per_rank_ms": {"0": 100.0, "1": 200.0}, "skew_ms": 100.0,
         "straggler": 1},
        {"cat": "training", "name": "forward",
         "per_rank_ms": {"0": 50.0, "1": 51.0}, "skew_ms": 1.0,
         "straggler": 1},
    ]}
    found = mxhealth.stragglers_from_merge(info)
    ok = (len(found) == 1 and found[0]["rank"] == 1
          and found[0]["phase"] == "backward")
    return {"ok": ok, "stragglers": found}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="exercise mxhealth + the alert engine, write the "
                    "HEALTH.json verdict")
    ap.add_argument("--out", default=os.path.join(_REPO, "HEALTH.json"))
    ap.add_argument("--no-gate", action="store_true",
                    help="write the artifact but exit 0 regardless "
                         "(tier-1 smoke)")
    ap.add_argument("--traces", nargs="*", default=None,
                    help="per-rank trace dumps for a real straggler "
                         "analysis (default: synthetic known-answer)")
    args = ap.parse_args(argv)

    from mxnet_tpu.telemetry import mxhealth

    t0 = time.time()
    stages = {}
    stages["clean_run"] = stage_clean_run()
    for policy in ("record", "raise", "skip_step"):
        key = f"nonfinite_{policy.replace('_step', '')}"
        stages[key] = stage_nonfinite(policy)
    stages["alert_engine"] = stage_alert_engine()
    stages["straggler"] = stage_straggler(args.traces)
    mxhealth.disable()

    gate_ok = all(s.get("ok") for s in stages.values())
    artifact = {
        "metric": "training-health detection + alerting known-answer "
                  "scenarios",
        "when": time.strftime("%Y-%m-%d %H:%M:%S"),
        "duration_s": round(time.time() - t0, 1),
        "stages": stages,
        "gate_ok": gate_ok,
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps({"gate_ok": gate_ok,
                      "stages": {k: v["ok"]
                                 for k, v in stages.items()}}))
    print(f"wrote {args.out}")
    if not gate_ok:
        for k, v in stages.items():
            if not v.get("ok"):
                print(f"HEALTH GATE FAIL: stage {k}: {v}",
                      file=sys.stderr)
    return 0 if gate_ok or args.no_gate else 1


if __name__ == "__main__":
    sys.exit(main())
