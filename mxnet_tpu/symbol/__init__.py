"""Symbolic frontend (ref: python/mxnet/symbol/).

``mx.sym.FullyConnected(...)`` etc. are synthesized lazily from the op
registry (the counterpart of the reference's generated symbol wrappers,
ref: python/mxnet/symbol/register.py::_make_symbol_function).
"""
from __future__ import annotations

from .symbol import Group, Symbol, Variable, load, load_json, var
from .executor import GraphExecutor

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "GraphExecutor", "zeros", "ones"]

_CACHE = {}


def zeros(shape, dtype="float32", name=None):
    from . import symbol as _s

    nm = name or _s._NAMER.next("zeros")
    return __getattr__("zeros_like")(var(nm, shape=shape))


def ones(shape, dtype="float32", name=None):
    from . import symbol as _s

    nm = name or _s._NAMER.next("ones")
    return __getattr__("ones_like")(var(nm, shape=shape))


def __getattr__(name):
    if name == "contrib":
        # sym.contrib IS mx.contrib.symbol (one lookup implementation,
        # ref: python/mxnet/symbol/contrib.py)
        import importlib

        mod = importlib.import_module("..contrib.symbol", __name__)
        _CACHE["contrib"] = mod
        globals()["contrib"] = mod
        return mod
    from ..ops.registry import OP_REGISTRY
    from .symbol import make_symbol_function

    if name in _CACHE:
        return _CACHE[name]
    if name in OP_REGISTRY:
        fn = make_symbol_function(name)
        _CACHE[name] = fn
        return fn
    raise AttributeError(f"module 'mxnet_tpu.symbol' has no attribute {name!r}")
