#!/usr/bin/env python
"""Distributed job launcher (ref: tools/launch.py + dmlc-core tracker).

Spawns N worker processes with the reference's DMLC_* environment contract:

    python tools/launch.py -n 2 python train.py --kv-store dist_sync

Workers bootstrap through mxnet_tpu.parallel.dist.init(), which maps the
DMLC_* variables onto jax.distributed's coordination service (worker 0
hosts it — there is no separate scheduler process) and collective
allreduce over DCN (there are no parameter-server processes; `-s` is
accepted for command-line parity and ignored with a note).

Only the `local` launcher (single machine, multi-process — the reference's
`--launcher local` dmlc tracker) is implemented; ssh/mpi/yarn/slurm
launchers raise with a pointer to run one process per host with the same
env contract instead.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job",
        usage="launch.py [-h] -n NUM_WORKERS [-s NUM_SERVERS] "
              "[--launcher local] command ...")
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="number of worker processes")
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference parity; no server "
                         "processes are spawned (collectives subsume them)")
    ap.add_argument("--launcher", default="local",
                    choices=["local", "ssh", "mpi", "yarn", "slurm"])
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    if not args.command:
        ap.error("no command given")
    if args.launcher != "local":
        raise NotImplementedError(
            f"launcher {args.launcher!r}: start one process per host with "
            "DMLC_PS_ROOT_URI/DMLC_PS_ROOT_PORT/DMLC_NUM_WORKER/"
            "DMLC_WORKER_ID set (see mxnet_tpu.parallel.dist)")
    if args.num_servers:
        print("[launch] note: server roles are subsumed by collectives; "
              f"-s {args.num_servers} ignored", file=sys.stderr)

    port = os.environ.get("DMLC_PS_ROOT_PORT") or str(_free_port())
    procs = []
    try:
        for i in range(args.num_workers):
            env = dict(os.environ)
            env.update({
                "DMLC_ROLE": "worker",
                "DMLC_PS_ROOT_URI": "127.0.0.1",
                "DMLC_PS_ROOT_PORT": port,
                "DMLC_NUM_WORKER": str(args.num_workers),
                "DMLC_WORKER_ID": str(i),
                "DMLC_NUM_SERVER": str(args.num_servers),
            })
            procs.append(subprocess.Popen(args.command, env=env))
        rc = 0
        for p in procs:
            rc = p.wait() or rc
        return rc
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        return 130
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


if __name__ == "__main__":
    sys.exit(main())
