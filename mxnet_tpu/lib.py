"""Native library loader: builds (if needed) and binds src/ via ctypes.

Counterpart of the reference's `python/mxnet/base.py` `_LIB` loader +
`check_call` over the flat C ABI (ref: include/mxnet/c_api.h; the
reference also binds exclusively through ctypes — no pybind11).

The library is built on demand from `src/*.cc` (g++ direct; the canonical
CMake build in src/CMakeLists.txt produces the same .so) and cached in
`build/`.  Everything degrades gracefully: `available()` is False when no
toolchain exists, and pure-Python paths take over.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
import weakref
from typing import List, Optional

from .base import MXNetError
from .util import env

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO, "src")
_BUILD = os.path.join(_REPO, "build")
_SO = os.path.join(_BUILD, "libmxnet_tpu_native.so")

_lock = threading.Lock()

EngineFnType = ctypes.CFUNCTYPE(None, ctypes.c_void_p)

# image_pipeline.cc links OpenCV and builds into its own .so (see below);
# ndarray_capi.cc links libpython and builds into its own .so too —
# the core library must stay dependency-free
_CORE_EXCLUDE = {"image_pipeline.cc", "ndarray_capi.cc"}


def _sources() -> List[str]:
    return sorted(
        os.path.join(_SRC, f) for f in os.listdir(_SRC)
        if f.endswith(".cc") and f not in _CORE_EXCLUDE)


def _img_sources() -> List[str]:
    return [os.path.join(_SRC, "image_pipeline.cc"),
            os.path.join(_SRC, "engine.cc")]


class _NativeLib:
    """One build-on-demand ctypes library: mtime staleness check, g++
    fallback build, env gate, double-checked-lock load, error ring."""

    def __init__(self, so_name: str, sources_fn, extra_flags: List[str],
                 err_sym: str, what: str):
        self.so_path = os.path.join(_BUILD, so_name)
        self._sources_fn = sources_fn
        self._flags = extra_flags
        self._err_sym = err_sym
        self._what = what
        self._lib: Optional[ctypes.CDLL] = None
        self._tried = False

    def _needs_build(self) -> bool:
        if not os.path.exists(self.so_path):
            return True
        mtime = os.path.getmtime(self.so_path)
        deps = self._sources_fn() + [
            os.path.join(_SRC, f) for f in os.listdir(_SRC)
            if f.endswith(".h")]
        return any(os.path.getmtime(p) > mtime for p in deps)

    def _build(self) -> None:
        os.makedirs(_BUILD, exist_ok=True)
        cmd = (["g++", "-std=c++17", "-O2", "-shared", "-fPIC", "-pthread",
                "-Wall", "-o", self.so_path] + self._sources_fn() +
               self._flags)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise MXNetError(f"{self._what} build failed:\n"
                             f"{' '.join(cmd)}\n{proc.stderr[-4000:]}")

    def load(self) -> Optional[ctypes.CDLL]:
        if self._lib is not None or self._tried:
            return self._lib
        with _lock:
            if self._lib is not None or self._tried:
                return self._lib
            self._tried = True
            if not env.get_bool("MXNET_USE_NATIVE"):
                return None
            try:
                if self._needs_build():
                    self._build()
                lib = ctypes.CDLL(self.so_path)
            except Exception:
                return None
            getattr(lib, self._err_sym).restype = ctypes.c_char_p
            self._lib = lib
        return self._lib

    def check(self, ret: int) -> None:
        if ret != 0:
            raise MXNetError(getattr(self._lib, self._err_sym)()
                             .decode("utf-8", "replace"))


def _capi_sources() -> List[str]:
    return [os.path.join(_SRC, "ndarray_capi.cc")]


def _capi_flags() -> List[str]:
    """Python embedding flags from sysconfig (no python3-config needed)."""
    import sysconfig

    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        f"{sys.version_info.major}.{sys.version_info.minor}"
    flags = [f"-I{inc}"]
    if libdir:
        flags += [f"-L{libdir}", f"-Wl,-rpath,{libdir}"]
    flags += [f"-lpython{ver}"]
    return flags


_CORE = _NativeLib("libmxnet_tpu_native.so", _sources, [],
                   "MXGetLastError", "native")
_IMAGE = _NativeLib("libmxnet_tpu_image.so", _img_sources,
                    ["-I/usr/include/opencv4", "-lopencv_core",
                     "-lopencv_imgproc", "-lopencv_imgcodecs"],
                    "MXImageGetLastError", "image pipeline")
_CAPI = _NativeLib("libmxnet_tpu_capi.so", _capi_sources, _capi_flags(),
                   "MXCapiGetLastError", "ndarray c-api")


def _load() -> Optional[ctypes.CDLL]:
    return _CORE.load()


def available() -> bool:
    return _CORE.load() is not None


def get() -> ctypes.CDLL:
    lib = _CORE.load()
    if lib is None:
        raise MXNetError(
            "native library unavailable (no toolchain or build failed); "
            "set MXNET_USE_NATIVE=0 to silence native paths entirely")
    return lib


def check_call(ret: int) -> None:
    """ref: base.py::check_call — raise MXNetError from the error ring."""
    if ret != 0:
        raise MXNetError(get().MXGetLastError().decode("utf-8", "replace"))


# ---------------------------------------------------------------------------
# Engine wrapper (ref: Engine::PushAsync contract, SURVEY.md CS1 async
# boundary — here scheduling HOST-side work; device work rides PjRt)
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# Fork safety (ref role: src/initialize.cc pthread_atfork handlers —
# quiesce engine threads before fork; don't let the child inherit handles
# whose worker threads/mutexes did not survive the fork)
# ---------------------------------------------------------------------------

_FORK_REGISTRY: "weakref.WeakSet" = weakref.WeakSet()
_FORK_HOOKS_INSTALLED = False


def _register_fork_guard(obj) -> None:
    _FORK_REGISTRY.add(obj)


def _before_fork() -> None:
    for obj in list(_FORK_REGISTRY):
        try:
            obj._quiesce_before_fork()
        except Exception:
            pass


def _after_fork_child() -> None:
    for obj in list(_FORK_REGISTRY):
        try:
            obj._after_fork_child()
        except Exception:
            pass


def _after_fork_parent() -> None:
    for obj in list(_FORK_REGISTRY):
        try:
            obj._after_fork_parent()
        except Exception:
            pass


def install_fork_handlers() -> None:
    """Register atfork hooks (idempotent; runs on os.fork / the
    multiprocessing 'fork' start method, NOT on subprocess spawn).
    Does not load the native library."""
    global _FORK_HOOKS_INSTALLED
    if _FORK_HOOKS_INSTALLED or not hasattr(os, "register_at_fork"):
        return
    _FORK_HOOKS_INSTALLED = True
    os.register_at_fork(before=_before_fork,
                        after_in_parent=_after_fork_parent,
                        after_in_child=_after_fork_child)


class _HandleGuard:
    """Mixin: `_hh()` returns the live native handle or raises loudly —
    a closed or fork-invalidated handle must never reach C++ as NULL."""

    _fork_invalid = False

    def _hh(self) -> ctypes.c_void_p:
        h = getattr(self, "_h", None)
        if not h:
            why = ("invalidated by fork (native threads/file offsets do "
                   "not survive into the child; recreate the object)"
                   if self._fork_invalid else "already closed")
            raise MXNetError(
                f"{type(self).__name__}: native handle {why}")
        return h

    def _quiesce_before_fork(self) -> None:  # overridden where needed
        pass

    def _after_fork_parent(self) -> None:  # overridden where needed
        pass

    def _after_fork_child(self) -> None:
        # leak the C++ object on purpose: freeing it in the child would
        # join worker threads that only exist in the parent
        self._h = None
        self._fork_invalid = True


class NativeEngine(_HandleGuard):
    """Dependency-scheduled host task engine.

    `push(fn, read=[v1], write=[v2])` runs `fn()` on a worker thread once
    all hazards on the named variables clear; reads run concurrently,
    writes are exclusive and FIFO — the reference ThreadedEngine contract.
    `num_workers=0` gives the synchronous NaiveEngine (debug mode).
    """

    def __init__(self, num_workers: Optional[int] = None):
        if num_workers is None:
            if env.get_str("MXNET_ENGINE_TYPE") == "NaiveEngine":
                num_workers = 0
            else:
                num_workers = env.get_int(
                    "MXNET_CPU_WORKER_NTHREADS",
                    default=max(2, (os.cpu_count() or 2)))
        self._lib = get()
        h = ctypes.c_void_p()
        check_call(self._lib.MXEngineCreate(ctypes.c_int(num_workers),
                                            ctypes.byref(h)))
        self._h = h
        self.num_workers = num_workers
        # keep callback objects alive until executed
        self._cb_lock = threading.Lock()
        self._cbs = {}
        self._next_id = 1  # never 0: ctypes maps a NULL void* to None

        def _trampoline(arg):
            key = int(arg or 0)
            with self._cb_lock:
                fn = self._cbs.pop(key)
            try:
                fn()
            except Exception:  # worker threads must never unwind into C++
                import traceback

                traceback.print_exc()

        self._tramp = EngineFnType(_trampoline)
        _register_fork_guard(self)

    def _quiesce_before_fork(self) -> None:
        # drain all pending work so no worker thread holds an engine
        # mutex at the instant of fork (the child inherits the mutexes
        # but not the threads — a held lock would deadlock it forever),
        # then take the Python-side callback lock across the fork so the
        # child cannot inherit it mid-acquire (standard atfork protocol)
        if self._h:
            self.wait_for_all()
        self._cb_lock.acquire()
        self._cb_lock_held_for_fork = True

    def _after_fork_parent(self) -> None:
        # only release what _quiesce_before_fork actually took: a bare
        # release() could strip the lock from a thread inside push()
        # when the quiesce raised before acquiring
        if getattr(self, "_cb_lock_held_for_fork", False):
            self._cb_lock_held_for_fork = False
            try:
                self._cb_lock.release()
            except RuntimeError:
                pass

    def _after_fork_child(self) -> None:
        # the parent's worker threads don't exist here; leak the old C++
        # engine (freeing would join ghost threads) and mark for LAZY
        # rebuild — a child that never touches the engine pays nothing
        # (the reference likewise restarts its engine lazily after fork,
        # src/initialize.cc role).  Pre-fork variable ids belong to the
        # leaked engine and error loudly on the rebuilt one.
        self._h = None
        self._needs_rebuild = True
        self._cb_lock = threading.Lock()  # fresh, never inherited-held
        self._cb_lock_held_for_fork = False

    def _hh(self) -> ctypes.c_void_p:
        if getattr(self, "_needs_rebuild", False):
            self._needs_rebuild = False
            h = ctypes.c_void_p()
            check_call(self._lib.MXEngineCreate(
                ctypes.c_int(self.num_workers), ctypes.byref(h)))
            self._h = h
            with self._cb_lock:
                self._cbs.clear()
        return super()._hh()

    def new_variable(self) -> int:
        v = ctypes.c_int64()
        check_call(self._lib.MXEngineNewVariable(self._hh(),
                                                 ctypes.byref(v)))
        return v.value

    def delete_variable(self, var: int) -> None:
        check_call(self._lib.MXEngineDeleteVariable(self._hh(),
                                                    ctypes.c_int64(var)))

    def push(self, fn, read=(), write=(), priority: int = 0) -> None:
        # convert BEFORE stashing: a bad var id must not leak the
        # callback into _cbs
        rv = (ctypes.c_int64 * len(read))(*read)
        wv = (ctypes.c_int64 * len(write))(*write)
        with self._cb_lock:
            key = self._next_id
            self._next_id += 1
            self._cbs[key] = fn
        try:
            check_call(self._lib.MXEnginePushAsync(
                self._hh(), self._tramp, ctypes.c_void_p(key), rv,
                len(read), wv, len(write), ctypes.c_int(priority)))
        except BaseException:
            # rejected push (duplicate-var check, dead handle): the
            # trampoline will never pop the stash — do it here or the
            # callable (and its closure) leaks on every retry
            with self._cb_lock:
                self._cbs.pop(key, None)
            raise

    def wait_for_var(self, var: int) -> None:
        check_call(self._lib.MXEngineWaitForVar(self._hh(),
                                                ctypes.c_int64(var)))

    def wait_for_all(self) -> None:
        check_call(self._lib.MXEngineWaitForAll(self._hh()))

    def num_pending(self) -> int:
        out = ctypes.c_int()
        check_call(self._lib.MXEngineNumPending(self._hh(),
                                                ctypes.byref(out)))
        return out.value

    def var_version(self, var: int) -> int:
        out = ctypes.c_uint64()
        check_call(self._lib.MXEngineVarVersion(self._hh(),
                                                ctypes.c_int64(var),
                                                ctypes.byref(out)))
        return out.value

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.MXEngineFree(self._h)
                self._h = None
        except Exception:
            pass


# ---------------------------------------------------------------------------
# RecordIO wrappers (native fast path for mxnet_tpu/recordio.py)
# ---------------------------------------------------------------------------

class NativeRecordWriter(_HandleGuard):
    def __init__(self, path: str, max_chunk: int = 0):
        # max_chunk=0 → the 29-bit wire default; smaller values exercise
        # the cflag-chained chunk path without gigabyte fixtures
        self._lib = get()
        h = ctypes.c_void_p()
        if max_chunk:
            check_call(self._lib.MXRecordIOWriterCreateEx(
                path.encode(), ctypes.c_size_t(max_chunk), ctypes.byref(h)))
        else:
            check_call(self._lib.MXRecordIOWriterCreate(
                path.encode(), ctypes.byref(h)))
        self._h = h
        _register_fork_guard(self)

    def write(self, buf: bytes) -> int:
        pos = ctypes.c_int64()
        check_call(self._lib.MXRecordIOWriterWrite(
            self._hh(), buf, ctypes.c_size_t(len(buf)), ctypes.byref(pos)))
        return pos.value

    def close(self):
        if self._h:
            check_call(self._lib.MXRecordIOWriterFree(self._h))
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _ReaderBase(_HandleGuard):
    _create = _next = _reset = _free = None  # bound by subclass

    def __init__(self, path: str, *extra):
        self._lib = get()
        h = ctypes.c_void_p()
        check_call(self._create(path.encode(), *extra, ctypes.byref(h)))
        self._h = h
        _register_fork_guard(self)

    def read(self) -> Optional[bytes]:
        buf = ctypes.c_char_p()
        length = ctypes.c_size_t()
        eof = ctypes.c_int()
        check_call(self._next(self._hh(), ctypes.byref(buf),
                              ctypes.byref(length), ctypes.byref(eof)))
        if eof.value:
            return None
        return ctypes.string_at(buf, length.value)

    def reset(self):
        check_call(self._reset(self._hh()))

    def close(self):
        if self._h:
            check_call(self._free(self._h))
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordReader(_ReaderBase):
    def __init__(self, path: str):
        lib = get()
        self._create = lib.MXRecordIOReaderCreate
        self._next = lib.MXRecordIOReaderNext
        self._reset = lib.MXRecordIOReaderReset
        self._free = lib.MXRecordIOReaderFree
        super().__init__(path)

    def seek(self, pos: int):
        check_call(self._lib.MXRecordIOReaderSeek(self._hh(),
                                                  ctypes.c_int64(pos)))


class NativePrefetchReader(_ReaderBase):
    """Background-thread prefetching record reader (dmlc ThreadedIter)."""

    def __init__(self, path: str, capacity: int = 64):
        lib = get()
        self._create = lib.MXPrefetchReaderCreate
        self._next = lib.MXPrefetchReaderNext
        self._reset = lib.MXPrefetchReaderReset
        self._free = lib.MXPrefetchReaderFree
        super().__init__(path, ctypes.c_int(capacity))


# ---------------------------------------------------------------------------
# Image pipeline (src/image_pipeline.cc, separate .so: links OpenCV like the
# reference's image pipeline; absence degrades to the Python decode path)
# ---------------------------------------------------------------------------

def capi_available() -> bool:
    """The NDArray/op C ABI .so (src/ndarray_capi.cc) builds and loads.

    RTLD_GLOBAL load path is in capi_get(): the library references
    libpython symbols which, inside a Python process, resolve from the
    interpreter already mapped into the process; standalone consumers
    link -lpython explicitly."""
    return _CAPI.load() is not None


def capi_get() -> ctypes.CDLL:
    lib = _CAPI.load()
    if lib is None:
        raise MXNetError("ndarray c-api library unavailable "
                         "(no toolchain or build failed)")
    return lib


def capi_check(ret: int) -> None:
    _CAPI.check(ret)


def image_available() -> bool:
    return _IMAGE.load() is not None


def _load_image() -> Optional[ctypes.CDLL]:
    return _IMAGE.load()


def _img_check(lib, ret: int) -> None:
    _IMAGE.check(ret)


class NativeImagePipeline(_HandleGuard):
    """Threaded decode+augment+batch pipeline over a .rec shard
    (src/image_pipeline.cc; decode tasks run on the N1 engine)."""

    def __init__(self, rec_path: str, idx_path: Optional[str], **cfg):
        import numpy as np

        self._np = np
        self._lib = _load_image()
        if self._lib is None:
            raise MXNetError("native image pipeline unavailable "
                             "(OpenCV toolchain missing?)")
        self.cfg = cfg
        cfg_s = ";".join(f"{k}={int(v) if isinstance(v, bool) else v}"
                         for k, v in cfg.items())
        h = ctypes.c_void_p()
        _img_check(self._lib, self._lib.MXImagePipelineCreate(
            rec_path.encode(), idx_path.encode() if idx_path else None,
            cfg_s.encode(), ctypes.byref(h)))
        self._h = h
        _register_fork_guard(self)

    def next(self):
        """-> (data ndarray, label ndarray, pad) or None at epoch end.
        data is u8 NHWC (default) or f32 NCHW (normalize=1)."""
        np = self._np
        batch_h = ctypes.c_void_p()
        data_p = ctypes.POINTER(ctypes.c_uint8)()
        label_p = ctypes.POINTER(ctypes.c_float)()
        pad = ctypes.c_int()
        _img_check(self._lib, self._lib.MXImagePipelineNext(
            self._hh(), ctypes.byref(batch_h), ctypes.byref(data_p),
            ctypes.byref(label_p), ctypes.byref(pad)))
        if not batch_h.value:
            return None
        b = int(self.cfg.get("batch", 1))
        c = int(self.cfg.get("channels", 3))
        hh = int(self.cfg.get("height", 224))
        ww = int(self.cfg.get("width", 224))
        lw = int(self.cfg.get("label_width", 1))
        norm = bool(self.cfg.get("normalize", False))
        n_el = b * c * hh * ww
        if norm:
            fp = ctypes.cast(data_p, ctypes.POINTER(ctypes.c_float))
            data = np.ctypeslib.as_array(fp, (n_el,)).reshape(
                b, c, hh, ww).copy()
        else:
            data = np.ctypeslib.as_array(data_p, (n_el,)).reshape(
                b, hh, ww, c).copy()
        label = np.ctypeslib.as_array(label_p, (b * lw,)).reshape(
            b, lw).copy()
        _img_check(self._lib,
                   self._lib.MXImagePipelineReleaseBatch(batch_h))
        return data, label, pad.value

    def reset(self):
        _img_check(self._lib, self._lib.MXImagePipelineReset(self._hh()))

    def close(self):
        if getattr(self, "_h", None):
            self._lib.MXImagePipelineFree(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
