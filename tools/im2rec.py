#!/usr/bin/env python
"""im2rec: pack an image directory / .lst file into RecordIO shards.

Counterpart of the reference's tools/im2rec.py (list generation +
multi-threaded packing into prefix.rec/prefix.idx).  Two modes:

  1. List generation:
       python tools/im2rec.py PREFIX ROOT --list [--recursive]
           [--train-ratio R] [--test-ratio R] [--shuffle]
     writes PREFIX.lst (and _train/_val/_test splits when ratios given):
     one line per image: "<index>\t<label>\t<relative/path>".
     Labels come from the top-level subdirectory index (sorted), exactly
     like the reference's folder-name labeling.

  2. Record packing:
       python tools/im2rec.py PREFIX ROOT [--resize N] [--quality Q]
           [--num-thread T] [--center-crop] [--color {-1,0,1}]
           [--pack-label] [--no-shuffle]
     reads every PREFIX*.lst and writes a .rec + .idx pair per list.
     Images are re-encoded (optionally shorter-edge-resized / square
     center-cropped) with T worker threads; records keep list order
     (pass --shuffle at list time for shuffled shards).

The output shards are read by io.ImageRecordIter — natively via
src/image_pipeline.cc when built, else the Python decode path.
"""
from __future__ import annotations

import argparse
import os
import random
import sys
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def list_images(root: str, recursive: bool):
    """Yield (relpath, label) with labels = sorted top-level dir index."""
    if recursive:
        cats = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        label_of = {c: i for i, c in enumerate(cats)}
        for cat in cats:
            for dirpath, _, files in os.walk(os.path.join(root, cat)):
                for f in sorted(files):
                    if os.path.splitext(f)[1].lower() in _EXTS:
                        yield (os.path.relpath(os.path.join(dirpath, f),
                                               root), label_of[cat])
    else:
        for f in sorted(os.listdir(root)):
            if os.path.splitext(f)[1].lower() in _EXTS:
                yield (f, 0)


def write_list(args):
    items = list(list_images(args.root, args.recursive))
    if args.shuffle:
        random.seed(args.seed)
        random.shuffle(items)
    n = len(items)
    n_train = int(n * args.train_ratio)
    n_test = int(n * args.test_ratio)
    chunks = {"": items}
    if args.train_ratio < 1.0 or args.test_ratio > 0.0:
        chunks = {"_train": items[:n_train],
                  "_test": items[n_train:n_train + n_test],
                  "_val": items[n_train + n_test:]}
        chunks = {k: v for k, v in chunks.items() if v}
    for suffix, chunk in chunks.items():
        path = f"{args.prefix}{suffix}.lst"
        with open(path, "w") as f:
            for i, (rel, label) in enumerate(chunk):
                f.write(f"{i}\t{label}\t{rel}\n")
        print(f"wrote {path} ({len(chunk)} images)")


def read_list(path: str):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            rel = parts[-1]
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels, rel


def _encode_one(args, rel: str):
    """Read + (resize/crop) + re-encode one image; returns encoded bytes."""
    import cv2

    path = os.path.join(args.root, rel)
    flag = (cv2.IMREAD_COLOR if args.color == 1 else
            cv2.IMREAD_GRAYSCALE if args.color == 0 else
            cv2.IMREAD_UNCHANGED)
    if args.pass_through:
        with open(path, "rb") as f:
            return f.read()
    img = cv2.imread(path, flag)
    if img is None:
        raise IOError(f"cannot decode {path}")
    if args.center_crop:
        s = min(img.shape[:2])
        y = (img.shape[0] - s) // 2
        x = (img.shape[1] - s) // 2
        img = img[y:y + s, x:x + s]
    if args.resize > 0:
        h, w = img.shape[:2]
        scale = args.resize / min(h, w)
        if scale != 1.0:
            img = cv2.resize(
                img, (max(1, round(w * scale)), max(1, round(h * scale))),
                interpolation=cv2.INTER_AREA if scale < 1
                else cv2.INTER_LINEAR)
    ext = ".png" if args.encoding == ".png" else ".jpg"
    params = [] if ext == ".png" else [cv2.IMWRITE_JPEG_QUALITY, args.quality]
    ok, buf = cv2.imencode(ext, img, params)
    if not ok:
        raise IOError(f"cannot encode {path}")
    return buf.tobytes()


def pack_list(args, lst_path: str):
    from mxnet_tpu import recordio

    prefix = lst_path[:-4]
    items = list(read_list(lst_path))
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    t0 = time.time()
    n_done = 0
    with ThreadPoolExecutor(max_workers=args.num_thread) as pool:
        # encode in parallel (cv2 releases the GIL), write in list order
        encoded = pool.map(lambda it: _encode_one(args, it[2]), items,
                           chunksize=8)
        for (idx, labels, _rel), payload in zip(items, encoded):
            label = labels[0] if len(labels) == 1 and not args.pack_label \
                else labels
            header = recordio.IRHeader(0, label, idx, 0)
            rec.write_idx(idx, recordio.pack(header, payload))
            n_done += 1
            if n_done % 1000 == 0:
                print(f"{lst_path}: {n_done}/{len(items)} "
                      f"({n_done / (time.time() - t0):.0f} img/s)")
    rec.close()
    print(f"wrote {prefix}.rec + .idx ({n_done} records, "
          f"{time.time() - t0:.1f}s)")


def main():
    ap = argparse.ArgumentParser(
        description="pack images into RecordIO shards "
                    "(counterpart of the reference tools/im2rec.py)")
    ap.add_argument("prefix", help="output prefix (and .lst prefix)")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="generate .lst instead of packing")
    ap.add_argument("--recursive", action="store_true",
                    help="label by top-level subdirectory")
    ap.add_argument("--train-ratio", type=float, default=1.0)
    ap.add_argument("--test-ratio", type=float, default=0.0)
    ap.add_argument("--shuffle", action="store_true")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--resize", type=int, default=0,
                    help="resize shorter edge before packing")
    ap.add_argument("--center-crop", action="store_true")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--num-thread", type=int, default=os.cpu_count() or 1)
    ap.add_argument("--color", type=int, default=1, choices=[-1, 0, 1])
    ap.add_argument("--encoding", default=".jpg", choices=[".jpg", ".png"])
    ap.add_argument("--pack-label", action="store_true",
                    help="store the full (multi-)label vector")
    ap.add_argument("--pass-through", action="store_true",
                    help="pack original file bytes without re-encoding")
    args = ap.parse_args()

    if args.list:
        write_list(args)
        return 0
    lsts = sorted(
        p for p in (
            f"{args.prefix}{s}" for s in
            ("", "_train", "_val", "_test"))
        if os.path.isfile(p + ".lst"))
    if not lsts:
        print(f"no .lst found for prefix {args.prefix}; "
              f"run with --list first", file=sys.stderr)
        return 1
    for p in lsts:
        pack_list(args, p + ".lst")
    return 0


if __name__ == "__main__":
    sys.exit(main())
