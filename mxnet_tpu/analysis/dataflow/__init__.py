"""mxflow — the interprocedural dataflow engine under mxlint.

Three layers (docs/static_analysis.md has the architecture section):

  * :mod:`cfg` — per-function control-flow graphs with exception
    edges, dominators/postdominators, reaching definitions;
  * :mod:`summaries` — per-function *local* summaries (blocking calls,
    host syncs, lock regions, donations, raises, symbolic call refs),
    a pure function of file bytes and therefore cacheable by content
    hash (``.mxflow_cache.json``);
  * :mod:`project` — the whole-program index: first-party import
    resolution, method lookup through the class hierarchy,
    op-registry indirection, and the bottom-up fixpoint that turns
    local summaries into transitive facts.

:mod:`rules` plugs MX008–MX012 into the ordinary mxlint engine —
pragmas, baseline ratchet, ``--diff``, reporters all apply unchanged.

Stdlib-only, like the rest of ``mxnet_tpu.analysis``: the mxlint CLI
loads this package standalone, and a full-package run must never pay
the jax import.
"""
from .cfg import (  # noqa: F401
    CFG, Block, build_cfg, dominators, postdominators, reaching_defs,
)
from .summaries import extract_module  # noqa: F401
from .project import (  # noqa: F401
    Project, FuncInfo, build_project, get_project, clear_memo,
    CACHE_NAME,
)
from .rules import (  # noqa: F401  — registers MX008–MX012 on import
    BlockingUnderLock, TransitiveHostSync, ExceptionPathLeak,
    RetryUnsafeSideEffect, InterproceduralDonation,
)

__all__ = [
    "CFG", "Block", "build_cfg", "dominators", "postdominators",
    "reaching_defs", "extract_module", "Project", "FuncInfo",
    "build_project", "get_project", "clear_memo", "CACHE_NAME",
    "BlockingUnderLock", "TransitiveHostSync", "ExceptionPathLeak",
    "RetryUnsafeSideEffect", "InterproceduralDonation",
]
