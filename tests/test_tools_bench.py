"""Smoke lane for the measurement tooling (bench_all / opperf /
scaling_bench): each harness must produce a parseable JSON row on the
CPU backend.  Real numbers come from the on-chip runs (BENCH_ALL.json,
OPPERF.json, SCALING.json artifacts)."""
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, timeout=420):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    p = subprocess.run(cmd, capture_output=True, text=True, cwd=_REPO,
                       timeout=timeout, env=env)
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
    lines = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
    assert lines, p.stdout[-2000:]
    return [json.loads(ln) for ln in lines]


def test_opperf_subset():
    rows = _run([sys.executable, "tools/opperf.py",
                 "--ops", "softmax,FullyConnected",
                 "--repeat", "2", "--number", "3"])
    by_op = {r["op"]: r for r in rows}
    assert set(by_op) == {"softmax", "FullyConnected"}
    for r in rows:
        assert r["eager_us"] > 0 and r["jit_fwd_us"] > 0
        assert r["jit_bwd_us"] > 0


def test_bench_all_mnist_smoke():
    rows = _run([sys.executable, "bench_all.py", "--cpu-smoke",
                 "--config", "mnist_mlp"])
    assert rows[-1]["metric"] == "mnist_mlp_train_throughput"
    assert rows[-1]["value"] > 0


def test_scaling_bench_single_proc():
    rows = _run([sys.executable, "tools/scaling_bench.py",
                 "--model", "resnet18", "--procs", "1", "--steps", "2",
                 "--warmup", "1", "--batch-per-device", "2",
                 "--image-size", "32",
                 "--out", "/tmp/scaling_test.json"])
    assert rows[-1]["processes"] == 1
    assert rows[-1]["efficiency_vs_1proc"] == 1.0
