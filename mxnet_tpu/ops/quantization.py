"""Quantization operators.

TPU-native counterpart of src/operator/quantization/** (quantize.cc,
quantize_v2.cc, dequantize.cc, requantize.cc, quantized_conv/fc/pool).

The numeric core — quantize / quantize_v2 / dequantize / requantize —
implements the reference's affine int8/uint8 scheme (min/max
calibration ranges carried alongside the payload).  The contraction
kernels — quantized_conv, quantized_fully_connected, quantized_pooling,
quantized_flatten — are REAL int8 ops: the MXU (and XLA CPU) execute
int8 dot/conv with int32 accumulate natively.  Only the quantized
*elementwise* variants remain stubs by design: between dequantize and
the next quantize, elementwise math runs in fp32/bf16 and XLA fuses the
converts for free, so dedicated int8 elementwise kernels would buy
nothing on TPU.  `contrib.quantization.quantize_model` is the
calibrating graph rewriter over these ops.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError
from .registry import register_op

__all__ = []


def _qrange(out_type: str):
    if out_type == "uint8":
        return 0.0, 255.0, jnp.uint8
    if out_type == "int8":
        return -127.0, 127.0, jnp.int8
    raise MXNetError(f"unsupported quantized type {out_type!r} "
                     "(uint8/int8)")


@register_op("_contrib_quantize", aliases=("quantize",), num_outputs=3,
             differentiable=False)
def _quantize(data, min_range, max_range, out_type="uint8"):
    """Affine-quantize fp32 into uint8/int8 given calibration ranges;
    returns (q, out_min, out_max) (ref: quantization/quantize.cc)."""
    qmin, qmax, qdt = _qrange(out_type)
    rmin = jnp.minimum(min_range, 0.0).reshape(())
    rmax = jnp.maximum(max_range, 0.0).reshape(())
    if out_type == "int8":
        # symmetric: scale by max |range| (ref quantize.cc int8 branch)
        absmax = jnp.maximum(jnp.abs(rmin), jnp.abs(rmax))
        scale = qmax / jnp.maximum(absmax, 1e-20)
        q = jnp.clip(jnp.round(data * scale), qmin, qmax).astype(qdt)
        return q, -absmax, absmax
    scale = (qmax - qmin) / jnp.maximum(rmax - rmin, 1e-20)
    q = jnp.clip(jnp.round((data - rmin) * scale) + qmin, qmin,
                 qmax).astype(qdt)
    return q, rmin, rmax


@register_op("_contrib_quantize_v2", aliases=("quantize_v2",),
             num_outputs=3, differentiable=False)
def _quantize_v2(data, out_type="int8", min_calib_range=None,
                 max_calib_range=None):
    """Quantize with self-calibration when no ranges are given
    (ref: quantize_v2.cc)."""
    if min_calib_range is None or max_calib_range is None:
        rmin = jnp.min(data)
        rmax = jnp.max(data)
    else:
        rmin = jnp.asarray(min_calib_range, jnp.float32)
        rmax = jnp.asarray(max_calib_range, jnp.float32)
    return _quantize(data, rmin, rmax, out_type=out_type)


@register_op("_contrib_dequantize", aliases=("dequantize",),
             differentiable=False)
def _dequantize(data, min_range, max_range, out_type="float32"):
    """Invert the affine quantization (ref: dequantize.cc)."""
    rmin = min_range.reshape(())
    rmax = max_range.reshape(())
    if data.dtype == jnp.int8:
        absmax = jnp.maximum(jnp.abs(rmin), jnp.abs(rmax))
        return data.astype(jnp.float32) * (absmax / 127.0)
    if data.dtype == jnp.int32:  # accumulator from quantized_conv/fc
        return _dequantize_int32(data, rmin, rmax)
    scale = (rmax - rmin) / 255.0
    return data.astype(jnp.float32) * scale + rmin


@register_op("_contrib_requantize", aliases=("requantize",), num_outputs=3,
             differentiable=False)
def _requantize(data, min_range, max_range, out_type="int8",
                min_calib_range=None, max_calib_range=None):
    """int32 accumulator -> int8 with recalibrated ranges
    (ref: requantize.cc)."""
    if data.dtype != jnp.int32:
        raise MXNetError("requantize expects int32 input")
    f = _dequantize_int32(data, min_range, max_range)
    if min_calib_range is not None and max_calib_range is not None:
        rmin = jnp.asarray(min_calib_range, jnp.float32)
        rmax = jnp.asarray(max_calib_range, jnp.float32)
    else:
        rmin = jnp.min(f)
        rmax = jnp.max(f)
    return _quantize(f, rmin, rmax, out_type=out_type)


def _dequantize_int32(data, min_range, max_range):
    absmax = jnp.maximum(jnp.abs(min_range.reshape(())),
                         jnp.abs(max_range.reshape(())))
    return data.astype(jnp.float32) * (absmax / float(2 ** 31 - 1))


# ---------------------------------------------------------------------------
# Real int8 compute kernels: the MXU (and XLA CPU) execute int8
# contractions with int32 accumulate natively, so quantized_conv /
# quantized_fully_connected are true int8 ops, not emulation
# (ref: quantized_conv.cc / quantized_fully_connected.cc semantics:
# int8 in -> int32 out, calibration ranges propagated alongside).
# ---------------------------------------------------------------------------

_INT32_MAX = float(2 ** 31 - 1)


def _absmax(lo, hi):
    return jnp.maximum(jnp.abs(lo.reshape(())), jnp.abs(hi.reshape(())))


def _int32_range(min_a, max_a, min_b, max_b):
    """Output range convention for int32 accumulators: the float value of
    accumulator V is V * (absmax_a/127) * (absmax_b/127); represent the
    range as the float magnitude of the int32 extreme so dequantize's
    int32 branch (absmax/2^31-1 scale) round-trips exactly."""
    scale = (_absmax(min_a, max_a) / 127.0) * (_absmax(min_b, max_b) / 127.0)
    out = _INT32_MAX * scale
    return -out, out


@register_op("_contrib_quantized_conv", aliases=("quantized_conv",),
             num_outputs=3, differentiable=False)
def _quantized_conv(data, weight, min_data, max_data, min_weight,
                    max_weight, kernel=(), stride=(), dilate=(), pad=(),
                    num_filter=0, num_group=1, layout=None, no_bias=True,
                    cudnn_tune=None, cudnn_off=False, workspace=1024):
    """int8 convolution with int32 accumulate on the MXU
    (ref: quantization/quantized_conv.cc; bias is applied in fp32 after
    dequantization by the quantize_model rewriter)."""
    from jax import lax

    if data.dtype != jnp.int8 or weight.dtype != jnp.int8:
        raise MXNetError("quantized_conv expects int8 data and weight")
    nd = len(kernel) if kernel else data.ndim - 2
    stride = tuple(stride) if stride else (1,) * nd
    dilate = tuple(dilate) if dilate else (1,) * nd
    pad = tuple(pad) if pad else (0,) * nd
    default = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nd]
    lay = layout or default
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=(lay, "OI" + default[2:], lay),
        feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    omin, omax = _int32_range(min_data, max_data, min_weight, max_weight)
    return out, omin, omax


@register_op("_contrib_quantized_fully_connected",
             aliases=("quantized_fully_connected",), num_outputs=3,
             differentiable=False)
def _quantized_fc(data, weight, min_data, max_data, min_weight,
                  max_weight, num_hidden=0, no_bias=True, flatten=True):
    """int8 x int8^T -> int32 matmul (ref: quantized_fully_connected.cc;
    fp32 bias applied post-dequantize by the rewriter)."""
    from jax import lax

    if data.dtype != jnp.int8 or weight.dtype != jnp.int8:
        raise MXNetError("quantized_fully_connected expects int8 inputs")
    x = data.reshape((data.shape[0], -1)) if flatten else data
    out = lax.dot_general(
        x, weight, (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    omin, omax = _int32_range(min_data, max_data, min_weight, max_weight)
    return out, omin, omax


@register_op("_contrib_quantized_pooling", aliases=("quantized_pooling",),
             num_outputs=3, differentiable=False)
def _quantized_pooling(data, min_data, max_data, kernel=(), pool_type="max",
                       stride=(), pad=(), global_pool=False,
                       pooling_convention="valid", layout=None):
    """Pooling directly on int8 (max: exact; avg: int32 accumulate then
    round back — ref: quantized_pooling.cc).  Mirrors the fp32 Pooling
    op's layout + pooling_convention semantics so quantized and fp32
    paths agree shape-for-shape."""
    from jax import lax

    from .nn import pool_window

    channels_last = bool(layout) and layout[-1] == "C"
    if global_pool:
        sp = (range(1, data.ndim - 1) if channels_last
              else range(2, data.ndim))
        window = [data.shape[i] if i in sp else 1
                  for i in range(data.ndim)]
        strides = [1] * data.ndim
        pads = [(0, 0)] * data.ndim
    else:
        # single source of truth with the fp32 Pooling op: shapes of the
        # int8 and fp32 paths must agree exactly
        window, strides, pads = pool_window(
            data.shape, kernel, stride, pad, pooling_convention,
            channels_last)
    if pool_type == "max":
        init = jnp.iinfo(data.dtype).min  # int8 AND uint8 inputs
        out = lax.reduce_window(data, jnp.asarray(init, data.dtype),
                                lax.max, window, strides, pads)
        return out, min_data.reshape(()), max_data.reshape(())
    if pool_type == "avg":
        info = jnp.iinfo(data.dtype)
        acc = lax.reduce_window(data.astype(jnp.int32), 0, lax.add,
                                window, strides, pads)
        n = 1
        for w in window:
            n *= w
        out = jnp.clip(jnp.round(acc / n), info.min,
                       info.max).astype(data.dtype)
        return out, min_data.reshape(()), max_data.reshape(())
    raise MXNetError(f"quantized_pooling: unsupported pool_type "
                     f"{pool_type!r}")


@register_op("_contrib_quantized_flatten", aliases=("quantized_flatten",),
             num_outputs=3, differentiable=False)
def _quantized_flatten(data, min_data, max_data):
    """Flatten quantized data to (N, -1), passing the min/max calibration
    scalars through unchanged."""
    return (data.reshape((data.shape[0], -1)), min_data.reshape(()),
            max_data.reshape(()))


def _register_quantized_stub(name: str):
    def stub(*args, **kwargs):
        raise MXNetError(
            f"{name} is not provided as a standalone kernel on TPU: "
            "int8 contractions/pooling are real ops here "
            "(quantized_conv/fully_connected/pooling), and everything "
            "elementwise should run in fp32/bf16 between dequantize and "
            "the next quantize — XLA fuses the converts for free.")

    stub.__name__ = name
    register_op(name, differentiable=False, no_jit=True)(stub)


for _name in ("_contrib_quantized_act", "_contrib_quantized_concat",
              "_contrib_quantized_elemwise_add"):
    _register_quantized_stub(_name)
