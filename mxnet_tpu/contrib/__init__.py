"""Contrib namespace (ref: python/mxnet/contrib/).

`mx.contrib.ndarray.MultiBoxPrior(...)` / `mx.contrib.symbol.*` proxy the
contrib ops registered in mxnet_tpu.ops.contrib, mirroring the reference's
`_contrib_*` generated namespaces.
"""
from . import ndarray
from . import ndarray as nd
from . import symbol
from . import symbol as sym
from . import quantization
from . import onnx
from . import amp
from . import deploy

__all__ = ["ndarray", "nd", "symbol", "sym", "quantization", "onnx",
           "amp", "deploy"]
