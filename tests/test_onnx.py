"""ONNX interop tests (model: tests/python/unittest/onnx/ in the
reference): proto round-trip, schema validity vs torch's bundled C++
ONNX checker, and numeric export->import round-trips."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, symbol as sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import onnx as onnx_mx
from mxnet_tpu.contrib.onnx import proto
from mxnet_tpu.test_utils import assert_almost_equal


def _convnet():
    d = sym.var("data")
    c = sym.Convolution(d, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        name="c1")
    b = sym.BatchNorm(c, name="bn1")
    a = sym.Activation(b, act_type="relu", name="r1")
    p = sym.Pooling(a, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="p1")
    f = sym.FullyConnected(p, num_hidden=10, name="fc1")
    return sym.softmax(f)


def _init_params(s, **shapes):
    rng = np.random.RandomState(0)
    arg_shapes, _, aux_shapes = s.infer_shape(**shapes)
    params = {}
    for name, shp in zip(s.list_arguments(), arg_shapes):
        if name in shapes:
            continue
        params[name] = nd.array(rng.randn(*shp).astype("f4") * 0.1)
    for name, shp in zip(s.list_auxiliary_states(), aux_shapes):
        params[name] = nd.array(
            np.zeros(shp, "f4") if "mean" in name
            else np.ones(shp, "f4"))
    return params


def _forward(s, params, data):
    ex = s.bind(mx.cpu(), {**params, "data": nd.array(data)})
    return ex.forward()[0].asnumpy()


def test_proto_roundtrip():
    t = proto.Tensor.from_numpy("w", np.arange(12, dtype="f4").reshape(3, 4))
    t2 = proto.Tensor.decode(t.encode())
    np.testing.assert_array_equal(t.to_numpy(), t2.to_numpy())
    n = proto.Node(op_type="Conv", inputs=["x", "w"], outputs=["y"],
                   attrs={"kernel_shape": [3, 3], "alpha": 0.5,
                          "mode": "same", "flag": 1})
    n2 = proto.Node.decode(n.encode())
    assert n2.op_type == "Conv" and n2.attrs["kernel_shape"] == [3, 3]
    assert n2.attrs["mode"] == "same" and n2.attrs["flag"] == 1
    assert n2.attrs["alpha"] == pytest.approx(0.5)


def test_export_passes_torch_onnx_checker(tmp_path):
    """The emitted file must satisfy the REAL ONNX schema — validated by
    torch's bundled C++ proto checker (no onnx pip package needed)."""
    torch = pytest.importorskip("torch")
    s = _convnet()
    params = _init_params(s, data=(1, 3, 8, 8))
    path = str(tmp_path / "net.onnx")
    onnx_mx.export_model(s, params, [(1, 3, 8, 8)], path)
    with open(path, "rb") as f:
        torch._C._check_onnx_proto(f.read())  # raises on invalid proto


def test_export_import_numeric_roundtrip(tmp_path):
    s = _convnet()
    params = _init_params(s, data=(2, 3, 8, 8))
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3, 8, 8).astype("f4")
    expect = _forward(s, params, x)

    path = str(tmp_path / "rt.onnx")
    onnx_mx.export_model(s, params, [(2, 3, 8, 8)], path)
    s2, arg_params, aux_params = onnx_mx.import_model(path)
    got = _forward(s2, {**arg_params, **aux_params}, x)
    assert_almost_equal(got, expect, rtol=1e-4, atol=1e-5)


def test_export_import_mlp_roundtrip(tmp_path):
    d = sym.var("data")
    f1 = sym.FullyConnected(d, num_hidden=16, name="fc1")
    a1 = sym.Activation(f1, act_type="tanh", name="t1")
    f2 = sym.FullyConnected(a1, num_hidden=4, name="fc2")
    s = (f2 + 1.0) * 2.0
    params = _init_params(s, data=(3, 6))
    rng = np.random.RandomState(2)
    x = rng.randn(3, 6).astype("f4")
    expect = _forward(s, params, x)
    path = str(tmp_path / "mlp.onnx")
    onnx_mx.export_model(s, params, [(3, 6)], path)
    s2, ap, xp = onnx_mx.import_model(path)
    got = _forward(s2, {**ap, **xp}, x)
    assert_almost_equal(got, expect, rtol=1e-4, atol=1e-5)


def test_metadata(tmp_path):
    s = _convnet()
    params = _init_params(s, data=(1, 3, 8, 8))
    path = str(tmp_path / "meta.onnx")
    onnx_mx.export_model(s, params, [(1, 3, 8, 8)], path)
    meta = onnx_mx.get_model_metadata(path)
    assert meta["input_tensor_data"] == [("data", (1, 3, 8, 8))]
    assert len(meta["output_tensor_data"]) == 1


def test_unsupported_op_is_loud(tmp_path):
    d = sym.var("data")
    s = sym.MultiBoxPrior(d, sizes=(0.5,))
    with pytest.raises(MXNetError, match="no ONNX mapping"):
        onnx_mx.export_model(s, {}, [(1, 3, 4, 4)],
                             str(tmp_path / "x.onnx"))


# ---------------------------------------------------------------------------
# storage / memory introspection (kept here with the other round-3
# aux-surface tests)
# ---------------------------------------------------------------------------

def test_memory_summary_live_accounting():
    from mxnet_tpu import storage

    base = storage.memory_summary(mx.cpu(0))
    assert base["platform"] == "cpu"
    keep = nd.zeros((1024, 256))  # 1MB fp32
    after = storage.memory_summary(mx.cpu(0))
    assert after["live_array_bytes"] >= base["live_array_bytes"] + 1024 * 256 * 4
    assert after["live_arrays"] >= base["live_arrays"] + 1
    del keep


def test_memory_info_or_loud():
    from mxnet_tpu import storage

    try:
        free, total = storage.memory_info(mx.cpu(0))
        assert 0 <= free <= total
    except MXNetError as e:
        # plugins without allocator stats fail loud with the fallback hint
        assert "live buffers" in str(e)


def test_configure_after_init_is_loud():
    from mxnet_tpu import storage

    nd.zeros((1,)).asnumpy()  # backend certainly initialized
    with pytest.raises(MXNetError, match="before the first jax backend"):
        storage.configure(pool_reserve_pct=5)
