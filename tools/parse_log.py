#!/usr/bin/env python
"""Parse training logs into a table (ref role: tools/parse_log.py).

Extracts per-epoch train/validation metrics, time cost, and Speedometer
throughput from logs produced by `Module.fit` / `Speedometer` /
`Trainer` loops:

    Epoch[3] Batch [50]\tSpeed: 2461.16 samples/sec\taccuracy=0.91
    Epoch[3] Train-accuracy=0.912
    Epoch[3] Validation-accuracy=0.887
    Epoch[3] Time cost=12.345

Usage:
    python tools/parse_log.py train.log                  # markdown table
    python tools/parse_log.py train.log --format csv
    python tools/parse_log.py train.log --format json
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from collections import defaultdict

_SPEED = re.compile(
    r"Epoch\[(\d+)\]\s+Batch\s*\[(\d+)\]\s+Speed:\s*([\d.]+)\s*samples/sec")
_TRAIN = re.compile(r"Epoch\[(\d+)\]\s+Train-([\w-]+)=([-\d.einfa]+)")
_VAL = re.compile(r"Epoch\[(\d+)\]\s+Validation-([\w-]+)=([-\d.einfa]+)")
_TIME = re.compile(r"Epoch\[(\d+)\]\s+Time cost=([\d.]+)")


def parse(lines):
    """-> {epoch: {column: value}} plus mean throughput per epoch."""
    rows = defaultdict(dict)
    speeds = defaultdict(list)
    for line in lines:
        m = _SPEED.search(line)
        if m:
            speeds[int(m.group(1))].append(float(m.group(3)))
            continue
        m = _TRAIN.search(line)
        if m:
            rows[int(m.group(1))][f"train-{m.group(2)}"] = float(m.group(3))
            continue
        m = _VAL.search(line)
        if m:
            rows[int(m.group(1))][f"val-{m.group(2)}"] = float(m.group(3))
            continue
        m = _TIME.search(line)
        if m:
            rows[int(m.group(1))]["time-s"] = float(m.group(2))
    for ep, ss in speeds.items():
        rows[ep]["speed"] = sum(ss) / len(ss)
    return dict(rows)


def render(rows, fmt: str) -> str:
    epochs = sorted(rows)
    cols = sorted({c for r in rows.values() for c in r})
    if fmt == "json":
        return json.dumps({str(e): rows[e] for e in epochs}, indent=2)
    header = ["epoch"] + cols
    table = [[str(e)] + [f"{rows[e][c]:.6g}" if c in rows[e] else ""
                         for c in cols] for e in epochs]
    if fmt == "csv":
        return "\n".join(",".join(r) for r in [header] + table)
    widths = [max(len(h), *(len(r[i]) for r in table)) if table else len(h)
              for i, h in enumerate(header)]
    def fmt_row(r):
        return "| " + " | ".join(v.ljust(w) for v, w in zip(r, widths)) + " |"
    sep = "|" + "|".join("-" * (w + 2) for w in widths) + "|"
    return "\n".join([fmt_row(header), sep] + [fmt_row(r) for r in table])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("logfile", help="training log ('-' for stdin)")
    ap.add_argument("--format", choices=["markdown", "csv", "json"],
                    default="markdown")
    args = ap.parse_args(argv)
    lines = (sys.stdin if args.logfile == "-"
             else open(args.logfile)).readlines()
    rows = parse(lines)
    if not rows:
        print("no epoch records found", file=sys.stderr)
        return 1
    print(render(rows, args.format))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
