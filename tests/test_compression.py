"""Gradient-compression tests (ref: tests for gradient_compression.cc /
test_kvstore.py compression cases)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore_compression import TwoBitCompressor, create


def test_two_bit_quantization_values():
    c = TwoBitCompressor(threshold=0.5)
    g = np.array([0.7, -0.6, 0.2, -0.1, 1.4, 0.0], "float32")
    packed, shape = c.compress("k", g)
    assert packed.dtype == np.uint8
    assert len(packed) == 2  # 6 elems -> 2 bytes
    out = c.decompress(packed, shape)
    np.testing.assert_array_equal(
        out, np.array([0.5, -0.5, 0.0, 0.0, 0.5, 0.0], "float32"))
    # residual carries the quantization error
    np.testing.assert_allclose(c._residual["k"],
                               g - out, rtol=1e-6)


def test_two_bit_residual_accumulates():
    """Repeated small gradients must eventually emit via the residual."""
    c = TwoBitCompressor(threshold=0.5)
    g = np.full((8,), 0.2, "float32")
    sent = np.zeros(8, "float32")
    for _ in range(10):
        packed, shape = c.compress("k", g)
        sent += c.decompress(packed, shape)
    # 10 * 0.2 = 2.0 total; sent must be within one threshold of that
    np.testing.assert_allclose(sent, 2.0, atol=0.5)


def test_compression_wire_size():
    c = TwoBitCompressor()
    g = np.random.randn(1000).astype("float32")
    packed, _ = c.compress("k", g)
    assert len(packed) == 250  # 16x smaller than fp32


def test_create_unknown_type_is_loud():
    with pytest.raises(MXNetError, match="unknown gradient compression"):
        create({"type": "8bit"})
    with pytest.raises(MXNetError, match="not implemented"):
        create({"type": "1bit"})


def test_kvstore_dist_push_applies_compression():
    """dist kvstore + 2bit: the pushed value is the quantized gradient
    (observable single-process: allgather degenerates to self)."""
    kv = mx.kvstore.create("dist_sync")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init(0, nd.zeros((4,)))
    kv.push(0, nd.array(np.array([0.8, -0.9, 0.1, 0.0], "f4")))
    out = nd.zeros((4,))
    kv.pull(0, out)
    np.testing.assert_array_equal(
        out.asnumpy(), np.array([0.5, -0.5, 0.0, 0.0], "f4"))
    # second push: residual (0.3, -0.4, 0.1, 0) + new grad crosses thresh
    kv.push(0, nd.array(np.array([0.3, -0.2, 0.0, 0.0], "f4")))
    kv.pull(0, out)
    np.testing.assert_array_equal(
        out.asnumpy(), np.array([0.5, -0.5, 0.0, 0.0], "f4"))


def test_kvstore_local_compression_rejected():
    kv = mx.kvstore.create("local")
    with pytest.raises(MXNetError, match="not supported on 'local'"):
        kv.set_gradient_compression({"type": "2bit"})


class _StatefulReLU(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.mask = (x > 0).astype("float32")  # stashed for backward
        self.assign(out_data[0], req[0], x * self.mask)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], out_grad[0].asnumpy() * self.mask)


@mx.operator.register("test_stateful_relu")
class _StatefulReLUProp(mx.operator.CustomOpProp):
    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return _StatefulReLU()


def test_custom_op_state_shared_fwd_bwd():
    """The standard mask pattern: forward stashes state on self, backward
    reads it — the SAME operator instance must serve both."""
    x = nd.array(np.array([-1.0, 2.0, -3.0, 4.0], "f4"))
    x.attach_grad()
    with mx.autograd.record():
        y = nd.Custom(x, op_type="test_stateful_relu")
    y.backward()
    np.testing.assert_array_equal(x.grad.asnumpy(), [0.0, 1.0, 0.0, 1.0])
    # traced path shares the instance too
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.registry import apply_pure

    g = jax.grad(lambda v: apply_pure(
        "Custom", v, op_type="test_stateful_relu").sum())(
        jnp.asarray([-1.0, 2.0], jnp.float32))
    np.testing.assert_array_equal(np.asarray(g), [0.0, 1.0])


def test_device_store_compression_roundtrips():
    """Reference parity: 'device' stores accept compression (only
    'local' rejects); a MULTI-replica push — the emulated inter-device
    wire — is quantized."""
    kv = mx.kvstore.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init(0, nd.zeros((3,)))
    kv.push(0, [nd.array(np.array([0.9, -0.7, 0.1], "f4")),
                nd.array(np.array([0.0, 0.0, 0.5], "f4"))])
    out = nd.zeros((3,))
    kv.pull(0, out)
    # quantize(sum) = quantize([0.9, -0.7, 0.6])
    np.testing.assert_array_equal(out.asnumpy(), [0.5, -0.5, 0.5])


def test_single_device_compression_is_bit_exact():
    """One replica + no DCN group transmits nothing, so the lossy
    quantize/dequantize round-trip must be SKIPPED: push/pushpull of a
    single value is bit-exact even with compression params set."""
    g = np.array([0.9, -0.7, 0.1, 0.24], "f4")
    for op in ("push", "pushpull"):
        kv = mx.kvstore.create("device")
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        kv.init(0, nd.zeros((4,)))
        out = nd.zeros((4,))
        if op == "push":
            kv.push(0, nd.array(g))
            kv.pull(0, out)
        else:
            kv.pushpull(0, nd.array(g), out=out)
        np.testing.assert_array_equal(out.asnumpy(), g)


def test_single_device_sparse_plus_compression_still_loud():
    """Skipping the single-replica round-trip must NOT skip the sparse
    rejection: the invalid config fails loud before the user scales."""
    from mxnet_tpu.ndarray import sparse as sp

    kv = mx.kvstore.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    rs = sp.row_sparse_array(
        (np.ones((2, 3), "f4"), np.array([0, 2])), shape=(4, 3))
    kv.init(0, nd.zeros((4, 3)))
    with pytest.raises(MXNetError, match="sparse"):
        kv.push(0, rs)


def test_single_device_training_bit_exact_with_compression():
    """End to end: a single-device Trainer configured with
    compression_params trains bit-for-bit identically to one without —
    nothing crosses a wire, so nothing may be degraded."""
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    def run(compression):
        np.random.seed(7)
        mx.random.seed(7)
        net = nn.Dense(3, in_units=4)
        net.initialize(mx.initializer.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9},
                                kvstore="device",
                                compression_params=compression)
        x = nd.array(np.random.RandomState(3).randn(2, 4).astype("f4"))
        for _ in range(3):
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
            trainer.step(2)
        return net.weight.data().asnumpy()

    w_plain = run(None)
    w_comp = run({"type": "2bit", "threshold": 0.5})
    np.testing.assert_array_equal(w_comp, w_plain)


def test_sparse_plus_compression_is_loud():
    from mxnet_tpu.ndarray import sparse as sp

    kv = mx.kvstore.create("dist_sync")
    kv.set_gradient_compression({"type": "2bit"})
    rs = sp.row_sparse_array(
        (np.ones((2, 3), "f4"), np.array([0, 2])), shape=(4, 3))
    kv.init(0, nd.zeros((4, 3)))
    with pytest.raises(MXNetError, match="sparse"):
        kv.push(0, rs)


def test_custom_op_instance_pairing_traced():
    """Two uses of the same stateful custom op inside ONE traced function
    must each get their own operator instance: backward(a) reads a's
    mask, not b's (tokens through the custom_vjp residuals)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.registry import apply_pure

    def f(a, b):
        ya = apply_pure("Custom", a, op_type="test_stateful_relu")
        yb = apply_pure("Custom", b, op_type="test_stateful_relu")
        return ya.sum() + yb.sum()

    a = jnp.asarray([-1.0, 2.0], jnp.float32)
    b = jnp.asarray([3.0, -4.0], jnp.float32)
    ga, gb = jax.grad(f, argnums=(0, 1))(a, b)
    np.testing.assert_array_equal(np.asarray(ga), [0.0, 1.0])
    np.testing.assert_array_equal(np.asarray(gb), [1.0, 0.0])


def test_custom_op_repeated_vjp_application():
    """f_vjp applied twice must reuse the SAME stashed operator instance
    (tokens are fetched, not popped)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.registry import apply_pure

    def f(v):
        return apply_pure("Custom", v, op_type="test_stateful_relu").sum()

    v = jnp.asarray([-1.0, 2.0], jnp.float32)
    _, f_vjp = jax.vjp(f, v)
    g1 = np.asarray(f_vjp(jnp.float32(1.0))[0])
    g2 = np.asarray(f_vjp(jnp.float32(1.0))[0])
    np.testing.assert_array_equal(g1, [0.0, 1.0])
    np.testing.assert_array_equal(g2, g1)
