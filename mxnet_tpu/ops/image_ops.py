"""Registry-level image operators (the _image_* family).

TPU-native counterpart of src/operator/image/{resize,crop,image_random}.cc
(to_tensor, normalize, resize, crop, flip_left_right/up_down and the
random_* variants).  These are DEVICE ops — jax.image handles the
interpolation on-accelerator — usable eagerly, hybridized, and inside the
SPMD step; heavy JPEG decode stays in the native host pipeline.

Layout convention follows the reference: image ops take HWC (or NHWC
batched) uint8/float input; to_tensor produces CHW float scaled to [0,1].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op

__all__ = []


def _is_batched(x):
    return x.ndim == 4


@register_op("_image_to_tensor", aliases=("image_to_tensor",))
def _to_tensor(x):
    """HWC [0,255] -> CHW float32 [0,1] (ref: image/to_tensor)."""
    perm = (0, 3, 1, 2) if _is_batched(x) else (2, 0, 1)
    return jnp.transpose(x.astype(jnp.float32) / 255.0, perm)


@register_op("_image_normalize", aliases=("image_normalize",))
def _normalize(x, mean=(0.0,), std=(1.0,)):
    """Channel-wise (x - mean) / std on CHW/NCHW float input."""
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    shape = (1, -1, 1, 1) if _is_batched(x) else (-1, 1, 1)
    return (x - mean.reshape(shape)) / std.reshape(shape)


@register_op("_image_resize", aliases=("image_resize",))
def _resize(x, size=None, keep_ratio=False, interp=1):
    """Resize HWC/NHWC to `size` ((w, h) or int shorter-edge when
    keep_ratio) — bilinear (interp 1) or nearest (0)."""
    if size is None:
        raise ValueError("_image_resize requires size=")
    h_ax = 1 if _is_batched(x) else 0
    h, w = x.shape[h_ax], x.shape[h_ax + 1]
    if isinstance(size, int):
        if keep_ratio:
            scale = size / min(h, w)
            nh, nw = max(1, round(h * scale)), max(1, round(w * scale))
        else:
            nh = nw = size
    else:
        nw, nh = size  # reference passes (w, h)
    shape = ((x.shape[0], nh, nw, x.shape[3]) if _is_batched(x)
             else (nh, nw, x.shape[2]))
    method = "nearest" if interp == 0 else "bilinear"
    odtype = x.dtype
    out = jax.image.resize(x.astype(jnp.float32), shape, method=method)
    if jnp.issubdtype(odtype, jnp.integer):
        out = jnp.clip(jnp.round(out), 0, 255)
    return out.astype(odtype)


@register_op("_image_crop", aliases=("image_crop",))
def _crop(x, x0=0, y0=0, width=0, height=0):
    """Fixed crop of HWC/NHWC (ref: image/crop.cc)."""
    if _is_batched(x):
        return x[:, y0:y0 + height, x0:x0 + width]
    return x[y0:y0 + height, x0:x0 + width]


@register_op("_image_flip_left_right", aliases=("image_flip_left_right",))
def _flip_lr(x):
    """Mirror HWC/NHWC horizontally (flip the width axis)."""
    return jnp.flip(x, axis=-2)


@register_op("_image_flip_up_down", aliases=("image_flip_up_down",))
def _flip_ud(x):
    """Mirror HWC/NHWC vertically (flip the height axis)."""
    return jnp.flip(x, axis=1 if _is_batched(x) else 0)


def _keyed_coin(key):
    return jax.random.bernoulli(key, 0.5)


@register_op("_image_random_flip_left_right",
             aliases=("image_random_flip_left_right",))
def _random_flip_lr(x, key):
    """Horizontal mirror with probability 0.5 under the given PRNG key."""
    return jnp.where(_keyed_coin(key), jnp.flip(x, axis=-2), x)


@register_op("_image_random_flip_up_down",
             aliases=("image_random_flip_up_down",))
def _random_flip_ud(x, key):
    """Vertical mirror with probability 0.5 under the given PRNG key."""
    ax = 1 if _is_batched(x) else 0
    return jnp.where(_keyed_coin(key), jnp.flip(x, axis=ax), x)


@register_op("_image_random_brightness",
             aliases=("image_random_brightness",))
def _random_brightness(x, key, min_factor=0.5, max_factor=1.5):
    """Scale pixel values by a uniform factor in [min_factor, max_factor]."""
    f = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    return (x.astype(jnp.float32) * f).astype(x.dtype)


@register_op("_image_random_contrast", aliases=("image_random_contrast",))
def _random_contrast(x, key, min_factor=0.5, max_factor=1.5):
    """Blend toward the scalar luminance mean by a uniform random factor
    (factor 1 = identity, 0 = flat gray)."""
    f = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    xf = x.astype(jnp.float32)
    # luminance-mean pivot (ref: image_random.cc contrast aug)
    coef = jnp.asarray([0.299, 0.587, 0.114], jnp.float32)
    gray = jnp.mean(jnp.tensordot(xf, coef, axes=([-1], [0])))
    return (gray * (1.0 - f) + xf * f).astype(x.dtype)


@register_op("_image_random_saturation",
             aliases=("image_random_saturation",))
def _random_saturation(x, key, min_factor=0.5, max_factor=1.5):
    """Blend toward per-pixel grayscale by a uniform random factor
    (factor 1 = identity, 0 = fully desaturated)."""
    f = jax.random.uniform(key, (), minval=min_factor, maxval=max_factor)
    xf = x.astype(jnp.float32)
    coef = jnp.asarray([0.299, 0.587, 0.114], jnp.float32)
    gray = jnp.tensordot(xf, coef, axes=([-1], [0]))[..., None]
    return (gray * (1.0 - f) + xf * f).astype(x.dtype)
