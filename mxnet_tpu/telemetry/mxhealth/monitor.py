"""The mxhealth monitor: where the in-graph numerics land.

The fused/SPMD step programs emit tiny extra outputs (per-bucket
grad/update/param norm-squares and a global nonfinite count — see
optimizer/fused.py and optimizer/spmd.py) and hand the DEVICE arrays
here via :meth:`HealthMonitor.on_step`.  The monitor:

  * fetches them to host **asynchronously** on a daemon thread (the
    step never blocks on a device sync; under the ``raise`` policy the
    fetch is synchronous by design — that policy's whole point is to
    stop the step);
  * keeps a bounded ring of health samples and detector events;
  * updates the declared metric families (``mx_grad_norm``,
    ``mx_param_norm``, ``mx_update_ratio``, ``mx_nonfinite_total``,
    ``mx_health_events_total``) — like mxprof's own gauges, these
    update whenever a sample lands, telemetry flag or not;
  * runs the rolling median/MAD spike detectors (grad norm, loss) and
    the update/param ratio-drift check on the fetch thread.

Locking: the producer-facing queue and the fetch-thread state live
under separate locks, so the step path never waits behind detector
math (see ``HealthMonitor.__init__``).  Samples are step-scale, never
op-scale.
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ...base import MXNetError
from .. import instruments as _ins
from .detectors import RollingMAD, ratio_drift

__all__ = ["HealthMonitor", "NonFiniteGradient", "POLICIES"]

POLICIES = ("record", "raise", "skip_step")


class NonFiniteGradient(MXNetError):
    """Raised from the step under ``MXNET_HEALTH_POLICY=raise`` when
    the in-graph counter saw nonfinite gradient values.  Raised BEFORE
    the new weights/states are written back, so the parameters stay at
    their pre-step values."""

    def __init__(self, step: int, count: float, site: str):
        super().__init__(
            f"[mxhealth] {int(count)} nonfinite gradient value(s) at "
            f"step {step} ({site}); params left at pre-step values")
        self.step = step
        self.count = count
        self.site = site


def _f(x) -> float:
    """Device array / numpy / python scalar -> float (the host fetch)."""
    return float(np.asarray(x))


def _norm(sq_vec) -> float:
    """sqrt(sum of norm-squares); a nonfinite contribution propagates
    (a NaN'd bucket must show as a NaN norm, not be masked)."""
    arr = np.asarray(sq_vec, dtype=np.float64)
    return float(np.sqrt(arr.sum())) if arr.size else 0.0


class HealthMonitor:
    """Numerics telemetry sink + detector host.  One per process (the
    package singleton in ``mxhealth.__init__``); tests build private
    instances."""

    def __init__(self, policy: str = "record", every: int = 1,
                 window: int = 64, spike_k: float = 8.0,
                 ratio_max: float = 0.1, ring: int = 512):
        if policy not in POLICIES:
            raise MXNetError(
                f"mxhealth policy {policy!r} unknown; expected one of "
                f"{POLICIES}")
        self.policy = policy
        self.every = max(1, int(every))
        self.ratio_max = float(ratio_max)
        # two locks by design: `_lock` guards ONLY the producer-facing
        # queue/step counter (what the step path touches — appends and
        # a counter bump, microseconds); `_state_lock` guards the
        # rings/windows the fetch thread mutates with real work under
        # it (a rolling-median sort).  One shared lock would stall the
        # training step behind detector math — the overhead gate
        # caught exactly that.
        self._lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._samples: "deque[dict]" = deque(maxlen=max(1, int(ring)))
        self._events: "deque[dict]" = deque(maxlen=max(1, int(ring)))
        self._step = 0
        self._grad_mad = RollingMAD(window=window, k=spike_k)
        self._loss_mad = RollingMAD(window=window, k=spike_k)
        self._nonfinite_steps = 0
        self._skipped_steps = 0
        # async fetch plumbing: payloads queue here, one daemon thread
        # drains — holding the device arrays costs nothing until the
        # fetch thread touches them, so the step path never syncs
        self._queue: "deque[tuple]" = deque()
        self._queue_cap = max(1, int(ring))
        self._fetch_dropped = 0
        self._cv = threading.Condition(self._lock)
        self._worker: Optional[threading.Thread] = None
        self._inflight = 0

    # ---- the step-path entry points ----------------------------------

    def on_step(self, site: str, payload: Dict[str, object]) -> None:
        """One step's health outputs.  ``payload`` carries device (or
        host) arrays: ``gn2``/``un2``/``pn2`` norm-square vectors,
        ``nonfinite`` scalar, and ``guarded`` (True when the in-graph
        skip_step guard selected the outputs).  Called once per step by
        the reporting replica; everything heavier than an append
        happens on the fetch thread — except under the ``raise``
        policy, whose sync check is the contract.  The cadence gate
        applies to the async policies only: ``raise`` promises params
        at their pre-step values, which a cadence-skipped step could
        silently violate (the NaN would be written back and the raise
        would fire steps later), so it checks EVERY step.  Under
        ``skip_step`` every payload is enqueued too — the guard runs
        every step, and a skip on a non-sampled step must still be
        counted — but the fetch thread discards clean off-cadence
        samples without recording them, so the cadence still bounds
        what lands in the ring."""
        with self._lock:
            self._step += 1
            step = self._step
            on_cadence = not (step - 1) % self.every
            if self.policy == "record" and not on_cadence:
                return
        if self.policy == "raise":
            self._ingest(site, step, payload)  # may raise
            return
        if not on_cadence:
            payload = dict(payload, sample=False)
        self._enqueue((site, step, payload))

    def observe_loss(self, value, step: Optional[int] = None) -> None:
        """Feed one loss sample (device array or float) to the
        loss-spike detector; fetched on the async thread like the step
        payloads."""
        self._enqueue(("loss", step or self._step, {"loss": value}))

    def _enqueue(self, item) -> None:
        """Hand one payload to the fetch thread.  The queue is BOUNDED
        by the ring size — if a sick device wedges the fetch thread's
        sync (or steps outrun it), the newest samples are dropped and
        counted rather than pinning device arrays without bound (the
        flat-memory promise the ring already makes)."""
        with self._lock:
            if len(self._queue) >= self._queue_cap:
                self._fetch_dropped += 1
                return
            self._queue.append(item)
            self._inflight += 1
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._drain, name="mxhealth-fetch",
                    daemon=True)
                self._worker.start()
            self._cv.notify()

    # ---- the fetch thread --------------------------------------------

    def _drain(self) -> None:
        while True:
            with self._lock:
                while not self._queue:
                    # idle fetch threads park; a 60s patience then exit
                    # keeps a finished process from pinning the thread.
                    # Condition.wait RELEASES the lock while parked —
                    # the canonical CV idiom, not a held-lock block
                    if not self._cv.wait(timeout=60.0):  # mxlint: disable=MX008
                        return
                site, step, payload = self._queue.popleft()
            try:
                self._ingest(site, step, payload)
            except Exception:  # noqa: BLE001 — a fetch must never kill training
                pass
            finally:
                with self._lock:
                    self._inflight -= 1
                    self._cv.notify_all()

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every queued payload is ingested (tests, dumps).
        Returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._queue or self._inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                # Condition.wait releases the lock while parked (the
                # canonical CV idiom — producers are never stalled)
                self._cv.wait(timeout=left)  # mxlint: disable=MX008
        return True

    # ---- ingestion + detectors ---------------------------------------

    def _event(self, kind: str, step: int, detail: dict) -> dict:
        ev = {"t": time.time(), "step": step, "kind": kind, **detail}
        self._events.append(ev)
        _ins.health_events_total(kind).inc()
        from .. import mxblackbox as _bb

        if _bb._ACTIVE:
            _bb.emit("health", f"health event {kind}", step=step,
                     kind=kind, **{k: v for k, v in detail.items()
                                   if isinstance(v, (int, float, str,
                                                     bool))})
        return ev

    def _ingest(self, site: str, step: int,
                payload: Dict[str, object]) -> None:
        if site == "loss":
            loss = _f(payload["loss"])
            with self._state_lock:
                if not math.isfinite(loss):
                    self._event("loss-nonfinite", step,
                                {"value": loss})
                    return
                spike = self._loss_mad.update(loss)
                if spike is not None:
                    self._event("loss-spike", step, spike)
            return
        nf = _f(payload.get("nonfinite", 0.0))
        if not nf and not payload.get("sample", True):
            # clean off-cadence payload (skip_step enqueues every step
            # so a guard rejection is never invisible): nothing to
            # record, the cadence still bounds the ring
            return
        gn = _norm(payload.get("gn2", ()))
        un = _norm(payload.get("un2", ()))
        pn = _norm(payload.get("pn2", ()))
        guarded = bool(payload.get("guarded"))
        sample = {"t": time.time(), "step": step, "site": site,
                  "grad_norm": gn, "update_norm": un, "param_norm": pn,
                  "nonfinite": nf, "guarded": guarded}
        _ins.grad_norm().set(gn)
        _ins.param_norm().set(pn)
        if pn > 0 and math.isfinite(un):
            _ins.update_ratio().set(un / pn)
        if nf:
            _ins.nonfinite_total().inc(nf)
        try:
            with self._state_lock:
                self._samples.append(sample)
                if nf:
                    self._nonfinite_steps += 1
                    self._event("nonfinite", step,
                                {"count": nf, "site": site,
                                 "action": self.policy})
                    if guarded:
                        self._skipped_steps += 1
                        _ins.health_steps_skipped_total().inc()
                    if self.policy == "raise":
                        raise NonFiniteGradient(step, nf, site)
                    return  # NaN norms must not poison spike windows
                if math.isfinite(gn):
                    spike = self._grad_mad.update(gn)
                    if spike is not None:
                        self._event("grad-spike", step, spike)
                drift = ratio_drift(un, pn, self.ratio_max)
                if drift is not None:
                    self._event("update-ratio", step, drift)
        except NonFiniteGradient as e:
            # crash bundle OUTSIDE the state lock: the gatherers take
            # other subsystems' locks (alerts engine, recorder), and
            # those may take _state_lock on their own threads
            from .. import mxblackbox as _bb

            if _bb._ACTIVE:
                _bb.write_crash_bundle(
                    "health",
                    reason=f"nonfinite gradient at step {step} "
                           f"({site})", step=step, exc=e)
            raise

    def record_straggler(self, step: int, detail: dict) -> None:
        """Straggler findings come from merged traces (tools), not the
        step path — recorded through the same event ring so one report
        carries everything."""
        with self._state_lock:
            self._event("straggler", step, detail)

    # ---- introspection -----------------------------------------------

    def step_count(self) -> int:
        with self._lock:
            return self._step

    def samples(self) -> List[dict]:
        with self._state_lock:
            return list(self._samples)

    def events(self, kind: Optional[str] = None) -> List[dict]:
        with self._state_lock:
            evs = list(self._events)
        return evs if kind is None else [e for e in evs
                                         if e["kind"] == kind]

    def verdict(self) -> str:
        """One word: 'healthy' (no detector fired), 'degraded' (spikes
        or drift, training continued), 'unhealthy' (nonfinite
        gradients seen)."""
        with self._state_lock:
            if self._nonfinite_steps:
                return "unhealthy"
            return "degraded" if self._events else "healthy"

    def report(self, flush_timeout: float = 5.0) -> dict:
        """The per-run health report (what tools/health_report.py and
        HEALTH.json embed).  ``flush_timeout=0`` renders from the
        already-fetched state — the /statusz path uses it, because a
        diagnostics page must not stall behind the wedged device sync
        it exists to diagnose."""
        if flush_timeout > 0:
            self.flush(timeout=flush_timeout)
        with self._state_lock:
            last = self._samples[-1] if self._samples else None
            return {
                "policy": self.policy,
                "every": self.every,
                "steps_observed": self._step,
                "samples_fetched": len(self._samples),
                "fetch_dropped": self._fetch_dropped,
                "nonfinite_steps": self._nonfinite_steps,
                "skipped_steps": self._skipped_steps,
                "last_sample": dict(last) if last else None,
                "events": [dict(e) for e in self._events],
                "verdict": ("unhealthy" if self._nonfinite_steps else
                            "degraded" if self._events else "healthy"),
            }
