"""ONNX import/export for Symbol graphs.

Counterpart of the reference's python/mxnet/contrib/onnx/ (mx2onnx
export + onnx2mx import).  The reference rides the `onnx` pip package;
this container cannot install it, so serialization uses the bundled
pure-Python protobuf layer (proto.py, validated against torch's C++
ONNX schema checker) and the op mapping lives here.

Supported op set (the common CNN/MLP interchange core, opset 13):
Conv, Gemm(+Flatten), BatchNormalization, Relu/Sigmoid/Tanh/Softplus,
MaxPool/AveragePool/Global*Pool, Softmax, Add/Sub/Mul/Div, Concat,
Reshape, Transpose, Flatten, Dropout, Identity.  Unsupported ops raise
with the op name (same contract as the reference's converter).

API parity::

    from mxnet_tpu.contrib import onnx as onnx_mxnet
    onnx_mxnet.export_model(sym, params, [(1, 3, 224, 224)], "net.onnx")
    sym, arg_params, aux_params = onnx_mxnet.import_model("net.onnx")
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...base import MXNetError
from . import proto

__all__ = ["export_model", "import_model", "get_model_metadata"]


# ---------------------------------------------------------------------------
# export: Symbol -> ONNX
# ---------------------------------------------------------------------------

def _pool_onnx(node, mk):
    a = node.attrs
    kernel = list(a.get("kernel", ()))
    if a.get("global_pool", False):
        op = ("GlobalMaxPool" if a.get("pool_type", "max") == "max"
              else "GlobalAveragePool")
        return mk(op, {})
    pads = list(a.get("pad", ())) or [0] * len(kernel)
    attrs = {"kernel_shape": kernel,
             "strides": list(a.get("stride", ())) or [1] * len(kernel),
             "pads": pads + pads}
    if a.get("pool_type", "max") == "max":
        return mk("MaxPool", attrs)
    attrs["count_include_pad"] = int(a.get("count_include_pad", True))
    return mk("AveragePool", attrs)


def export_model(sym, params, input_shapes: Sequence[Tuple[int, ...]],
                 onnx_file: str = "model.onnx",
                 input_dtype=np.float32, verbose: bool = False) -> str:
    """Export a Symbol + params to an ONNX file
    (ref: contrib/onnx/mx2onnx/export_model.py).  `params` maps names to
    NDArray/numpy; 'arg:'/'aux:' prefixes (checkpoint convention) are
    accepted."""
    from ...ndarray.ndarray import NDArray
    from ...symbol.symbol import Symbol

    if not isinstance(sym, Symbol):
        from ...symbol import load as sym_load

        sym = sym_load(sym)
    weights: Dict[str, np.ndarray] = {}
    for k, v in dict(params).items():
        name = k.split(":", 1)[1] if ":" in k else k
        weights[name] = np.asarray(
            v.asnumpy() if isinstance(v, NDArray) else v)

    g = proto.Graph(name=sym.name or "mxnet_tpu")
    topo = sym._topo()
    out_name: Dict[Tuple[int, int], str] = {}
    data_inputs = [n for n in topo
                   if n.op is None and n.name not in weights]
    if len(data_inputs) != len(input_shapes):
        raise MXNetError(
            f"export_model got {len(input_shapes)} input_shapes for "
            f"{len(data_inputs)} graph inputs "
            f"({[n.name for n in data_inputs]})")
    for n, shp in zip(data_inputs, input_shapes):
        g.inputs.append(proto.ValueInfo(
            n.name, proto.NP_TO_DT[np.dtype(input_dtype)], list(shp)))
        out_name[(id(n), 0)] = n.name
    for n in topo:
        if n.op is None and n.name in weights:
            g.initializers.append(
                proto.Tensor.from_numpy(n.name, weights[n.name]))
            out_name[(id(n), 0)] = n.name

    def conv_node(node, ins, outs):
        a = node.attrs
        kernel = list(a.get("kernel", ()))
        pads = list(a.get("pad", ())) or [0] * len(kernel)
        return [proto.Node(
            op_type="Conv", inputs=ins, outputs=outs, name=node.name,
            attrs={"kernel_shape": kernel,
                   "strides": list(a.get("stride", ())) or [1] * len(kernel),
                   "pads": pads + pads,
                   "dilations": list(a.get("dilate", ())) or [1] * len(kernel),
                   "group": int(a.get("num_group", 1))})]

    def fc_node(node, ins, outs):
        a = node.attrs
        nodes = []
        data = ins[0]
        if a.get("flatten", True):
            flat = node.name + "_flat"
            nodes.append(proto.Node(op_type="Flatten", inputs=[data],
                                    outputs=[flat], name=flat,
                                    attrs={"axis": 1}))
            data = flat
        nodes.append(proto.Node(
            op_type="Gemm", inputs=[data] + ins[1:], outputs=outs,
            name=node.name,
            attrs={"alpha": 1.0, "beta": 1.0, "transB": 1}))
        return nodes

    def bn_node(node, ins, outs):
        a = node.attrs
        return [proto.Node(
            op_type="BatchNormalization", inputs=ins, outputs=outs,
            name=node.name,
            attrs={"epsilon": float(a.get("eps", 1e-5)),
                   "momentum": float(a.get("momentum", 0.9))})]

    def act_node(node, ins, outs):
        mapping = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
                   "softrelu": "Softplus"}
        t = node.attrs.get("act_type", "relu")
        if t not in mapping:
            raise MXNetError(f"onnx export: unsupported act_type {t!r}")
        return [proto.Node(op_type=mapping[t], inputs=ins, outputs=outs,
                           name=node.name)]

    def reshape_node(node, ins, outs):
        shape = np.asarray(node.attrs.get("shape", ()), np.int64)
        sname = node.name + "_shape"
        g.initializers.append(proto.Tensor.from_numpy(sname, shape))
        return [proto.Node(op_type="Reshape", inputs=ins + [sname],
                           outputs=outs, name=node.name)]

    simple = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
              "elemwise_add": "Add", "broadcast_add": "Add",
              "elemwise_sub": "Sub", "broadcast_sub": "Sub",
              "elemwise_mul": "Mul", "broadcast_mul": "Mul",
              "elemwise_div": "Div", "broadcast_div": "Div",
              "flatten": "Flatten", "Flatten": "Flatten",
              "identity": "Identity", "_copy": "Identity"}

    for node in topo:
        if node.op is None:
            if (id(node), 0) not in out_name:
                raise MXNetError(
                    f"onnx export: free variable {node.name!r} has no "
                    "shape (pass it in input_shapes) and no weight")
            continue
        ins = []
        for (inp, idx) in node.inputs:
            name = out_name.get((id(inp), idx))
            if name is None:
                raise MXNetError(
                    f"onnx export: {node.op} consumes output {idx} of "
                    f"{inp.op} {inp.name!r}, which has no ONNX "
                    f"equivalent (e.g. BatchNorm mean/var side outputs)")
            ins.append(name)
        outs = [node.name if node.num_outputs == 1
                else f"{node.name}_{i}" for i in range(node.num_outputs)]
        for i in range(node.num_outputs):
            out_name[(id(node), i)] = outs[i]

        def mk(op_type, attrs):
            return [proto.Node(op_type=op_type, inputs=ins, outputs=outs,
                               name=node.name, attrs=attrs)]

        op = node.op
        if op == "Convolution":
            new = conv_node(node, ins, outs)
        elif op == "FullyConnected":
            new = fc_node(node, ins, outs)
        elif op == "BatchNorm":
            new = bn_node(node, ins, outs[:1])
            out_name[(id(node), 0)] = outs[0]
            # ONNX BatchNormalization (inference) has one output; the
            # mean/var side outputs (output_mean_var=1) have no ONNX
            # name -> a consumer of them fails loudly at lookup above
            for i in range(1, node.num_outputs):
                out_name.pop((id(node), i), None)
        elif op == "Activation":
            new = act_node(node, ins, outs)
        elif op == "Pooling":
            new = _pool_onnx(node, mk)
        elif op in ("softmax", "SoftmaxOutput"):
            new = [proto.Node(op_type="Softmax", inputs=ins[:1],
                              outputs=outs, name=node.name,
                              attrs={"axis": -1})]
        elif op == "Dropout":
            new = [proto.Node(op_type="Identity", inputs=ins[:1],
                              outputs=outs, name=node.name)]
        elif op == "reshape":
            new = reshape_node(node, ins, outs)
        elif op == "transpose":
            axes = node.attrs.get("axes")
            new = mk("Transpose", {"perm": list(axes)} if axes else {})
        elif op == "concat" or op == "Concat":
            new = mk("Concat", {"axis": int(node.attrs.get("dim", 1))})
        elif op in simple:
            new = mk(simple[op], {})
        elif op in ("_plus_scalar", "_mul_scalar", "_minus_scalar",
                    "_div_scalar"):
            const = np.asarray(node.attrs.get("scalar", 0.0), np.float32)
            cname = node.name + "_const"
            g.initializers.append(proto.Tensor.from_numpy(cname, const))
            op_map = {"_plus_scalar": "Add", "_mul_scalar": "Mul",
                      "_minus_scalar": "Sub", "_div_scalar": "Div"}
            new = [proto.Node(op_type=op_map[op], inputs=ins + [cname],
                              outputs=outs, name=node.name)]
        else:
            raise MXNetError(
                f"onnx export: operator {op!r} has no ONNX mapping yet "
                "(ref: mx2onnx op coverage is similarly incremental)")
        g.nodes.extend(new)

    try:
        shape_kwargs = {n.name: shp
                        for n, shp in zip(data_inputs, input_shapes)}
        _, out_shapes, _ = sym.infer_shape_partial(**shape_kwargs)
    except Exception:
        out_shapes = [None] * len(sym._heads)
    for (n, i), oshape in zip(sym._heads, out_shapes):
        head = out_name.get((id(n), i))
        if head is None:
            raise MXNetError(
                f"onnx export: graph output {i} of {n.op} {n.name!r} "
                f"has no ONNX equivalent (e.g. BatchNorm mean/var side "
                f"outputs)")
        g.outputs.append(proto.ValueInfo(
            head, proto.DT_FLOAT, list(oshape) if oshape else []))
    model = proto.Model(graph=g)
    proto.save(model, onnx_file)
    return onnx_file


# ---------------------------------------------------------------------------
# import: ONNX -> Symbol
# ---------------------------------------------------------------------------

def import_model(model_file: str):
    """Load an ONNX model -> (sym, arg_params, aux_params)
    (ref: contrib/onnx/onnx2mx/import_model.py)."""
    from ...ndarray.ndarray import array as nd_array
    from ... import symbol as sym_mod

    m = proto.load(model_file)
    g = m.graph
    inits = {t.name: t.to_numpy() for t in g.initializers}
    sym_of: Dict[str, object] = {}
    arg_params: Dict[str, object] = {}
    aux_params: Dict[str, object] = {}

    for vi in g.inputs:
        if vi.name not in inits:
            sym_of[vi.name] = sym_mod.var(vi.name, shape=[
                d if d else 1 for d in vi.shape] or None)

    def var_for(name: str, aux: bool = False):
        if name in sym_of:
            return sym_of[name]
        if name not in inits:
            raise MXNetError(f"onnx import: undefined input {name!r}")
        v = sym_mod.var(name)
        if aux:
            v._heads[0][0].is_aux = True
            aux_params[name] = nd_array(inits[name])
        else:
            arg_params[name] = nd_array(inits[name])
        sym_of[name] = v
        return v

    def out(node, results):
        res = results if isinstance(results, (list, tuple)) else [results]
        for nm, s in zip(node.outputs, res):
            sym_of[nm] = s

    simple = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
              "Softplus": "softrelu", "Add": "broadcast_add",
              "Sub": "broadcast_sub", "Mul": "broadcast_mul",
              "Div": "broadcast_div", "Identity": "identity",
              "Flatten": "flatten"}

    def _weight_init(inits, node, i):
        name = node.inputs[i]
        if name not in inits:
            raise MXNetError(
                f"onnx import: {node.op_type} weight '{name}' is not a "
                f"graph initializer (it is a graph input or produced by "
                f"another node); only initializer-backed weights are "
                f"supported")
        return inits[name]

    for node in g.nodes:
        a = node.attrs
        op = node.op_type
        if op == "Conv":
            kernel = a.get("kernel_shape")
            pads = a.get("pads", [0] * (2 * len(kernel)))
            if pads[:len(kernel)] != pads[len(kernel):]:
                raise MXNetError("onnx import: asymmetric Conv pads "
                                 "are not supported")
            w = _weight_init(inits, node, 1)
            res = sym_mod.Convolution(
                var_for(node.inputs[0]), var_for(node.inputs[1]),
                *( [var_for(node.inputs[2])] if len(node.inputs) > 2
                   else []),
                kernel=tuple(kernel), num_filter=int(w.shape[0]),
                stride=tuple(a.get("strides", [1] * len(kernel))),
                pad=tuple(pads[:len(kernel)]),
                dilate=tuple(a.get("dilations", [1] * len(kernel))),
                num_group=int(a.get("group", 1)),
                no_bias=len(node.inputs) <= 2, name=node.name or None)
            out(node, res)
        elif op == "Gemm":
            if a.get("transB", 0) != 1 or a.get("transA", 0) != 0 or \
                    a.get("alpha", 1.0) != 1.0 or a.get("beta", 1.0) != 1.0:
                raise MXNetError("onnx import: general Gemm forms beyond "
                                 "Y = X W^T + b are not supported")
            w = _weight_init(inits, node, 1)
            res = sym_mod.FullyConnected(
                var_for(node.inputs[0]), var_for(node.inputs[1]),
                *( [var_for(node.inputs[2])] if len(node.inputs) > 2
                   else []),
                num_hidden=int(w.shape[0]), flatten=False,
                no_bias=len(node.inputs) <= 2, name=node.name or None)
            out(node, res)
        elif op == "BatchNormalization":
            res = sym_mod.BatchNorm(
                var_for(node.inputs[0]), var_for(node.inputs[1]),
                var_for(node.inputs[2]),
                var_for(node.inputs[3], aux=True),
                var_for(node.inputs[4], aux=True),
                eps=float(a.get("epsilon", 1e-5)),
                momentum=float(a.get("momentum", 0.9)),
                name=node.name or None)
            out(node, res)
        elif op in ("MaxPool", "AveragePool"):
            kernel = a.get("kernel_shape")
            pads = a.get("pads", [0] * (2 * len(kernel)))
            res = sym_mod.Pooling(
                var_for(node.inputs[0]), kernel=tuple(kernel),
                stride=tuple(a.get("strides", [1] * len(kernel))),
                pad=tuple(pads[:len(kernel)]),
                pool_type="max" if op == "MaxPool" else "avg",
                count_include_pad=bool(a.get("count_include_pad", 1)),
                name=node.name or None)
            out(node, res)
        elif op in ("GlobalMaxPool", "GlobalAveragePool"):
            res = sym_mod.Pooling(
                var_for(node.inputs[0]), global_pool=True,
                pool_type="max" if "Max" in op else "avg",
                name=node.name or None)
            out(node, res)
        elif op == "Softmax":
            res = sym_mod.softmax(var_for(node.inputs[0]),
                                  axis=int(a.get("axis", -1)))
            out(node, res)
        elif op == "Reshape":
            shape = inits.get(node.inputs[1])
            if shape is None:
                raise MXNetError("onnx import: dynamic Reshape shape "
                                 "inputs are not supported")
            res = sym_mod.reshape(var_for(node.inputs[0]),
                                  shape=tuple(int(s) for s in shape))
            out(node, res)
        elif op == "Transpose":
            perm = a.get("perm")
            res = sym_mod.transpose(var_for(node.inputs[0]),
                                    axes=tuple(perm) if perm else None)
            out(node, res)
        elif op == "Concat":
            res = sym_mod.concat(*[var_for(i) for i in node.inputs],
                                 dim=int(a.get("axis", 1)))
            out(node, res)
        elif op == "Dropout":
            out(node, sym_mod.identity(var_for(node.inputs[0])))
        elif op in simple:
            fn = getattr(sym_mod, simple[op])
            res = fn(*[var_for(i) for i in node.inputs])
            out(node, res)
        else:
            raise MXNetError(
                f"onnx import: operator {op!r} has no mapping yet")

    from ...symbol.symbol import Group

    outs = [sym_of[vi.name] for vi in g.outputs]
    sym = outs[0] if len(outs) == 1 else Group(outs)
    return sym, arg_params, aux_params


def get_model_metadata(model_file: str) -> Dict[str, List]:
    """ref: contrib/onnx get_model_metadata — input/output signatures."""
    m = proto.load(model_file)
    inits = {t.name for t in m.graph.initializers}
    return {
        "input_tensor_data": [
            (vi.name, tuple(vi.shape)) for vi in m.graph.inputs
            if vi.name not in inits],
        "output_tensor_data": [
            (vi.name, tuple(vi.shape)) for vi in m.graph.outputs],
    }
