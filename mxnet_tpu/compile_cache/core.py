"""The two-tier compile cache behind ``get_or_compile``.

Tier 1 is an in-process dict keyed by the full digest: a site whose own
(bounded) executable cache just evicted an entry gets it back here for
the price of a lower + digest, never an XLA compile.  Tier 2 is the
content-addressed :class:`~mxnet_tpu.compile_cache.store.DiskStore`,
shared across processes: a fresh process (deploy, preemption restart,
autoscale-up) loads yesterday's executables instead of paying the
compile storm.

Entry tiers (self-described in the entry header):

  * ``exec`` — the serialized compiled executable
    (``jax.experimental.serialize_executable``).  A hit deserializes
    and runs: **no XLA compilation at all**.
  * ``stablehlo`` — the lowered module text, persisted when the
    backend cannot serialize the executable.  A hit proves the program
    is byte-identical to a known-good build and re-``compile()``\\ s the
    caller's in-process lowering (trace+lower were already spent
    producing the digest); the compile still runs, so call sites count
    it as a real build.

``get_or_compile`` returns ``(executable, origin)`` with origin one of
``"memory"`` / ``"disk"`` / ``"compiled"`` — call sites use it to keep
their compile counters honest (a disk hit must not look like a compile,
and vice versa) and to hand :func:`mxsan.record_compile` its cache
provenance.
"""
from __future__ import annotations

import itertools
import pickle
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ..analysis import sanitizer as _mxsan
from ..telemetry import instruments as _ins
from ..util import env as _env
from .key import CacheKey, env_fingerprint
from .store import DiskStore

__all__ = ["CompileCache", "get_cache", "get_or_compile", "stats",
           "reset", "enabled"]

_TICKS = itertools.count(1)


class _MemEntry:
    """``touch`` is the EXEC-tier disk digest this entry's payload
    lives under — what a memory hit must mtime-refresh so byte-cap
    eviction sees the use.  For an alias-keyed entry that is the alias
    TARGET, not the (tiny) alias file itself."""

    __slots__ = ("fn", "tick", "touch")

    def __init__(self, fn, touch=None):
        self.fn = fn
        self.tick = next(_TICKS)
        self.touch = touch


def _encode_executable(compiled: Any,
                       program_text: Optional[str]) -> Optional[Tuple[str, bytes]]:
    """(tier, payload) for one compiled executable, or None when
    nothing persistable exists (serialization unsupported AND no
    program text to fall back to)."""
    try:
        from jax.experimental import serialize_executable as _se

        payload, in_tree, out_tree = _se.serialize(compiled)
        return "exec", pickle.dumps(
            {"payload": payload, "in_tree": in_tree,
             "out_tree": out_tree}, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:  # noqa: BLE001 — backend/runtime may not support it
        if program_text is not None:
            return "stablehlo", program_text.encode()
        return None


def _decode_executable(payload: bytes) -> Any:
    """Rehydrate an ``exec``-tier payload into a callable executable."""
    from jax.experimental import serialize_executable as _se

    d = pickle.loads(payload)
    return _se.deserialize_and_load(d["payload"], d["in_tree"],
                                    d["out_tree"])


class CompileCache:
    """One memory+disk cache.  The process normally holds a single
    instance (:func:`get_cache`); tests construct private ones."""

    def __init__(self, disk_dir: Optional[str] = None,
                 cap_bytes: int = 0, mem_entries: int = 256):
        self._lock = threading.Lock()
        # mxsan: all memory-tier accesses hold self._lock (digest
        # lookups are rare — once per site-cache miss, not per step)
        self._mem: Dict[str, _MemEntry] = _mxsan.track(
            {}, "compile_cache._mem")
        self.mem_entries = int(mem_entries)
        self.disk = DiskStore(disk_dir, cap_bytes) if disk_dir else None
        # process-local stats: cheap to assert in tests, mirrored to
        # telemetry for operations
        self._stats = {"memory_hits": 0, "disk_hits": 0,
                       "stablehlo_hits": 0, "misses": 0, "writes": 0,
                       "write_errors": 0, "mem_evictions": 0,
                       "decode_failures": 0}

    # ---- the one public verb -----------------------------------------

    def get_or_compile(self, site: str, key, compile_fn: Callable[[], Any],
                       alias: Optional[CacheKey] = None) -> Tuple[Any, str]:
        """The executable for ``key``: memory tier, then disk, then
        ``compile_fn()`` (storing the result).  Returns
        ``(executable, origin)``; origin ``"compiled"`` means an XLA
        compilation actually ran in this call.

        ``key`` may be a :class:`CacheKey` or a zero-arg callable
        returning one — pass a callable when building the full key is
        itself expensive (it digests the lowered program text, so it
        needs trace+lower).  ``alias`` is a CHEAP secondary key (no
        program text: artifact fingerprint + bucket + avals) stored as
        a tiny index entry pointing at the full digest.  An alias hit
        on a warm process therefore skips trace+lower entirely — the
        difference between "restart compiles nothing" and "restart
        still re-traces every program to find out it compiled
        nothing"."""
        adig = alias.digest if alias is not None else None
        if adig is not None:
            hit = self._mem_hit(site, adig)
            if hit is not None:
                return hit, "memory"
            if self.disk is not None:
                got = self._load_alias(site, adig)
                if got is not None:
                    exe, target = got
                    self._mem_put(adig, exe, touch=target)
                    return exe, "disk"

        if callable(key) and not isinstance(key, CacheKey):
            key = key()
        digest = key.digest
        hit = self._mem_hit(site, digest)
        if hit is not None:
            if adig is not None:
                self._mem_put(adig, hit, touch=digest)
            return hit, "memory"

        if self.disk is not None:
            got = self._load_disk(site, digest)
            if got is not None:
                exe, origin = got
                if exe is not None:
                    self._mem_put(digest, exe, touch=digest)
                    if adig is not None:
                        self._mem_put(adig, exe, touch=digest)
                        self._store_alias(adig, digest)
                    return exe, origin
                # stablehlo tier: the program is known-good but the
                # executable wasn't persistable — compile the caller's
                # in-process lowering (counted as a real build)
                compiled = compile_fn()
                self._mem_put(digest, compiled, touch=digest)
                return compiled, "compiled"

        with self._lock:
            self._stats["misses"] += 1
        _ins.compile_cache_miss_total(site).inc()
        # compile provenance (telemetry.mxtriage): every miss records
        # WHICH signature component changed vs the nearest prior
        # compile at this site — the recompile-storm diagnosis layer
        # (record_miss never raises)
        from ..telemetry.mxtriage import provenance as _prov

        _prov.record_miss(site, key)
        compiled = compile_fn()
        self._mem_put(digest, compiled, touch=digest)
        if adig is not None:
            self._mem_put(adig, compiled, touch=digest)
        if self.disk is not None:
            stored = self._store_disk(site, key, digest, compiled)
            if stored and adig is not None:
                self._store_alias(adig, digest)
        return compiled, "compiled"

    def _mem_hit(self, site: str, digest: str):
        with self._lock:
            ent = self._mem.get(digest)
            if ent is not None:
                ent.tick = next(_TICKS)
                self._stats["memory_hits"] += 1
        if ent is None:
            return None
        _ins.compile_cache_hit_total(site, "memory").inc()
        if self.disk is not None and ent.touch is not None:
            # a memory-tier hit is still USE of the disk entry: refresh
            # its mtime so byte-cap eviction (LRU by mtime) does not
            # drop the hottest executables first just because their
            # consumers stopped touching the disk
            self.disk.touch(ent.touch)
        return ent.fn

    def _load_alias(self, site: str, adig: str):
        """Follow an alias index entry to its exec-tier target;
        ``(executable, target_digest)`` on a hit, None on any miss
        along the way (the caller falls through to the full path,
        which re-creates both entries)."""
        t0 = time.perf_counter()
        got = self.disk.load(adig)
        if got is None:
            return None
        header, payload = got
        if header["tier"] != "alias":
            return None
        try:
            target = payload.decode("ascii")
        except UnicodeDecodeError:
            self.disk.quarantine(adig)
            return None
        got = self.disk.load(target)
        if got is None or got[0]["tier"] != "exec":
            return None
        try:
            exe = _decode_executable(got[1])
        except Exception:  # noqa: BLE001 — incompatibility, not corruption
            with self._lock:
                self._stats["decode_failures"] += 1
            return None
        dt = time.perf_counter() - t0
        with self._lock:
            self._stats["disk_hits"] += 1
        _ins.compile_cache_hit_total(site, "exec").inc()
        _ins.compile_cache_load_seconds().observe(dt)
        return exe, target

    def _store_alias(self, adig: str, digest: str) -> None:
        fp = env_fingerprint()
        try:
            self.disk.store(adig, {"tier": "alias", "site": "alias",
                                   "env": list(fp),
                                   "created": time.time()},
                            digest.encode("ascii"))
        except Exception:  # noqa: BLE001 — index is an optimization
            with self._lock:
                self._stats["write_errors"] += 1

    # ---- tiers --------------------------------------------------------

    def _mem_put(self, digest: str, fn: Any, touch: Optional[str] = None) -> None:
        with self._lock:
            self._mem[digest] = _MemEntry(fn, touch)
            while len(self._mem) > self.mem_entries:
                oldest = min(self._mem.items(),
                             key=lambda kv: kv[1].tick)[0]
                if oldest == digest:
                    break  # never evict what we just inserted
                del self._mem[oldest]
                self._stats["mem_evictions"] += 1
                _ins.compile_cache_evict_total("memory").inc()

    def _load_disk(self, site: str, digest: str):
        """None = miss.  ``(executable, "disk")`` for an exec-tier hit;
        ``(None, "stablehlo")`` tells the caller to compile its own
        lowering (the hit is still counted — the entry verified)."""
        t0 = time.perf_counter()
        got = self.disk.load(digest)
        if got is None:
            return None
        header, payload = got
        if header["tier"] == "exec":
            try:
                exe = _decode_executable(payload)
            except Exception:  # noqa: BLE001 — stale pickle, runtime drift
                # the bytes VERIFIED but this runtime rejected them —
                # that is an incompatibility (fingerprint gap), not
                # corruption.  Count a miss and compile fresh; do NOT
                # quarantine: on a shared cache dir that would let one
                # incompatible host destroy entries that are perfectly
                # valid for their writers.
                with self._lock:
                    self._stats["decode_failures"] += 1
                return None
            dt = time.perf_counter() - t0
            with self._lock:
                self._stats["disk_hits"] += 1
            _ins.compile_cache_hit_total(site, "exec").inc()
            _ins.compile_cache_load_seconds().observe(dt)
            return exe, "disk"
        with self._lock:
            self._stats["stablehlo_hits"] += 1
        _ins.compile_cache_hit_total(site, "stablehlo").inc()
        return None, "stablehlo"

    def _store_disk(self, site: str, key: CacheKey, digest: str,
                    compiled: Any) -> bool:
        """Persist a fresh build; True when an exec-tier entry landed
        (aliases only point at exec entries).  Best-effort: a full disk
        or IO error costs durability, never the request — but it is
        counted (``write_errors``) so a silently-cold cache is
        diagnosable."""
        enc = _encode_executable(compiled, key.program_text)
        if enc is None:
            return False
        tier, payload = enc
        fp = env_fingerprint()
        header = {"tier": tier, "site": site,
                  "env": list(fp),
                  "created": time.time()}
        try:
            self.disk.store(digest, header, payload)
        except Exception:  # noqa: BLE001 — durability is best-effort
            with self._lock:
                self._stats["write_errors"] += 1
            return False
        with self._lock:
            self._stats["writes"] += 1
        evicted, live_bytes = self.disk.evict()
        if evicted:
            _ins.compile_cache_evict_total("disk").inc(evicted)
        _ins.compile_cache_bytes().set(live_bytes)
        return tier == "exec"

    # ---- introspection ------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self._stats)
        if self.disk is not None:
            out["disk_evictions"] = self.disk.evictions
            out["disk_corrupt"] = self.disk.corrupt
            out["bytes_on_disk"] = self.disk.bytes_on_disk()
        out["mem_entries"] = len(self._mem)
        return out


# ---------------------------------------------------------------------------
# the process-wide instance (env-configured, lazily built)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[CompileCache] = None
_DISABLED_SENTINEL = object()
_active_lock = threading.Lock()


def _build_from_env() -> Optional[CompileCache]:
    if _env.get_bool("MXNET_COMPILE_CACHE_DISABLE"):
        return None
    d = _env.get_str("MXNET_COMPILE_CACHE_DIR")
    if not d:
        return None
    return CompileCache(disk_dir=d,
                        cap_bytes=_env.get_int("MXNET_COMPILE_CACHE_BYTES"))


def get_cache() -> Optional[CompileCache]:
    """The env-configured process cache, or None when the persistent
    cache is off (no ``MXNET_COMPILE_CACHE_DIR``, or explicitly
    disabled).  Off is the default: call sites keep their own
    in-process caches either way."""
    global _ACTIVE
    a = _ACTIVE
    if a is None:
        # build OUTSIDE the lock (mxflow MX008: DiskStore creation
        # does directory IO, and every get_cache/reset/enabled call
        # contends on _active_lock — op dispatch holds its own lock
        # while calling in here).  Racing builders produce equivalent
        # instances; the first to publish wins, the loser's instance
        # holds no resources (makedirs is idempotent, no open fds).
        built = _build_from_env()
        with _active_lock:
            if _ACTIVE is None:
                _ACTIVE = built if built is not None \
                    else _DISABLED_SENTINEL
            a = _ACTIVE
    return None if a is _DISABLED_SENTINEL else a


def reset(cache: Optional[CompileCache] = None,
          disabled: bool = False) -> None:
    """Swap the process cache (tests; :mod:`tools.warm_cache`).  With
    no arguments the env knobs are re-read on the next
    :func:`get_cache`."""
    global _ACTIVE
    with _active_lock:
        _ACTIVE = _DISABLED_SENTINEL if disabled else cache


def enabled() -> bool:
    return get_cache() is not None


def get_or_compile(site: str, key, compile_fn: Callable[[], Any],
                   alias: Optional[CacheKey] = None) -> Tuple[Any, str]:
    """Module-level convenience over the process cache.  With the cache
    off this is exactly ``(compile_fn(), "compiled")`` — zero overhead,
    zero behavior change (the production default until a cache dir is
    configured).  ``key`` may be a CacheKey or a lazy thunk; ``alias``
    is the cheap secondary key (see CompileCache.get_or_compile)."""
    cc = get_cache()
    if cc is None:
        if callable(key) and not isinstance(key, CacheKey):
            key = None  # never built: the thunk exists for cache keying only
        return compile_fn(), "compiled"
    return cc.get_or_compile(site, key, compile_fn, alias=alias)


def stats() -> Dict[str, int]:
    """Process-cache stats ({} when off) — what the warm-start tests
    and ``tools/warm_cache.py`` report."""
    cc = get_cache()
    return cc.stats() if cc is not None else {}
