"""mxlint — framework-aware static analysis for mxnet_tpu.

An AST-based lint engine with rules grounded in real bug classes from
this repo's history: silent recompiles in AOT-cached paths (MX001),
host syncs inside the training hot loop (MX002), env knobs that bypass
the central registry (MX003), unguarded module-level shared state
(MX004), donated buffers read after donation (MX005), and op-registry
contract breaks (MX006).

Usage (CLI): ``python tools/mxlint.py mxnet_tpu --baseline
MXLINT_BASELINE.json``; see docs/static_analysis.md for the rule
catalogue, pragma syntax, and baseline workflow.

This subpackage deliberately imports ONLY the standard library so the
CLI can load it without paying the jax import (the full-package lint
must finish in seconds, and tools/mxlint.py loads it standalone).
"""
from .engine import (
    LintEngine, Violation, Rule, RULE_REGISTRY, register_rule,
    load_baseline, diff_baseline, make_baseline,
)
# NOTE `from .rules import ...` (not `from . import rules`): the latter
# routes through a full dotted __import__ that walks from the ROOT
# package — defeating the standalone load and pulling in jax.
from .rules import (  # noqa: F401  — registers the MX00x rules on import
    RecompileHazard, HostSyncInHotPath, UntrackedEnvKnob,
    UnguardedSharedState, DonationMisuse, OpRegistryContract,
)
# mxflow: the interprocedural dataflow engine + MX008–MX012.  NOTE
# `from .dataflow import X` (one level, non-empty fromlist), never
# `from .dataflow.rules import X`: the two-level form makes the import
# system load the intermediate package with an EMPTY fromlist, which
# finishes by fetching the head package `mxnet_tpu` — absent in the
# CLI's standalone (jax-free) load.
from .dataflow import (  # noqa: F401  — registers MX008–MX012
    BlockingUnderLock, TransitiveHostSync, ExceptionPathLeak,
    RetryUnsafeSideEffect, InterproceduralDonation,
)
from .reporters import render_text, render_json, render_sarif
from .drift import instrument_names, chaos_sites, drift_findings
# mxir: the StableHLO program auditor (MX014–MX018) — same one-level
# import rule as .dataflow above.
from .ir import (  # noqa: F401  — registers MX014–MX018
    IrParseError, audit_module, parse_module, estimate_wire_bytes,
    wire_drift, ProgramAudit, render_ir_json, IR_RULE_IDS, FIXTURES,
)
# mxrank: cross-rank collective-schedule verification (MX019–MX020) —
# same one-level import rule as .dataflow above.
from .mxrank import (  # noqa: F401  — registers MX019–MX020
    RankDivergentSchedule, DataDivergentSchedule,
)

__all__ = [
    "LintEngine", "Violation", "Rule", "RULE_REGISTRY", "register_rule",
    "load_baseline", "diff_baseline", "make_baseline",
    "render_text", "render_json", "render_sarif",
    "instrument_names", "chaos_sites", "drift_findings",
    "IrParseError", "audit_module", "parse_module",
    "estimate_wire_bytes", "wire_drift", "ProgramAudit",
    "render_ir_json", "IR_RULE_IDS", "FIXTURES",
    "RankDivergentSchedule", "DataDivergentSchedule",
]
