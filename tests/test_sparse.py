"""Sparse NDArray tests, modeled on the reference's
tests/python/unittest/test_sparse_ndarray.py and test_sparse_operator.py
(numpy/scipy as oracle)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse


def test_row_sparse_creation():
    vals = np.arange(6, dtype=np.float32).reshape(2, 3)
    rsp = sparse.row_sparse_array((vals, [4, 1]), shape=(6, 3))
    assert rsp.stype == "row_sparse"
    assert rsp.shape == (6, 3)
    # indices come back sorted; data follows
    np.testing.assert_array_equal(rsp.indices.asnumpy(), [1, 4])
    np.testing.assert_allclose(rsp.data.asnumpy(), vals[[1, 0]])
    dense = rsp.todense().asnumpy()
    expect = np.zeros((6, 3), np.float32)
    expect[4], expect[1] = vals[0], vals[1]
    np.testing.assert_allclose(dense, expect)


def test_csr_creation_and_asscipy():
    import scipy.sparse as sps

    m = sps.random(8, 5, density=0.4, format="csr", dtype=np.float32,
                   random_state=0)
    csr = sparse.csr_matrix(m)
    assert csr.stype == "csr"
    assert csr.shape == (8, 5)
    np.testing.assert_allclose(csr.todense().asnumpy(), m.toarray())
    back = csr.asscipy()
    np.testing.assert_allclose(back.toarray(), m.toarray())
    # (data, indices, indptr) constructor
    csr2 = sparse.csr_matrix((m.data, m.indices, m.indptr), shape=m.shape)
    np.testing.assert_allclose(csr2.todense().asnumpy(), m.toarray())


def test_cast_storage_round_trip():
    rng = np.random.RandomState(0)
    dense = rng.rand(6, 4).astype(np.float32)
    dense[[1, 3]] = 0
    x = nd.array(dense)
    rsp = nd.cast_storage(x, "row_sparse")
    assert rsp.stype == "row_sparse"
    np.testing.assert_array_equal(rsp.indices.asnumpy(), [0, 2, 4, 5])
    np.testing.assert_allclose(rsp.todense().asnumpy(), dense)
    csr = x.tostype("csr")
    assert csr.stype == "csr"
    np.testing.assert_allclose(csr.todense().asnumpy(), dense)
    assert nd.cast_storage(csr, "default").stype == "default"


def test_sparse_zeros():
    z = sparse.zeros("row_sparse", (4, 3))
    assert z.stype == "row_sparse" and z.indices.shape == (0,)
    zc = sparse.zeros("csr", (4, 3))
    assert zc.stype == "csr"
    assert zc.indptr.shape == (5,)
    np.testing.assert_allclose(zc.todense().asnumpy(), np.zeros((4, 3)))


def test_retain():
    vals = np.ones((3, 2), np.float32)
    rsp = sparse.row_sparse_array((vals, [0, 2, 4]), shape=(6, 2))
    out = sparse.retain(rsp, [2, 4, 5])
    np.testing.assert_array_equal(out.indices.asnumpy(), [2, 4])
    expect = np.zeros((6, 2), np.float32)
    expect[2] = expect[4] = 1
    np.testing.assert_allclose(out.todense().asnumpy(), expect)


def test_sparse_elemwise_keeps_stype():
    a = sparse.row_sparse_array((np.ones((2, 3), np.float32), [0, 2]),
                                shape=(5, 3))
    b = sparse.row_sparse_array((2 * np.ones((2, 3), np.float32), [2, 4]),
                                shape=(5, 3))
    out = sparse.add(a, b)
    assert out.stype == "row_sparse"
    np.testing.assert_array_equal(out.indices.asnumpy(), [0, 2, 4])
    np.testing.assert_allclose(out.todense().asnumpy(),
                               a.todense().asnumpy() + b.todense().asnumpy())


def test_sparse_dot():
    import scipy.sparse as sps

    rng = np.random.RandomState(0)
    m = sps.random(6, 4, density=0.5, format="csr", dtype=np.float32,
                   random_state=1)
    rhs = rng.rand(4, 3).astype(np.float32)
    csr = sparse.csr_matrix(m)
    out = sparse.dot(csr, nd.array(rhs))
    np.testing.assert_allclose(out.asnumpy(), m.toarray() @ rhs, rtol=1e-5)
    rhs2 = rng.rand(6, 3).astype(np.float32)
    out_t = sparse.dot(csr, nd.array(rhs2), transpose_a=True)
    np.testing.assert_allclose(out_t.asnumpy(), m.toarray().T @ rhs2,
                               rtol=1e-5)


def test_sparse_save_load(tmp_path):
    fname = str(tmp_path / "sparse.params")
    vals = np.arange(8, dtype=np.float32).reshape(2, 4)
    rsp = sparse.row_sparse_array((vals, [1, 3]), shape=(5, 4))
    import scipy.sparse as sps

    m = sps.random(4, 6, density=0.4, format="csr", dtype=np.float32,
                   random_state=0)
    csr = sparse.csr_matrix(m)
    dense = nd.array(np.arange(3, dtype=np.float32))
    nd.save(fname, {"rsp": rsp, "csr": csr, "dense": dense})
    loaded = nd.load(fname)
    assert loaded["rsp"].stype == "row_sparse"
    np.testing.assert_array_equal(loaded["rsp"].indices.asnumpy(), [1, 3])
    np.testing.assert_allclose(loaded["rsp"].todense().asnumpy(),
                               rsp.todense().asnumpy())
    assert loaded["csr"].stype == "csr"
    np.testing.assert_allclose(loaded["csr"].todense().asnumpy(),
                               m.toarray())
    assert loaded["dense"].stype == "default"
    np.testing.assert_allclose(loaded["dense"].asnumpy(), [0, 1, 2])


def test_kvstore_row_sparse_pull():
    kv = mx.kv.create("local")
    w = np.random.RandomState(0).rand(8, 3).astype(np.float32)
    kv.init("emb", nd.array(w))
    out = sparse.zeros("row_sparse", (8, 3))
    kv.row_sparse_pull("emb", out=out,
                       row_ids=nd.array([5, 1, 5], dtype="int32"))
    np.testing.assert_array_equal(out.indices.asnumpy(), [1, 5])
    expect = np.zeros((8, 3), np.float32)
    expect[[1, 5]] = w[[1, 5]]
    np.testing.assert_allclose(out.todense().asnumpy(), expect, rtol=1e-6)


def test_kvstore_push_row_sparse_reduce():
    kv = mx.kv.create("local")
    kv.init("w", nd.zeros((6, 2)))
    g1 = sparse.row_sparse_array((np.ones((1, 2), np.float32), [1]),
                                 shape=(6, 2))
    g2 = sparse.row_sparse_array((np.ones((2, 2), np.float32), [1, 4]),
                                 shape=(6, 2))
    kv.push("w", [g1, g2])
    out = nd.zeros((6, 2))
    kv.pull("w", out=out)
    expect = np.zeros((6, 2), np.float32)
    expect[1] = 2
    expect[4] = 1
    np.testing.assert_allclose(out.asnumpy(), expect)


@pytest.mark.parametrize("momentum", [0.0, 0.9])
def test_sgd_lazy_update_touches_only_grad_rows(momentum):
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=momentum, wd=0.01,
                           lazy_update=True)
    w0 = np.random.RandomState(0).rand(6, 3).astype(np.float32)
    weight = nd.array(w0)
    state = opt.create_state(0, weight)
    gvals = np.ones((2, 3), np.float32)
    grad = sparse.row_sparse_array((gvals, [1, 4]), shape=(6, 3))
    opt.update(0, weight, grad, state)
    w1 = weight.asnumpy()
    untouched = [0, 2, 3, 5]
    np.testing.assert_allclose(w1[untouched], w0[untouched])
    # touched rows follow dense SGD math on those rows
    g = gvals + 0.01 * w0[[1, 4]]
    np.testing.assert_allclose(w1[[1, 4]], w0[[1, 4]] - 0.1 * g, rtol=1e-5)
    if momentum:
        # second step uses accumulated momentum on touched rows
        opt.update(0, weight, grad, state)
        w2 = weight.asnumpy()
        np.testing.assert_allclose(w2[untouched], w0[untouched])
        g2 = gvals + 0.01 * w1[[1, 4]]
        m = -0.1 * g  # state after step 1
        m2 = momentum * m - 0.1 * g2
        np.testing.assert_allclose(w2[[1, 4]], w1[[1, 4]] + m2, rtol=1e-5)


def test_sgd_std_update_with_sparse_grad():
    """lazy_update=False densifies: wd decays every row."""
    opt = mx.optimizer.SGD(learning_rate=0.1, wd=0.1, lazy_update=False)
    w0 = np.ones((4, 2), np.float32)
    weight = nd.array(w0)
    grad = sparse.row_sparse_array((np.ones((1, 2), np.float32), [2]),
                                   shape=(4, 2))
    opt.update(0, weight, grad, None)
    w1 = weight.asnumpy()
    # untouched rows still decay by lr*wd*w
    np.testing.assert_allclose(w1[0], w0[0] - 0.1 * 0.1 * w0[0], rtol=1e-5)
    np.testing.assert_allclose(w1[2], w0[2] - 0.1 * (1 + 0.1 * w0[2]),
                               rtol=1e-5)


def test_sparse_setitem_and_copy():
    rsp = sparse.zeros("row_sparse", (4, 2))
    src = sparse.row_sparse_array((np.ones((1, 2), np.float32), [3]),
                                  shape=(4, 2))
    rsp[:] = src
    np.testing.assert_array_equal(rsp.indices.asnumpy(), [3])
    cp = rsp.copy()
    assert cp.stype == "row_sparse"
    with pytest.raises(mx.base.MXNetError):
        rsp[1] = 5.0


def test_row_sparse_pull_from_sparse_store_and_multi_key():
    """Regression: sparse-valued store + per-key row_ids pairing."""
    kv = mx.kv.create("local")
    kv.init("a", nd.array(np.arange(12, dtype=np.float32).reshape(6, 2)))
    kv.init("b", nd.array(-np.arange(12, dtype=np.float32).reshape(6, 2)))
    # store a sparse value under 'c' via push without updater
    kv.init("c", nd.zeros((6, 2)))
    kv.push("c", sparse.row_sparse_array(
        (np.ones((1, 2), np.float32), [3]), shape=(6, 2)))
    oa, ob = nd.zeros((6, 2)), nd.zeros((6, 2))
    kv.row_sparse_pull(["a", "b"], out=[oa, ob],
                       row_ids=[nd.array([1], dtype="int32"),
                                nd.array([4], dtype="int32")])
    assert oa.asnumpy()[1].sum() != 0 and oa.asnumpy()[4].sum() == 0
    assert ob.asnumpy()[4].sum() != 0 and ob.asnumpy()[1].sum() == 0
    oc = sparse.zeros("row_sparse", (6, 2))
    kv.row_sparse_pull("c", out=oc, row_ids=nd.array([3], dtype="int32"))
    np.testing.assert_allclose(oc.todense().asnumpy()[3], [1, 1])


def test_pull_sparse_out_raises():
    kv = mx.kv.create("local")
    kv.init("w", nd.ones((4, 2)))
    with pytest.raises(mx.base.MXNetError):
        kv.pull("w", out=sparse.zeros("row_sparse", (4, 2)))


def test_nag_and_adam_accept_sparse_grad():
    for name in ("nag", "adam"):
        opt = mx.optimizer.create(name, learning_rate=0.1)
        if name == "nag":
            opt.momentum = 0.9
        w = nd.ones((4, 2))
        state = opt.create_state(0, w)
        g = sparse.row_sparse_array((np.ones((1, 2), np.float32), [2]),
                                    shape=(4, 2))
        opt.update(0, w, g, state)
        assert np.isfinite(w.asnumpy()).all()


def test_cast_storage_bf16_csr():
    x = nd.array(np.eye(3, dtype=np.float32)).astype("bfloat16")
    csr = x.tostype("csr")
    assert csr.stype == "csr"
    np.testing.assert_allclose(
        csr.todense().asnumpy().astype(np.float32), np.eye(3))
