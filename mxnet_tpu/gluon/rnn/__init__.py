"""Gluon recurrent layers (ref: python/mxnet/gluon/rnn/__init__.py)."""
from .rnn_cell import (RecurrentCell, RNNCell, LSTMCell, GRUCell,
                       SequentialRNNCell, BidirectionalCell, DropoutCell,
                       ZoneoutCell, ResidualCell, HybridSequentialRNNCell)
from .rnn_layer import RNN, LSTM, GRU

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "HybridSequentialRNNCell",
           "BidirectionalCell", "DropoutCell", "ZoneoutCell", "ResidualCell",
           "RNN", "LSTM", "GRU"]
