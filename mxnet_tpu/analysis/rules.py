"""The MX00x rule set.  Each rule is grounded in a bug class this repo
actually shipped (see docs/static_analysis.md for the catalogue with
the historical example behind every rule).

All rules are heuristic AST passes: they favor precision over recall
(a lint gate that cries wolf gets pragma'd into silence), and every
false positive has an escape hatch — ``# mxlint: disable=MXnnn`` on
the flagged line.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .engine import FileContext, Rule, Violation, register_rule

__all__ = [
    "RecompileHazard", "HostSyncInHotPath", "UntrackedEnvKnob",
    "UnguardedSharedState", "DonationMisuse", "OpRegistryContract",
    "SwallowedException",
]


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

def _attr_chain(node: ast.AST) -> str:
    """Dotted name of a Name/Attribute chain ('jax.jit'), '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _terminal_name(node: ast.AST) -> str:
    """Last component of a Name/Attribute chain ('self._jit_lock' ->
    '_jit_lock')."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


_JIT_NAMES = re.compile(r"(^|\.)(jit|pjit|pmap)$")


def _is_jit_callable(node: ast.AST) -> bool:
    """Does this expression name a jit-like transform (jax.jit, jit,
    pjit, jax.pmap, functools.partial(jax.jit, ...))?"""
    chain = _attr_chain(node)
    if chain and _JIT_NAMES.search(chain):
        return True
    if isinstance(node, ast.Call):
        # partial(jax.jit, ...) / jax.jit(fn, static_argnums=...) used
        # as a decorator factory
        if _attr_chain(node.func).endswith("partial") and node.args:
            return _is_jit_callable(node.args[0])
        return _is_jit_callable(node.func)
    return False


def _walk_excluding_nested_classes(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body including nested functions (they trace too)
    but not nested classes."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _walk_same_scope(node: ast.AST) -> Iterable[ast.AST]:
    """Walk a statement WITHOUT descending into nested function/class
    scopes — they are analyzed as scopes of their own (and re-walking
    them from every enclosing level is quadratic)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


# ---------------------------------------------------------------------------
# MX001 — recompile hazard inside jit/AOT contexts
# ---------------------------------------------------------------------------

_STATIC_ATTRS = {"shape", "ndim", "dtype"}
_STATIC_CALLS = {"len", "range", "isinstance", "str", "repr", "type"}


def _is_static_expr(node: ast.AST) -> bool:
    """Conservatively true when the expression is trace-static (built
    from shapes, ranks, dtypes, len(), constants): coercing THOSE to a
    Python scalar is fine inside a trace."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_ATTRS
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.Call):
        fn = _terminal_name(node.func)
        return fn in _STATIC_CALLS
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand)
    return False


@register_rule
class RecompileHazard(Rule):
    """MX001: Python scalar coercion of a traced value inside a
    ``jax.jit``/AOT-compiled function.  ``int(x)``, ``float(x)``,
    ``bool(x)``, ``x.item()``, ``x.tolist()``, ``np.asarray(x)`` on a
    traced value either raises a ``TracerError`` or — worse, with
    ``static_argnums``/shape-dependent code — silently retraces and
    recompiles per value, destroying the AOT no-recompile guarantee the
    fused-step path is built on."""

    id = "MX001"
    cacheable = "file"
    name = "recompile-hazard"
    description = ("Host scalar coercion or materialization inside a "
                   "jit-compiled function (silent recompile / trace "
                   "error).")

    _COERCIONS = {"int", "float", "bool", "complex"}
    _HOST_METHODS = {"item", "tolist", "asnumpy"}
    _NP_FUNCS = {"asarray", "array"}
    _NP_MODULES = {"np", "numpy", "onp"}

    def _jit_functions(self, ctx: FileContext) -> List[ast.AST]:
        jit_fns: List[ast.AST] = []
        by_name: Dict[str, ast.AST] = {}
        wrapped: Set[str] = set()
        for node in ctx.functions:
            by_name.setdefault(node.name, node)
            if any(_is_jit_callable(d) for d in node.decorator_list):
                jit_fns.append(node)
        for node in ctx.calls:
            if _is_jit_callable(node.func):
                # jax.jit(fn) / jax.jit(fn, donate_argnums=...) on a
                # locally defined function or lambda
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name):
                        wrapped.add(arg.id)
                    elif isinstance(arg, ast.Lambda):
                        jit_fns.append(arg)
        jit_fns.extend(fn for name, fn in by_name.items()
                       if name in wrapped
                       and not any(_is_jit_callable(d) for d in
                                   getattr(fn, "decorator_list", ())))
        return jit_fns

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        seen: Set[int] = set()
        for fn in self._jit_functions(ctx):
            for node in _walk_excluding_nested_classes(fn):
                if id(node) in seen or not isinstance(node, ast.Call):
                    continue
                v = self._check_call(ctx, node)
                if v is not None:
                    seen.add(id(node))
                    yield v

    def _check_call(self, ctx: FileContext,
                    node: ast.Call) -> Optional[Violation]:
        fname = _terminal_name(node.func)
        if isinstance(node.func, ast.Name) and fname in self._COERCIONS:
            if len(node.args) == 1 and not _is_static_expr(node.args[0]):
                return ctx.violation(
                    self.id, node,
                    f"{fname}() on a value inside a jit-compiled "
                    "function forces a concrete host scalar — "
                    "TracerError at best, silent per-value recompile "
                    "at worst. Hoist it out of the traced function or "
                    "derive it from .shape/.ndim.")
        if isinstance(node.func, ast.Attribute):
            if fname in self._HOST_METHODS and not node.args:
                return ctx.violation(
                    self.id, node,
                    f".{fname}() inside a jit-compiled function "
                    "materializes the value on the host — it cannot "
                    "trace, and in AOT-cached paths it forces a "
                    "recompile per distinct value.")
            if fname in self._NP_FUNCS and \
                    _terminal_name(node.func.value) in self._NP_MODULES:
                return ctx.violation(
                    self.id, node,
                    f"numpy.{fname}() inside a jit-compiled function "
                    "pulls the traced value to the host; use jnp "
                    "inside traces.")
        return None


# ---------------------------------------------------------------------------
# MX002 — host sync in the training hot path
# ---------------------------------------------------------------------------

@register_rule
class HostSyncInHotPath(Rule):
    """MX002: ``.asnumpy()`` / ``np.asarray`` on NDArrays inside
    ``autograd.record()`` blocks or the Trainer/Updater/KVStore step
    chain.  Each such call is a device→host round-trip that stalls the
    async dispatch pipeline — the exact class of bug that erases the
    fused-step win (arxiv 2004.13336).

    Direct syncs only: a sync reached *through* a call — any number of
    levels deep, across modules — is MX009's job (the mxflow dataflow
    engine follows the whole call graph; the one-level special case
    this rule used to carry is gone)."""

    id = "MX002"
    cacheable = "file"
    name = "hot-path-host-sync"
    description = ("Device->host synchronization (.asnumpy()/np.asarray/"
                   ".item()/.wait_to_read()) written directly inside "
                   "autograd.record() or the Trainer.step call chain "
                   "(transitive reach is MX009).")

    _SYNC_METHODS = {"asnumpy", "item", "wait_to_read"}
    _NP_FUNCS = {"asarray", "array"}
    _NP_MODULES = {"np", "numpy", "onp"}
    # the step call chain: methods with these names on these classes
    _HOT_CLASSES = re.compile(r"(Trainer|Updater|KVStore)")
    _HOT_METHODS = {"step", "update", "_update", "update_all", "__call__",
                    "allreduce_grads", "_allreduce_grads",
                    "_allreduce_grads_fused", "_update_fused",
                    "push", "pull", "pushpull", "pushpull_fused"}

    def _record_blocks(self, ctx: FileContext) -> Iterable[ast.With]:
        for node in ctx.withs:
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call) and \
                        _terminal_name(expr.func) == "record":
                    yield node
                    break

    def _hot_methods(self, ctx: FileContext
                     ) -> Iterable[Tuple[ast.FunctionDef, ast.ClassDef]]:
        for node in ctx.classes:
            if self._HOT_CLASSES.search(node.name):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) and \
                            item.name in self._HOT_METHODS:
                        yield item, node

    def _direct_sync(self, node: ast.Call) -> Optional[str]:
        """A short description when `node` is itself a host sync."""
        fname = _terminal_name(node.func)
        if isinstance(node.func, ast.Attribute):
            if fname in self._SYNC_METHODS and not node.args:
                return f".{fname}()"
            if fname in self._NP_FUNCS and \
                    _terminal_name(node.func.value) in self._NP_MODULES:
                return f"numpy.{fname}()"
        return None

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        hot = list(self._hot_methods(ctx))
        seen: Set[int] = set()
        scopes = [(b, "inside autograd.record()")
                  for b in self._record_blocks(ctx)] + \
                 [(m, f"in the {m.name}() step chain") for m, _ in hot]
        for scope, where in scopes:
            for node in ast.walk(scope):
                if id(node) in seen or not isinstance(node, ast.Call):
                    continue
                desc = self._direct_sync(node)
                if not desc:
                    continue
                if desc.startswith("numpy."):
                    msg = (f"{desc[:-2]}() {where} synchronously "
                           "materializes device data on the host")
                else:
                    msg = (f"{desc} {where} blocks on a "
                           "device->host transfer, stalling the "
                           "async dispatch pipeline")
                seen.add(id(node))
                yield ctx.violation(
                    self.id, node,
                    msg + "; move it outside the hot loop or use "
                    "an async metric hook.")


# ---------------------------------------------------------------------------
# MX003 — untracked env knob
# ---------------------------------------------------------------------------

@register_rule
class UntrackedEnvKnob(Rule):
    """MX003: a ``MXNET_*`` env var read that bypasses the central knob
    registry (``mxnet_tpu.util.env``).  Untracked reads drift out of
    docs/env_vars.md and a typo'd name silently returns its default
    forever — the registry raises on undeclared names instead."""

    id = "MX003"
    cacheable = "file"
    name = "untracked-env-knob"
    description = ("os.environ/get_env read of a MXNET_* name outside "
                   "the mxnet_tpu.util.env knob registry.")

    _RAW_READERS = {"getenv"}          # os.getenv
    _ENVIRON_METHODS = {"get", "setdefault", "pop"}
    _LEGACY = {"get_env"}              # mxnet_tpu.base.get_env

    def _literal_knob(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value.startswith("MXNET_"):
            return node.value
        return None

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        # the registry module itself is the one legitimate home of raw
        # MXNET_* reads
        if ctx.relpath.replace("\\", "/").endswith("mxnet_tpu/util/env.py"):
            return
        candidates: List[Tuple[ast.AST, Optional[str]]] = []
        for node in ctx.calls:
            fname = _terminal_name(node.func)
            chain = _attr_chain(node.func)
            if node.args:
                knob = self._literal_knob(node.args[0])
                if knob and (
                        fname in self._RAW_READERS
                        or (fname in self._ENVIRON_METHODS
                            and chain.endswith("environ." + fname))
                        or fname in self._LEGACY):
                    candidates.append((node, knob))
        for node in ctx.subscripts:
            if _attr_chain(node.value).endswith("environ") and \
                    not isinstance(node.slice, ast.Slice):
                candidates.append((node, self._literal_knob(node.slice)))
        for node, name in candidates:
            if name:
                yield ctx.violation(
                    self.id, node,
                    f"{name} read bypasses the knob registry; use "
                    "mxnet_tpu.util.env.get_* so the knob is typed, "
                    "documented, and typo-proof.")


# ---------------------------------------------------------------------------
# MX004 — unguarded module-level shared state
# ---------------------------------------------------------------------------

_LOCKISH = re.compile(r"lock|mutex", re.IGNORECASE)
_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "clear", "remove", "discard"}
_CONTAINER_CALLS = {"dict", "list", "set", "defaultdict", "OrderedDict",
                    "deque", "Counter"}


@register_rule
class UnguardedSharedState(Rule):
    """MX004: mutation of a module-level dict/list/set from a function
    body with no enclosing lock acquisition.  Module caches are shared
    by serving threads, DataLoader workers, and the training loop; the
    ``_jit_lock`` double-checked pattern in ``ops/registry.py`` is the
    house style — follow it or justify the race in the baseline."""

    id = "MX004"
    cacheable = "file"
    name = "unguarded-shared-state"
    description = ("Write to a module-level mutable container from a "
                   "function body with no enclosing `with <lock>:`.")

    def _module_containers(self, ctx: FileContext) -> Set[str]:
        names: Set[str] = set()
        for node in ctx.tree.body:
            targets: List[ast.expr] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None:
                continue
            is_container = isinstance(
                value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                        ast.ListComp, ast.SetComp)) or (
                isinstance(value, ast.Call)
                and _terminal_name(value.func) in _CONTAINER_CALLS)
            if is_container:
                names.update(t.id for t in targets
                             if isinstance(t, ast.Name))
        return names

    def _check_function(self, ctx: FileContext, fn: ast.AST,
                        tracked: Set[str]) -> Iterable[Violation]:

        def visit(node: ast.AST, locked: bool) -> Iterable[Violation]:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                # nested function: fresh walk happens from the top level
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                holds = locked or any(
                    _LOCKISH.search(_terminal_name(
                        i.context_expr.func
                        if isinstance(i.context_expr, ast.Call)
                        else i.context_expr) or "")
                    for i in node.items)
                for child in node.body:
                    yield from visit(child, holds)
                return
            yield from self._check_node(ctx, node, tracked, locked)
            # With nodes returned above, so this descent never re-enters
            # a lock scope
            for child in ast.iter_child_nodes(node):
                yield from visit(child, locked)

        for stmt in fn.body:
            yield from visit(stmt, False)

    def _check_node(self, ctx: FileContext, node: ast.AST,
                    tracked: Set[str], locked: bool
                    ) -> Iterable[Violation]:
        if locked:
            return
        name = None
        how = None
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in tracked:
                    name, how = t.value.id, f"{t.value.id}[...] ="
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in tracked:
                    name, how = t.value.id, f"del {t.value.id}[...]"
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in tracked and \
                node.func.attr in _MUTATORS:
            name, how = node.func.value.id, \
                f"{node.func.value.id}.{node.func.attr}(...)"
        if name:
            yield ctx.violation(
                self.id, node,
                f"`{how}` mutates module-level `{name}` with no "
                "enclosing lock; serving/dataloader threads share this "
                "module — guard it with the double-checked `with "
                "<lock>:` pattern (ops/registry.py::jitted).")

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        tracked = self._module_containers(ctx)
        if not tracked:
            return
        for node in ctx.functions:
            yield from self._check_function(ctx, node, tracked)


# ---------------------------------------------------------------------------
# MX005 — donation misuse
# ---------------------------------------------------------------------------

@register_rule
class DonationMisuse(Rule):
    """MX005: an argument donated via ``donate_argnums`` is read again
    after the call in the same scope.  XLA invalidates donated buffers;
    the read returns garbage on TPU (and 'works' on CPU where donation
    is a no-op — the worst kind of portability bug)."""

    id = "MX005"
    cacheable = "file"
    name = "donation-misuse"
    description = ("Variable passed at a donated argument position is "
                   "read after the donating call in the same scope.")

    @staticmethod
    def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
        for kw in call.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                v = kw.value
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    out = []
                    for e in v.elts:
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, int):
                            out.append(e.value)
                    return tuple(out)
                return ()  # dynamic donate spec: cannot track
        return None

    def _scan_scope(self, ctx: FileContext, body: Sequence[ast.stmt]
                    ) -> Iterable[Violation]:
        # jitted-callable name -> donated positions
        donating: Dict[str, Tuple[int, ...]] = {}
        # donated variable name -> (stmt index of donating call, lineno)
        donated_at: Dict[str, Tuple[int, int]] = {}

        def record_call(call: ast.Call, idx: int) -> None:
            """If `call` donates buffers, mark plain-Name args at the
            donated positions."""
            positions: Optional[Tuple[int, ...]] = None
            f = call.func
            if isinstance(f, ast.Name) and f.id in donating:
                positions = donating[f.id]
            elif isinstance(f, ast.Call):
                positions = self._donated_positions(f) \
                    if _is_jit_callable(f.func) else None
            if not positions:
                return
            for pos in positions:
                if pos < len(call.args):
                    arg = call.args[pos]
                    if isinstance(arg, ast.Name):
                        donated_at.setdefault(arg.id, (idx, call.lineno))

        # statement-index semantics: reads flag only in statements
        # STRICTLY AFTER the donating one (a donating statement's own
        # argument list is a safe read), and any Store in or after the
        # donating statement ends the lifetime — so the canonical
        # rebind idiom `w = f(w, g)` never false-positives.
        for idx, stmt in enumerate(body):
            # 1) reads of names donated in an earlier statement
            for node in _walk_same_scope(stmt):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in donated_at and \
                        donated_at[node.id][0] < idx:
                    _, line = donated_at.pop(node.id)
                    yield ctx.violation(
                        self.id, node,
                        f"`{node.id}` was donated to the compiled call "
                        f"on line {line}; its buffer is invalidated — "
                        "reading it here returns garbage on TPU. Use "
                        "the call's result instead.")
            # 2) f = jax.jit(fn, donate_argnums=...) [.lower().compile()]
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call) and \
                    len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name):
                src = stmt.value
                # unwrap .lower(...).compile() AOT chains
                inner = src
                while isinstance(inner, ast.Call) and \
                        isinstance(inner.func, ast.Attribute):
                    inner = inner.func.value
                for cand in (src, inner):
                    if isinstance(cand, ast.Call) and \
                            _is_jit_callable(cand.func):
                        pos = self._donated_positions(cand)
                        if pos:
                            donating[stmt.targets[0].id] = pos
            # 3) any call in this statement that donates
            for node in _walk_same_scope(stmt):
                if isinstance(node, ast.Call):
                    record_call(node, idx)
            # 4) a Store rebinding a donated name ends its lifetime
            #    (including a same-statement rebind, `w = f(w)`)
            for node in _walk_same_scope(stmt):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Store) and \
                        node.id in donated_at:
                    del donated_at[node.id]

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ctx.functions:
            yield from self._scan_scope(ctx, node.body)
        yield from self._scan_scope(ctx, ctx.tree.body)


# ---------------------------------------------------------------------------
# MX007 — swallowed exception in a hot path
# ---------------------------------------------------------------------------

#: Modules whose call chains ARE the hot paths (Trainer step, KVStore
#: sync, the serving request path, worker pools, the resilience layer
#: itself) — a swallowed error here becomes a hang, a short epoch, or a
#: silently-wrong gradient instead of a diagnosable failure.
_HOT_PATHS = re.compile(
    r"mxnet_tpu/(kvstore[^/]*|gluon/trainer|gluon/data/dataloader|"
    r"optimizer/[^/]+|parallel/(dist|checkpoint)|serving/[^/]+|"
    r"resilience/[^/]+)\.py$")

#: Class names on the same chains, for files outside the module list
#: (and for fixtures).
_HOT_CLASS = re.compile(
    r"(Trainer|Updater|KVStore|Server|Batcher|Repository|ModelEntry|"
    r"DataLoader|Checkpoint|Breaker)")


@register_rule
class SwallowedException(Rule):
    """MX007: a bare ``except:`` / ``except Exception:`` /
    ``except BaseException:`` whose body only ``pass``\\ es (or
    ``continue``\\ s / ``...``) inside a first-party hot path.  Broad
    catch-and-drop turned real faults into the bug classes this PR
    series keeps paying for: a dead DataLoader worker became a silent
    short epoch, a failed collective became a deadlocked peer group.
    Narrow catches (``except ValueError: pass``) are the legitimate
    EAFP idiom and are not flagged; a broad handler that logs,
    re-raises, cleans up, or returns a value is fine too — only
    catch-everything-do-nothing is the bug."""

    id = "MX007"
    cacheable = "file"
    name = "swallowed-exception"
    description = ("Bare except/except Exception with a pass-only body "
                   "in Trainer/KVStore/serving/dataloader/resilience "
                   "hot paths — errors must propagate, be transformed, "
                   "or be loudly recorded.")

    _BROAD = {"Exception", "BaseException"}

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True  # bare except:
        if isinstance(t, ast.Tuple):
            return any(_terminal_name(e) in self._BROAD for e in t.elts)
        return _terminal_name(t) in self._BROAD

    @staticmethod
    def _swallows(handler: ast.ExceptHandler) -> bool:
        for stmt in handler.body:
            if isinstance(stmt, (ast.Pass, ast.Continue)):
                continue
            if isinstance(stmt, ast.Expr) and \
                    isinstance(stmt.value, ast.Constant):
                continue  # docstring / `...`
            return False
        return True

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        hot_file = bool(_HOT_PATHS.search(
            ctx.relpath.replace("\\", "/")))
        hot_spans: List[Tuple[int, int]] = []
        if not hot_file:
            for node in ctx.classes:
                if _HOT_CLASS.search(node.name):
                    end = getattr(node, "end_lineno", node.lineno)
                    hot_spans.append((node.lineno, end))
            if not hot_spans:
                return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not hot_file and not any(
                    lo <= node.lineno <= hi for lo, hi in hot_spans):
                continue
            if self._is_broad(node) and self._swallows(node):
                what = "bare except:" if node.type is None else \
                    f"except {_terminal_name(node.type) or 'Exception'}:"
                yield ctx.violation(
                    self.id, node,
                    f"`{what}` with a pass-only body swallows every "
                    "error on a hot path — a dead worker or failed "
                    "collective becomes a silent hang or wrong result. "
                    "Catch the specific exception, or handle/log/"
                    "re-raise (baseline with a justification if the "
                    "swallow is truly intended).")


# ---------------------------------------------------------------------------
# MX006 — op-registry contract
# ---------------------------------------------------------------------------

@register_rule
class OpRegistryContract(Rule):
    """MX006: op-registry hygiene — duplicate ``register_op`` names
    (the runtime registry raises, but only when both modules happen to
    import) and registered ops with no docstring (`Operator.param_doc`
    renders the attr table, but the semantic one-liner must come from
    the kernel author)."""

    id = "MX006"
    name = "op-registry-contract"
    description = ("Duplicate register_op name/alias, or a registered "
                   "op missing a docstring.")
    cacheable = "contrib"

    def __init__(self) -> None:
        #: name -> (first path, line); duplicates reported at 2nd site
        self._names: Dict[str, Tuple[str, int]] = {}
        self._dups: List[Violation] = []

    @staticmethod
    def _register_calls(node: ast.AST) -> Iterable[ast.Call]:
        for dec in getattr(node, "decorator_list", ()):
            if isinstance(dec, ast.Call) and \
                    _terminal_name(dec.func) == "register_op":
                yield dec

    def contribution(self, ctx: FileContext) -> dict:
        """This file's pure share of the cross-file state: every
        ``register_op`` name in order (with the site needed to rebuild
        a duplicate finding against ANY prior file), plus the per-file
        docstring findings — both independent of other files, so an
        unchanged file replays from cache while dup detection still
        runs fresh across the whole walk in :meth:`absorb`."""
        regs: List[dict] = []
        doc_violations: List[dict] = []
        for node in ctx.functions:
            for call in self._register_calls(node):
                names: List[str] = []
                if call.args and isinstance(call.args[0], ast.Constant) \
                        and isinstance(call.args[0].value, str):
                    names.append(call.args[0].value)
                for kw in call.keywords:
                    if kw.arg == "aliases" and isinstance(
                            kw.value, (ast.Tuple, ast.List)):
                        names.extend(
                            e.value for e in kw.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str))
                if names:
                    line = call.lineno
                    src = ctx.lines[line - 1].strip() \
                        if line <= len(ctx.lines) else ""
                    regs.append({
                        "names": names, "line": line,
                        "col": call.col_offset,
                        "symbol": ctx.symbol_at(line), "src": src,
                        "suppressed": ctx.suppressed(self.id, line)})
                if not ast.get_docstring(node):
                    v = ctx.violation(
                        self.id, node,
                        f"registered op {node.name!r} has no docstring; "
                        "the op catalogue renders it — state the "
                        "semantic contract in one line.")
                    if not ctx.suppressed(self.id, v.line):
                        doc_violations.append({
                            "rule": v.rule, "path": v.path,
                            "line": v.line, "col": v.col,
                            "message": v.message, "symbol": v.symbol,
                            "src": v.src})
        return {"regs": regs, "violations": doc_violations}

    def absorb(self, contrib: dict, relpath: str) -> Iterable[Violation]:
        for reg in contrib["regs"]:
            for name in reg["names"]:
                prev = self._names.get(name)
                if prev is not None and not reg["suppressed"]:
                    self._dups.append(Violation(
                        rule=self.id, path=relpath, line=reg["line"],
                        col=reg["col"],
                        message=(
                            f"op name {name!r} already registered at "
                            f"{prev[0]}:{prev[1]} — the runtime "
                            "registry will raise when both modules "
                            "import."),
                        symbol=reg["symbol"], src=reg["src"]))
                else:
                    self._names.setdefault(name, (relpath, reg["line"]))
        return [Violation(**d) for d in contrib["violations"]]

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        return self.absorb(self.contribution(ctx), ctx.relpath)

    def finalize(self) -> Iterable[Violation]:
        return self._dups


# ---------------------------------------------------------------------------
# MX013 — per-replica dispatch in step-chain code
# ---------------------------------------------------------------------------

@register_rule
class PerReplicaDispatch(Rule):
    """MX013: pmap-style per-replica dispatch in Trainer/Updater/KVStore
    step-chain code — the pattern the unified SPMD spine (ISSUE 9,
    optimizer/spmd.py) exists to replace.  Two shapes:

      * a loop in a step-chain method that issues one dispatch per
        replica/key (``update_all``/``pushpull``/``push``/``pull``/
        ``device_put``/an ``_updaters[r](...)`` call): N kernel launches
        where one mesh program would do;
      * ``jax.device_put(x, <device>)`` with a raw device instead of a
        sharding: data placed outside the mesh layout cannot
        participate in GSPMD collective insertion.

    Surviving legacy sites (the eager fallback loops, the classic
    bucket reduce) are baselined with justifications; NEW step-chain
    code must land on the SPMD spine."""

    id = "MX013"
    cacheable = "file"
    name = "per-replica-dispatch"
    description = ("Per-replica dispatch loop, or device_put without a "
                   "sharding, in Trainer/Updater/KVStore step-chain "
                   "code — new code belongs on the one-program SPMD "
                   "spine (optimizer/spmd.py).")

    _HOT_CLASSES = re.compile(r"(Trainer|Updater|KVStore)")
    _HOT_METHODS = {"step", "update", "_update", "update_all",
                    "update_all_mesh", "_step_spmd", "__call__",
                    "allreduce_grads", "_allreduce_grads",
                    "_allreduce_grads_fused", "_update_fused",
                    "push", "pull", "pushpull", "pushpull_fused",
                    "_bucket_allreduce", "_bucket_allreduce_spmd",
                    "_reduce", "_dcn_allreduce"}
    _DISPATCH = {"update_all", "pushpull", "push", "pull", "device_put"}

    def _hot_methods(self, ctx: FileContext):
        for node in ctx.classes:
            if self._HOT_CLASSES.search(node.name):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) and \
                            item.name in self._HOT_METHODS:
                        yield item

    @staticmethod
    def _is_updater_subscript_call(call: ast.Call) -> bool:
        """self._updaters[r](...) — the eager per-replica dispatch."""
        f = call.func
        return isinstance(f, ast.Subscript) and \
            _attr_chain(f.value).endswith("_updaters")

    def _dispatch_desc(self, call: ast.Call) -> Optional[str]:
        fname = _terminal_name(call.func)
        if fname in self._DISPATCH:
            return f"{fname}()"
        if self._is_updater_subscript_call(call):
            return "_updaters[r](...)"
        return None

    @staticmethod
    def _sharding_expr(node: ast.AST) -> bool:
        """Heuristic: the expression produces a sharding (a call to or
        attribute of something sharding/spec-named)."""
        if isinstance(node, ast.Call):
            name = _terminal_name(node.func)
            return "shard" in name.lower() or "spec" in name.lower()
        chain = _attr_chain(node) if isinstance(
            node, (ast.Attribute, ast.Name)) else ""
        return "shard" in chain.lower() or "spec" in chain.lower()

    @classmethod
    def _sharded_locals(cls, method: ast.AST) -> Set[str]:
        """Local names bound from a sharding-producing expression
        (``sh = rules.sharding_for(...)``) — one-level flow, same
        spirit as MX002's helper resolution."""
        out: Set[str] = set()
        for node in ast.walk(method):
            if isinstance(node, ast.Assign) and \
                    cls._sharding_expr(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    @classmethod
    def _sharded_second_arg(cls, call: ast.Call,
                            sharded_locals: Set[str]) -> bool:
        """True when device_put's placement argument is a sharding."""
        arg = call.args[1] if len(call.args) >= 2 else next(
            (kw.value for kw in call.keywords
             if kw.arg in ("device", "sharding")), None)
        if arg is None:
            # only **kwargs / unknown keywords left: benefit of doubt
            return bool(call.keywords)
        if isinstance(arg, ast.Name) and arg.id in sharded_locals:
            return True
        return cls._sharding_expr(arg)

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        seen: Set[int] = set()
        for method in self._hot_methods(ctx):
            for node in ast.walk(method):
                if isinstance(node, (ast.For, ast.While)):
                    for inner in ast.walk(node):
                        if not isinstance(inner, ast.Call) or \
                                id(inner) in seen:
                            continue
                        desc = self._dispatch_desc(inner)
                        if desc is None:
                            continue
                        seen.add(id(inner))
                        yield ctx.violation(
                            self.id, inner,
                            f"{desc} inside a loop in the "
                            f"{method.name}() step chain dispatches "
                            "once per replica/key — one mesh program "
                            "(SpmdUpdater.update_all_mesh / "
                            "pushpull_fused's SPMD path) replaces the "
                            "whole loop.")
            sharded_locals = self._sharded_locals(method)
            for node in ast.walk(method):
                if isinstance(node, ast.Call) and id(node) not in seen \
                        and _terminal_name(node.func) == "device_put" \
                        and not self._sharded_second_arg(
                            node, sharded_locals):
                    seen.add(id(node))
                    yield ctx.violation(
                        self.id, node,
                        f"device_put without a sharding in the "
                        f"{method.name}() step chain pins data to one "
                        "raw device; pass a NamedSharding (or build "
                        "the global array per the mesh layout) so XLA "
                        "can insert collectives.")
