"""Portable StableHLO deployment artifacts (contrib/deploy.py).

The deployment claim is 'runs without the model's Python code', so the
central test reloads the artifact in a SUBPROCESS that never imports
the model class — the reference's C++-predictor story
(ref: docs/faq/smart_device.md) re-expressed as versioned StableHLO.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import deploy
from mxnet_tpu.gluon import nn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8))
        net.add(nn.Dense(4, in_units=16))
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    return net


def test_roundtrip_same_process(tmp_path):
    net = _mlp()
    x = nd.array(np.random.RandomState(0).rand(2, 8).astype("float32"))
    ref = net(x).asnumpy()
    deploy.export_model(net, str(tmp_path), [x])
    served = deploy.import_model(str(tmp_path))
    np.testing.assert_allclose(served(x).asnumpy(), ref, rtol=1e-6)
    # artifact layout is the documented one
    assert sorted(os.listdir(tmp_path)) == [
        "meta.json", "model.params", "model.stablehlo"]


def test_reload_in_subprocess_without_model_code(tmp_path):
    net = _mlp()
    rng = np.random.RandomState(1)
    x = nd.array(rng.rand(2, 8).astype("float32"))
    ref = net(x).asnumpy()
    deploy.export_model(net, str(tmp_path), [x])
    np.save(tmp_path / "x.npy", x.asnumpy())
    np.save(tmp_path / "ref.npy", ref)
    script = (
        "import os\n"
        "os.environ['XLA_FLAGS'] = "
        "os.environ.get('XLA_FLAGS','')\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "from mxnet_tpu.contrib import deploy\n"
        f"served = deploy.import_model({str(tmp_path)!r})\n"
        f"x = np.load({str(tmp_path / 'x.npy')!r})\n"
        f"ref = np.load({str(tmp_path / 'ref.npy')!r})\n"
        "got = served(x).asnumpy()\n"
        "np.testing.assert_allclose(got, ref, rtol=1e-6)\n"
        "print('SUBPROCESS_SERVE_OK')\n")
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    p = subprocess.run([sys.executable, "-c", script], env=env, cwd=_REPO,
                       capture_output=True, text=True, timeout=240)
    assert p.returncode == 0, (p.stdout + p.stderr)[-1500:]
    assert "SUBPROCESS_SERVE_OK" in p.stdout


def test_param_swap_changes_output(tmp_path):
    net = _mlp()
    x = nd.array(np.random.RandomState(2).rand(2, 8).astype("float32"))
    deploy.export_model(net, str(tmp_path), [x])
    served = deploy.import_model(str(tmp_path))
    before = served(x).asnumpy()
    # 'further training': scale one WEIGHT (biases start at zero, where
    # scaling is a no-op), swap the whole set in
    params = {n: p.data() for n, p in sorted(net.collect_params().items())}
    wname = next(n for n in sorted(params) if n.endswith("weight"))
    params[wname] = params[wname] * 2.0
    served.set_params(params)
    after = served(x).asnumpy()
    assert not np.allclose(after, before)


def test_shape_and_arity_validation(tmp_path):
    net = _mlp()
    x = nd.array(np.zeros((2, 8), "float32"))
    deploy.export_model(net, str(tmp_path), [x])
    served = deploy.import_model(str(tmp_path))
    with pytest.raises(MXNetError, match="fixed-shape"):
        served(nd.array(np.zeros((3, 8), "float32")))
    with pytest.raises(MXNetError, match="takes 1 inputs"):
        served(x, x)
    # a non-artifact directory is rejected up front
    (tmp_path / "empty").mkdir()
    (tmp_path / "empty" / "meta.json").write_text(json.dumps({}))
    with pytest.raises(MXNetError, match="not a deploy artifact"):
        deploy.import_model(str(tmp_path / "empty"))


def test_resnet_block_export(tmp_path):
    """A conv/BN model exports too (running stats are parameters of the
    eval-mode program like any other)."""
    from mxnet_tpu.gluon.model_zoo.vision.resnet import BasicBlockV1

    net = BasicBlockV1(8, 1, downsample=False, in_channels=8,
                       layout="NHWC")
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    x = nd.array(np.random.RandomState(3).rand(1, 8, 8, 8)
                 .astype("float32"))
    net(x)  # resolve shapes
    ref = net(x).asnumpy()
    deploy.export_model(net, str(tmp_path), [x])
    served = deploy.import_model(str(tmp_path))
    np.testing.assert_allclose(served(x).asnumpy(), ref, rtol=1e-5,
                               atol=1e-6)


def test_deferred_init_resolved_by_export(tmp_path):
    """export_model holds example inputs, so it resolves deferred
    shapes itself (the CachedOp resolve-and-retry pattern)."""
    net = nn.Dense(4)  # no in_units
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    x = nd.array(np.random.RandomState(5).rand(2, 6).astype("float32"))
    deploy.export_model(net, str(tmp_path), [x])
    served = deploy.import_model(str(tmp_path))
    np.testing.assert_allclose(served(x).asnumpy(), net(x).asnumpy(),
                               rtol=1e-6)


def test_bad_param_swap_rejected_atomically(tmp_path):
    net = _mlp()
    x = nd.array(np.zeros((2, 8), "float32"))
    deploy.export_model(net, str(tmp_path), [x])
    served = deploy.import_model(str(tmp_path))
    good = served(x).asnumpy()
    params = {n: p.data() for n, p in sorted(net.collect_params().items())}
    wname = next(n for n in sorted(params) if n.endswith("weight"))
    bad = dict(params)
    bad[wname] = nd.zeros((3, 3))
    with pytest.raises(MXNetError, match="shape"):
        served.set_params(bad)
    # the failed swap must not have clobbered the working weights
    np.testing.assert_allclose(served(x).asnumpy(), good, rtol=0, atol=0)
    bad[wname] = nd.zeros(params[wname].shape, dtype="int32")
    with pytest.raises(MXNetError, match="dtype"):
        served.set_params(bad)


def test_input_dtype_validated(tmp_path):
    net = _mlp()
    x = nd.array(np.zeros((2, 8), "float32"))
    deploy.export_model(net, str(tmp_path), [x])
    served = deploy.import_model(str(tmp_path))
    with pytest.raises(MXNetError, match="dtype"):
        served(np.zeros((2, 8), "int32"))


def test_output_ctx_follows_input(tmp_path):
    net = _mlp()
    x = nd.array(np.zeros((2, 8), "float32"))
    deploy.export_model(net, str(tmp_path), [x])
    served = deploy.import_model(str(tmp_path))
    assert served(x).ctx == x.ctx


def test_dynamic_batch_export(tmp_path):
    """dynamic_batch=True serves any batch size from one artifact (the
    serving analogue of BucketingModule), including in a fresh process."""
    net = _mlp()
    x8 = nd.array(np.random.RandomState(7).rand(8, 8).astype("float32"))
    deploy.export_model(net, str(tmp_path), [x8], dynamic_batch=True)
    served = deploy.import_model(str(tmp_path))
    for n in (1, 3, 32):
        xn = nd.array(np.random.RandomState(n).rand(n, 8)
                      .astype("float32"))
        got = served(xn).asnumpy()
        np.testing.assert_allclose(got, net(xn).asnumpy(), rtol=1e-6)
    # non-batch dims stay fixed
    with pytest.raises(MXNetError, match="free batch dim"):
        served(nd.array(np.zeros((2, 9), "float32")))


def test_output_pytree_structure_preserved(tmp_path):
    """A block returning a nested dict/tuple serves the SAME structure,
    not a flat list in tree-flatten order."""
    from mxnet_tpu.gluon.block import HybridBlock

    class _Multi(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.d = nn.Dense(4, in_units=8)

        def hybrid_forward(self, F, x):
            y = self.d(x)
            return {"logits": y, "extras": (y * 2, y + 1)}

    net = _Multi()
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    x = nd.array(np.random.RandomState(9).rand(2, 8).astype("float32"))
    ref = net(x)
    deploy.export_model(net, str(tmp_path), [x])
    served = deploy.import_model(str(tmp_path))
    got = served(x)
    assert isinstance(got, dict) and set(got) == {"logits", "extras"}
    assert isinstance(got["extras"], tuple) and len(got["extras"]) == 2
    np.testing.assert_allclose(got["logits"].asnumpy(),
                               ref["logits"].asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(got["extras"][0].asnumpy(),
                               ref["extras"][0].asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(got["extras"][1].asnumpy(),
                               ref["extras"][1].asnumpy(), rtol=1e-6)


def test_output_namedtuple_fields_preserved(tmp_path):
    """A block returning a namedtuple serves a NAMEDTUPLE back — field
    access by name must survive the artifact round-trip (a plain-tuple
    encoding would break consumers silently)."""
    import collections

    from mxnet_tpu.gluon.block import HybridBlock

    Out = collections.namedtuple("Out", ["logits", "hidden"])

    class _NT(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.d = nn.Dense(4, in_units=8)

        def hybrid_forward(self, F, x):
            y = self.d(x)
            return Out(logits=y, hidden=y * 2)

    net = _NT()
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    x = nd.array(np.random.RandomState(21).rand(2, 8).astype("float32"))
    ref = net(x)
    deploy.export_model(net, str(tmp_path), [x])
    with open(tmp_path / "meta.json") as f:
        tree = json.load(f)["out_tree"]
    assert tree["kind"] == "namedtuple"
    assert tree["fields"] == ["logits", "hidden"]
    served = deploy.import_model(str(tmp_path))
    got = served(x)
    assert hasattr(got, "_fields") and got._fields == ("logits", "hidden")
    np.testing.assert_allclose(got.logits.asnumpy(),
                               ref.logits.asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(got.hidden.asnumpy(),
                               ref.hidden.asnumpy(), rtol=1e-6)


def test_meta_records_exporting_jax_version(tmp_path):
    """meta.json carries the exporter's jax version so a later-era
    deserialization failure is attributable (nightly compat test)."""
    import jax

    net = _mlp()
    x = nd.array(np.zeros((2, 8), "float32"))
    deploy.export_model(net, str(tmp_path), [x])
    with open(tmp_path / "meta.json") as f:
        assert json.load(f)["jax_version"] == jax.__version__


def test_dynamic_batch_scalar_side_input(tmp_path):
    """0-d side-inputs stay concrete under dynamic_batch instead of
    being fabricated into (b,) vectors."""
    from mxnet_tpu.gluon.block import HybridBlock

    class _Scaled(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.d = nn.Dense(4, in_units=8)

        def hybrid_forward(self, F, x, s):
            return self.d(x) * s

    net = _Scaled()
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    x = nd.array(np.random.RandomState(11).rand(2, 8).astype("float32"))
    s = nd.array(np.float32(2.0))
    deploy.export_model(net, str(tmp_path), [x, s], dynamic_batch=True)
    served = deploy.import_model(str(tmp_path))
    x5 = nd.array(np.random.RandomState(12).rand(5, 8).astype("float32"))
    np.testing.assert_allclose(served(x5, s).asnumpy(),
                               net(x5, s).asnumpy(), rtol=1e-6)


def test_artifact_is_multi_platform(tmp_path):
    """Artifacts are lowered for BOTH cpu and tpu, so a model exported
    on the dev box serves on the accelerator host (jax.export would
    otherwise pin the lowering platform)."""
    net = _mlp()
    x = nd.array(np.zeros((2, 8), "float32"))
    deploy.export_model(net, str(tmp_path), [x])
    with open(tmp_path / "meta.json") as f:
        meta = json.load(f)
    assert sorted(meta["platforms"]) == ["cpu", "tpu"]
    from jax import export as jexport

    with open(tmp_path / "model.stablehlo", "rb") as f:
        exported = jexport.deserialize(f.read())
    assert sorted(exported.platforms) == ["cpu", "tpu"]


def test_single_platform_opt_out(tmp_path):
    net = _mlp()
    x = nd.array(np.zeros((2, 8), "float32"))
    deploy.export_model(net, str(tmp_path), [x], platforms=("cpu",))
    with open(tmp_path / "meta.json") as f:
        meta = json.load(f)
    assert meta["platforms"] == ["cpu"]
    served = deploy.import_model(str(tmp_path))
    assert served(x).shape == (2, 4)


def test_non_platform_export_error_not_retried(tmp_path, monkeypatch):
    """An export failure unrelated to platform lowering re-raises
    directly instead of burning a second trace on the fallback."""
    from jax import export as jexport

    calls = {"n": 0}
    real = jexport.export

    def spy(*a, **k):
        calls["n"] += 1
        if "platforms" in k:
            raise ValueError("symbolic dimension mismatch in reshape")
        return real(*a, **k)

    monkeypatch.setattr("jax.export.export", spy)
    net = _mlp()
    x = nd.array(np.zeros((2, 8), "float32"))
    with pytest.raises(ValueError, match="symbolic dimension"):
        deploy.export_model(net, str(tmp_path), [x])
    assert calls["n"] == 1  # no second lowering attempt


def test_unknown_platform_raises_not_degrades(tmp_path):
    """A typo'd platform name raises up front (jax.export would accept
    the string silently and produce an artifact that can never serve
    where it claims to)."""
    net = _mlp()
    x = nd.array(np.zeros((2, 8), "float32"))
    with pytest.raises(MXNetError, match="gpux"):
        deploy.export_model(net, str(tmp_path), [x],
                            platforms=("cpu", "gpux"))
    assert not (tmp_path / "model.stablehlo").exists()
