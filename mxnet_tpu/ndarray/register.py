"""Generated op namespace for mxnet_tpu.nd.

Counterpart of the reference's import-time wrapper generation
(ref: python/mxnet/ndarray/register.py::_make_ndarray_function, which lists
registered ops through the C API and synthesizes Python functions).  Here
wrappers are synthesized lazily from the op registry via module __getattr__.

Special frontends (RNG injection, train-mode injection, in-place aux-state
rebinds) are defined explicitly below, matching the reference ops whose
kernels consult OpContext state.
"""
from __future__ import annotations

from typing import Callable, Dict

from .. import autograd
from .. import random as _random
from ..ops.registry import OP_REGISTRY, invoke
from .ndarray import NDArray


def _make_wrapper(name: str) -> Callable:
    def fn(*args, out=None, name=name, **kwargs):
        res = invoke(name, *args, **kwargs)
        if out is not None:
            src = res[0] if isinstance(res, list) else res
            out._data = src._data
            return out
        return res

    op = OP_REGISTRY.get(name)
    fn.__name__ = name
    fn.__qualname__ = name
    fn.__doc__ = (f"Imperative wrapper for registered op '{name}'.\n\n"
                  f"{op.param_doc}")
    return fn


# ---- special frontends ----------------------------------------------------

def Dropout(data, p=0.5, mode="training", axes=(), **kw):
    """ref: nd.Dropout — consults global train mode; key auto-threaded."""
    return invoke("Dropout", data, _random.next_key(), p=p, mode=mode,
                  axes=tuple(axes), _train=autograd.is_training())


def Custom(*inputs, op_type=None, **kwargs):
    """Eager frontend for user CustomOps (ref: nd.Custom over custom.cc).

    Runs the user's forward/backward DIRECTLY on host numpy — works on
    any device, including PJRT plugins without host-callback support
    (this container's axon TPU tunnel is one).  Traced programs
    (hybridize / Symbol / SPMDTrainer) instead hit the registry 'Custom'
    op, which stages the same host code via jax.pure_callback."""
    import jax.numpy as jnp
    import numpy as np

    from .. import operator as _operator
    from .ndarray import NDArray

    prop = _operator.get_prop(op_type)(**kwargs)
    np_ins = [x.asnumpy() for x in inputs]
    structs = _operator.out_structs_for(
        prop, [a.shape for a in np_ins], [a.dtype for a in np_ins])
    # ONE operator instance shared forward->backward (user code may stash
    # forward state on self for backward, reference lifetime semantics)
    op_inst = _operator.make_operator(prop, np_ins)
    np_outs = _operator.run_forward_host(op_inst, np_ins, structs,
                                         is_train=autograd.is_training())
    ctx = inputs[0].ctx if inputs else None
    outs = tuple(NDArray(jnp.asarray(o), ctx=ctx) for o in np_outs)
    if autograd.is_recording():
        parents = [(autograd._node_of(x), x) for x in inputs]

        def custom_backward(node_cts, _np_ins=np_ins, _np_outs=np_outs,
                            _op=op_inst):
            import jax

            np_cts = [np.asarray(jax.device_get(c)) if c is not None
                      else np.zeros(o.shape, o.dtype)
                      for c, o in zip(node_cts, _np_outs)]
            grads = _operator.run_backward_host(_op, _np_ins, _np_outs,
                                                np_cts)
            return [jnp.asarray(g) for g in grads]

        node = autograd.TapeNode(None, None, [x.data for x in inputs],
                                 parents, len(outs),
                                 custom_backward=custom_backward)
        for i, o in enumerate(outs):
            o._ag_node = (node, i)
    return outs[0] if len(outs) == 1 else list(outs)


def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
              momentum=0.9, fix_gamma=False, use_global_stats=False,
              output_mean_var=False, axis=1, **kw):
    """ref: nd.BatchNorm — updates moving stats in place in train mode."""
    train = autograd.is_training() and not use_global_stats
    res = invoke("BatchNorm", data, gamma, beta, moving_mean, moving_var,
                 eps=eps, momentum=momentum, fix_gamma=fix_gamma,
                 use_global_stats=use_global_stats, axis=axis, _train=train,
                 **kw)
    if train:
        out, new_mean, new_var = res
        moving_mean._data = new_mean._data
        moving_var._data = new_var._data
        return out
    return res


def dot_product_attention(query, key, value, valid_mask=None, num_heads=1,
                          scale=None, dropout=0.0, causal=False, **kw):
    """Fused attention frontend — threads the PRNG key + train flag for
    attention-probability dropout (ref: BERT dropout-on-softmax)."""
    if valid_mask is None:
        import numpy as _np

        from .ndarray import array as _array

        sk = key.shape[1] if key.ndim == 3 else key.shape[2]
        valid_mask = _array(_np.ones((key.shape[0], sk), _np.float32),
                            ctx=key.ctx)
    return invoke("dot_product_attention", query, key, value, valid_mask,
                  _random.next_key(), num_heads=num_heads, scale=scale,
                  dropout=dropout, causal=causal,
                  _train=autograd.is_training())


def _make_random_wrapper(op_name: str):
    def fn(*args, ctx=None, **kwargs):
        out = invoke(op_name, _random.next_key(), *args, **kwargs)
        if ctx is not None:
            out = out.as_in_context(ctx)
        return out

    fn.__name__ = op_name
    return fn


_SPECIAL: Dict[str, Callable] = {
    "Dropout": Dropout,
    "dropout": Dropout,
    "BatchNorm": BatchNorm,
    "batch_norm": BatchNorm,
    "dot_product_attention": dot_product_attention,
    "FusedAttention": dot_product_attention,
    "Custom": Custom,
}
for _rn in ("_random_uniform", "_random_normal", "_random_randint",
            "_random_gamma", "_random_exponential", "_random_poisson",
            "_random_bernoulli", "_sample_multinomial", "_shuffle",
            "_random_gumbel", "_random_laplace", "_random_negative_binomial",
            "_sample_uniform", "_sample_normal", "_sample_gamma",
            "_sample_exponential", "_sample_poisson",
            "_sample_negative_binomial",
            "_sample_generalized_negative_binomial"):
    _SPECIAL[_rn] = _make_random_wrapper(_rn)
    _SPECIAL[_rn.lstrip("_")] = _SPECIAL[_rn]  # e.g. nd.sample_gamma
# legacy bare aliases (ref: nd.uniform/nd.normal over random_uniform)
_SPECIAL["uniform"] = _SPECIAL["_random_uniform"]
_SPECIAL["normal"] = _SPECIAL["_random_normal"]


def lookup(name: str):
    if name in _SPECIAL:
        return _SPECIAL[name]
    if name in OP_REGISTRY:
        return _make_wrapper(name)
    raise AttributeError(f"no registered op '{name}'")
