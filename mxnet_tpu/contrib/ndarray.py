"""mx.contrib.ndarray — imperative contrib op wrappers
(ref: python/mxnet/ndarray/contrib.py generated namespace)."""
from __future__ import annotations

from ..ndarray import register as _register


def __getattr__(name):
    return _register.lookup(name)
