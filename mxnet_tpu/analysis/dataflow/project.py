"""The whole-program half of mxflow: module index, call graph,
bottom-up summary propagation, and the content-hash summary cache.

A :class:`Project` is built from the set of files a lint run touches
*plus* every sibling module of any package those files belong to — an
interprocedural rule linting one changed file still needs the summaries
of the 150 modules it can call into.  Files outside any package (test
fixtures in a tmp dir) form a flat pseudo-package of their stems.

Cost model (the <30s full / <1s ``--diff`` acceptance criteria):

  * extraction (parse + local summary) is the expensive part and is a
    pure function of file bytes -> cached in ``.mxflow_cache.json``
    next to the package, keyed by sha1.  A ``--diff`` run parses only
    the changed files;
  * resolution + transitive propagation is in-memory dict work over a
    few thousand function records and reruns every time — which is
    exactly what makes a changed dependency invalidate its dependents'
    *derived* facts without any dependency bookkeeping: local
    summaries are per-file, transitive ones are never persisted.

Resolution policy: an unresolvable call contributes NOTHING (empty
callee list) — conservative in the precision direction, because every
rule built on this reports only what it can prove (a lint gate that
guesses gets pragma'd into silence).
"""
from __future__ import annotations

import ast
import hashlib
import json
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .summaries import extract_module

__all__ = ["Project", "FuncInfo", "get_project", "build_project",
           "CACHE_NAME", "clear_memo"]

CACHE_NAME = ".mxflow_cache.json"
_CACHE_VERSION = 2

_MEMO_LOCK = threading.Lock()
_MEMO: "Dict[tuple, Project]" = {}
_MEMO_MAX = 4


class FuncInfo:
    """One function/method in the project, with its local summary and
    the transitive facts propagation filled in."""

    __slots__ = ("qual", "mod", "cls", "name", "rec", "edges",
                 "t_blocks", "t_syncs", "t_donates", "t_raises")

    def __init__(self, qual: str, mod: str, cls: Optional[str],
                 name: str, rec: Dict[str, Any]):
        self.qual = qual          # "module:Class.meth" / "module:fn"
        self.mod = mod
        self.cls = cls
        self.name = name
        self.rec = rec
        # (call entry, resolved callees) pairs — resolution runs once
        # in propagate(); rules iterate these instead of re-resolving
        self.edges: List[Tuple[Dict[str, Any], List["FuncInfo"]]] = []
        # transitive facts: None, or ("direct", desc, line), or
        # ("call", callee_qual, call_line)
        self.t_blocks: Optional[tuple] = None
        self.t_syncs: Optional[tuple] = None
        # param index -> ("direct", line) | ("call", callee_qual,
        #                 call_line, callee_pos)
        self.t_donates: Dict[int, tuple] = {}
        self.t_raises: bool = bool(rec.get("raises"))

    @property
    def params(self) -> List[str]:
        return self.rec.get("params", [])

    @property
    def hot(self) -> bool:
        return bool(self.rec.get("hot"))


def _sha1(data: bytes) -> str:
    return hashlib.sha1(data).hexdigest()


def _package_root(path: str) -> Optional[str]:
    """Topmost directory of the package ``path`` belongs to (walks up
    while ``__init__.py`` exists), or None for a loose file."""
    d = os.path.dirname(os.path.abspath(path))
    if not os.path.exists(os.path.join(d, "__init__.py")):
        return None
    while os.path.exists(os.path.join(os.path.dirname(d),
                                      "__init__.py")):
        d = os.path.dirname(d)
    return d


def _modname_for(path: str, root: Optional[str]) -> Tuple[str, bool]:
    """(dotted module name, is_package_init)."""
    path = os.path.abspath(path)
    if root is None:
        return os.path.splitext(os.path.basename(path))[0], False
    rel = os.path.relpath(path, os.path.dirname(root))
    parts = rel.replace(os.sep, "/").split("/")
    is_pkg = parts[-1] == "__init__.py"
    if is_pkg:
        parts = parts[:-1]
    else:
        parts[-1] = parts[-1][:-3]
    return ".".join(parts), is_pkg


class Project:
    """Module records + resolution + propagated summaries."""

    def __init__(self) -> None:
        self.modules: Dict[str, Dict[str, Any]] = {}
        self.funcs: Dict[str, FuncInfo] = {}
        self.ops: Dict[str, str] = {}      # op name -> function qual
        self.path_mod: Dict[str, str] = {}  # abs path -> modname
        self._resolve_memo: Dict[tuple, List[FuncInfo]] = {}
        self._module_memo: Dict[str, Optional[Dict[str, Any]]] = {}
        self.errors: List[str] = []
        self.cache_hits = 0
        self.cache_misses = 0

    def funcs_of_module(self, modname: str) -> List[FuncInfo]:
        return [f for f in self.funcs.values() if f.mod == modname]

    # ---- indexing -----------------------------------------------------

    def _index_module(self, data: Dict[str, Any]) -> None:
        mod = data["modname"]
        self.modules[mod] = data
        for fname, rec in data.get("functions", {}).items():
            self._index_fn(mod, None, fname, rec)
        for cname, cls in data.get("classes", {}).items():
            for mname, rec in cls.get("methods", {}).items():
                self._index_fn(mod, cname, mname, rec)
        for op, fname in data.get("register_ops", {}).items():
            self.ops.setdefault(op, f"{mod}:{fname}")

    def _index_fn(self, mod: str, cls: Optional[str], name: str,
                  rec: Dict[str, Any], prefix: str = "") -> None:
        local = (f"{cls}." if cls else "") + prefix + name
        qual = f"{mod}:{local}"
        self.funcs[qual] = FuncInfo(qual, mod, cls, name, rec)
        for nname, nrec in rec.get("nested", {}).items():
            self._index_fn(mod, cls, nname, nrec,
                           prefix=prefix + name + ".<locals>.")

    # ---- name resolution ----------------------------------------------

    def _module(self, dotted: str) -> Optional[Dict[str, Any]]:
        m = self.modules.get(dotted)
        if m is not None:
            return m
        hit = self._module_memo.get(dotted, False)
        if hit is not False:
            return hit
        # suffix match tolerates the project seeing a package from a
        # different root spelling (tmp fixture packages, vendored dirs)
        cands = [k for k in self.modules
                 if k == dotted or k.endswith("." + dotted)]
        out = self.modules[cands[0]] if len(cands) == 1 else None
        self._module_memo[dotted] = out
        return out

    def _follow_import(self, mod: Dict[str, Any], alias: str,
                       depth: int = 0) -> Optional[tuple]:
        """Resolve ``alias`` in ``mod`` to ("mod", modname) or
        ("fn", qual) or ("cls", modname, clsname).  Follows re-export
        chains through package __init__ files (bounded)."""
        if depth > 6:
            return None
        if alias in mod.get("functions", {}):
            return ("fn", f"{mod['modname']}:{alias}")
        if alias in mod.get("classes", {}):
            return ("cls", mod["modname"], alias)
        imp = mod.get("imports", {}).get(alias)
        if imp is None:
            return None
        if imp[0] == "mod":
            target = self._module(imp[1])
            return ("mod", target["modname"]) if target else None
        # ["sym", module, name]: the name may itself be a submodule
        # (from .serving import batcher), a function, a class, or a
        # re-export to chase one level deeper
        src = self._module(imp[1])
        sub = self._module(f"{imp[1]}.{imp[2]}")
        if sub is not None:
            return ("mod", sub["modname"])
        if src is None:
            return None
        return self._follow_import(src, imp[2], depth + 1)

    def _class_info(self, modname: str, clsname: str,
                    depth: int = 0) -> Optional[Tuple[str, Dict]]:
        mod = self._module(modname)
        if mod is None or depth > 6:
            return None
        cls = mod.get("classes", {}).get(clsname)
        if cls is not None:
            return (mod["modname"], cls)
        got = self._follow_import(mod, clsname, depth + 1)
        if got is not None and got[0] == "cls":
            return self._class_info(got[1], got[2], depth + 1)
        return None

    def _method(self, modname: str, clsname: str, meth: str,
                depth: int = 0) -> Optional[FuncInfo]:
        """Method lookup through the (first-party) base-class chain."""
        got = self._class_info(modname, clsname)
        if got is None or depth > 8:
            return None
        cmod, cls = got
        if meth in cls.get("methods", {}):
            return self.funcs.get(f"{cmod}:{clsname}.{meth}")
        for base in cls.get("bases", []):
            leaf = base.rsplit(".", 1)
            if len(leaf) == 2:
                # qualified base (module.Cls): resolve the module part
                bmod = self._module_of_alias(cmod, leaf[0])
                if bmod:
                    hit = self._method(bmod, leaf[1], meth, depth + 1)
                    if hit:
                        return hit
                continue
            hit = self._method(cmod, base, meth, depth + 1)
            if hit:
                return hit
        return None

    def _module_of_alias(self, modname: str, alias: str
                         ) -> Optional[str]:
        mod = self._module(modname)
        if mod is None:
            return None
        got = self._follow_import(mod, alias)
        return got[1] if got and got[0] == "mod" else None

    def resolve(self, modname: str, clsname: Optional[str],
                ref: Optional[Sequence[str]]) -> List[FuncInfo]:
        """Callees for one symbolic reference; [] when unresolvable
        (the conservative default every rule is built against).
        ``clsname`` scopes ``self``/``sattr`` references.  Memoized —
        the same (module, class, ref) repeats across thousands of
        call sites and resolution is pure once the index is built."""
        if not ref:
            return []
        key = (modname, clsname, tuple(ref))
        hit = self._resolve_memo.get(key)
        if hit is not None:
            return hit
        out = self._resolve_uncached(modname, clsname, ref)
        self._resolve_memo[key] = out
        return out

    def _resolve_uncached(self, modname: str, clsname: Optional[str],
                          ref: Sequence[str]) -> List[FuncInfo]:
        mod = self._module(modname)
        if mod is None:
            return []
        kind = ref[0]
        if kind == "n":
            return self._resolve_name(mod, ref[1])
        if kind == "self" and clsname:
            hit = self._method(modname, clsname, ref[1])
            return [hit] if hit else []
        if kind == "sattr" and clsname:
            got = self._class_info(modname, clsname)
            if got:
                attr_t = got[1].get("attrs", {}).get(ref[1])
                if attr_t:
                    return self._resolve_typed(mod, attr_t, ref[2])
            return []
        if kind == "lv":
            return self._resolve_typed(mod, ref[1], ref[2])
        if kind == "a":
            base, meth = ref[1], ref[2]
            got = self._follow_import(mod, base)
            if got is None:
                # op-registry indirection: F.relu / nd.relu where the
                # namespace is synthesized at runtime from register_op
                return self._op(meth)
            if got[0] == "mod":
                target = self._module(got[1])
                if target:
                    return self._resolve_name(target, meth)
            elif got[0] == "cls":
                hit = self._method(got[1], got[2], meth)
                return [hit] if hit else []
            return []
        if kind == "c":
            dotted = ref[1]
            head, _, rest = dotted.partition(".")
            got = self._follow_import(mod, head)
            while got and got[0] == "mod" and "." in rest:
                nxt, _, rest = rest.partition(".")
                target = self._module(got[1])
                if target is None:
                    return []
                got = self._follow_import(target, nxt)
            if got and got[0] == "mod" and rest:
                target = self._module(got[1])
                if target:
                    return self._resolve_name(target, rest)
            return []
        return []

    def _resolve_name(self, mod: Dict[str, Any], name: str
                      ) -> List[FuncInfo]:
        if name in mod.get("functions", {}):
            hit = self.funcs.get(f"{mod['modname']}:{name}")
            return [hit] if hit else []
        if name in mod.get("classes", {}):
            # a constructor call runs __init__
            hit = self._method(mod["modname"], name, "__init__")
            return [hit] if hit else []
        got = self._follow_import(mod, name)
        if got is None:
            return self._op(name)
        if got[0] == "fn":
            hit = self.funcs.get(got[1])
            return [hit] if hit else []
        if got[0] == "cls":
            hit = self._method(got[1], got[2], "__init__")
            return [hit] if hit else []
        return []

    def _resolve_typed(self, mod: Dict[str, Any], clstext: str,
                       meth: str) -> List[FuncInfo]:
        """<expr of class type clstext>.meth().  clstext may be a bare
        class name, a dotted alias.Cls, or a factory "fn()" marker."""
        if clstext.endswith("()"):
            # receiver is the result of a call (e.g. _io_policy());
            # resolve the factory's return type only through the
            # well-known policy idiom: unresolvable otherwise
            return []
        if "." in clstext:
            alias, cls = clstext.rsplit(".", 1)
            modname = self._module_of_alias(mod["modname"], alias)
            if modname is None:
                return []
            hit = self._method(modname, cls, meth)
            return [hit] if hit else []
        got = self._follow_import(mod, clstext) \
            if clstext not in mod.get("classes", {}) \
            else ("cls", mod["modname"], clstext)
        if got and got[0] == "cls":
            hit = self._method(got[1], got[2], meth)
            return [hit] if hit else []
        return []

    def _op(self, name: str) -> List[FuncInfo]:
        qual = self.ops.get(name)
        hit = self.funcs.get(qual) if qual else None
        return [hit] if hit else []

    def resolve_call(self, fn: FuncInfo,
                     entry: Dict[str, Any]) -> List[FuncInfo]:
        """Callees of one recorded call entry, checking the caller's
        own nested defs first (closures are called by name)."""
        ref = entry.get("ref")
        if ref and ref[0] == "n":
            nested = fn.rec.get("nested", {})
            if ref[1] in nested:
                hit = self.funcs.get(
                    f"{fn.mod}:" + (f"{fn.cls}." if fn.cls else "")
                    + self._local_of(fn) + ".<locals>." + ref[1])
                return [hit] if hit else []
        return self.resolve(fn.mod, fn.cls, ref)

    def _local_of(self, fn: FuncInfo) -> str:
        local = fn.qual.split(":", 1)[1]
        if fn.cls and local.startswith(fn.cls + "."):
            local = local[len(fn.cls) + 1:]
        return local

    # ---- transitive propagation ---------------------------------------

    def propagate(self) -> None:
        """Bottom-up fixpoint for blocks/syncs/raises/donates.  Facts
        only turn on, so iteration terminates; witness chains record
        the first call edge that switched a fact on (rule messages
        walk them into a path)."""
        for f in self.funcs.values():
            rec = f.rec
            if rec.get("blocks"):
                f.t_blocks = ("direct", rec["blocks"][0], rec["blocks"][1])
            if rec.get("syncs"):
                f.t_syncs = ("direct", rec["syncs"][0], rec["syncs"][1])
            for pos, line in rec.get("donates", {}).items():
                f.t_donates[int(pos)] = ("direct", line)
            f.edges = [(entry, self.resolve_call(f, entry))
                       for entry in rec.get("calls", [])]
        changed = True
        while changed:
            changed = False
            for f in self.funcs.values():
                for entry, callees in f.edges:
                    for g in callees:
                        if g is f:
                            continue
                        if f.t_blocks is None and g.t_blocks is not None:
                            f.t_blocks = ("call", g.qual, entry["line"])
                            changed = True
                        if f.t_syncs is None and g.t_syncs is not None:
                            f.t_syncs = ("call", g.qual, entry["line"])
                            changed = True
                        if not f.t_raises and g.t_raises:
                            f.t_raises = True
                            changed = True
                        # donation flows: my param passed at a donated
                        # position of the callee donates my position
                        if g.t_donates:
                            args = entry.get("args", [])
                            params = f.params
                            for cpos in g.t_donates:
                                if cpos < len(args) and args[cpos] and \
                                        args[cpos] in params:
                                    mypos = params.index(args[cpos])
                                    if mypos not in f.t_donates:
                                        f.t_donates[mypos] = (
                                            "call", g.qual,
                                            entry["line"], cpos)
                                        changed = True

    def witness_path(self, fact: Optional[tuple],
                     kind: str, limit: int = 6) -> Tuple[str, int]:
        """Flatten a blocks/syncs witness chain into ("a() -> b() ->
        .asnumpy() (file:line)", first_call_line)."""
        hops: List[str] = []
        line = 0
        seen = set()
        while fact is not None and len(hops) < limit:
            if fact[0] == "direct":
                hops.append(f"{fact[1]} at line {fact[2]}")
                break
            qual = fact[1]
            if qual in seen:
                break
            seen.add(qual)
            if not line:
                line = fact[2]
            g = self.funcs.get(qual)
            if g is None:
                break
            hops.append(_pretty(qual))
            fact = g.t_blocks if kind == "blocks" else g.t_syncs
        return " -> ".join(hops), line


def _pretty(qual: str) -> str:
    mod, _, local = qual.partition(":")
    leaf = mod.rsplit(".", 1)[-1]
    return f"{leaf}.{local}()"


# ---------------------------------------------------------------------------
# building + caching
# ---------------------------------------------------------------------------

def _discover(paths: Iterable[str]) -> Tuple[Dict[str, Optional[str]],
                                             List[str]]:
    """{abs file -> package root or None} for the lint set plus every
    sibling of any package it touches; plus the package roots."""
    files: Dict[str, Optional[str]] = {}
    roots: List[str] = []

    def register(path: str) -> None:
        root = _package_root(path)
        files.setdefault(path, root)
        if root and root not in roots:
            roots.append(root)

    for p in paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for f in filenames:
                    if f.endswith(".py"):
                        register(os.path.join(dirpath, f))
            continue
        register(p)
    for root in roots:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for f in filenames:
                if f.endswith(".py"):
                    files.setdefault(os.path.join(dirpath, f), root)
    return files, roots


def _load_cache(path: str) -> Dict[str, Any]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        if doc.get("version") == _CACHE_VERSION and \
                isinstance(doc.get("files"), dict):
            return doc["files"]
    except (OSError, ValueError):
        pass
    return {}


def _store_cache(path: str, files: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": _CACHE_VERSION, "files": files}, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass  # cache is an optimization; never fail the lint


def build_project(paths: Sequence[str],
                  parsed: Optional[Dict[str, ast.Module]] = None,
                  use_cache: bool = True) -> Project:
    """Index the project reachable from ``paths``.  ``parsed`` maps
    abs paths to already-parsed trees (the engine's FileContexts) so
    linted files are never parsed twice."""
    parsed = parsed or {}
    proj = Project()
    files, roots = _discover(paths)
    cache_path = None
    cache: Dict[str, Any] = {}
    if use_cache and len(roots) == 1:
        cache_path = os.path.join(os.path.dirname(roots[0]), CACHE_NAME)
        cache = _load_cache(cache_path)
    dirty = False
    for path, root in sorted(files.items()):
        modname, is_pkg = _modname_for(path, root)
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError as e:
            proj.errors.append(f"{path}: {e}")
            continue
        sha = _sha1(blob)
        key = os.path.relpath(path, os.path.dirname(root)) \
            if root else os.path.basename(path)
        key = key.replace(os.sep, "/")
        ent = cache.get(key)
        if ent is not None and ent.get("sha1") == sha:
            # the summary is a pure function of the bytes, so a sha
            # match serves even files the engine parsed for reporting
            proj.cache_hits += 1
            data = dict(ent["data"], modname=modname)
        else:
            proj.cache_misses += 1
            tree = parsed.get(path)
            if tree is None:
                try:
                    tree = ast.parse(blob.decode("utf-8", "replace"),
                                     filename=path)
                except SyntaxError as e:
                    proj.errors.append(f"{path}: {e}")
                    continue
            data = extract_module(tree, modname, is_pkg=is_pkg,
                                  source=blob.decode("utf-8", "replace"))
            if cache_path is not None and (
                    ent is None or ent.get("sha1") != sha):
                cache[key] = {"sha1": sha, "data": data}
                dirty = True
        proj.path_mod[path] = modname
        proj._index_module(data)
    if cache_path is not None and dirty:
        # drop entries for files that no longer exist (renames)
        live = {os.path.relpath(p, os.path.dirname(r)).replace(
            os.sep, "/") if r else os.path.basename(p)
            for p, r in files.items()}
        cache = {k: v for k, v in cache.items() if k in live}
        _store_cache(cache_path, cache)
    proj.propagate()
    return proj


class _LazyParsed:
    """``parsed``-mapping view over the engine's FileContexts that
    forces a context's (possibly lazy) parse only when the summary
    cache actually misses — on a warm incremental-cache run the engine
    never parsed unchanged files, and neither should we."""

    def __init__(self, ctxs: Sequence[Any]) -> None:
        self._by_path = {os.path.abspath(c.path): c for c in ctxs}

    def get(self, path: str, default: Any = None) -> Any:
        c = self._by_path.get(path)
        if c is None:
            return default
        try:
            return c.tree
        except SyntaxError:
            return default  # build_project re-parses and records the error


def get_project(ctxs: Sequence[Any], use_cache: bool = True) -> Project:
    """Memoized :func:`build_project` over the engine's FileContexts —
    the five dataflow rules in one engine run share one build."""
    paths = [c.path for c in ctxs]
    key_parts = []
    files, _ = _discover(paths)
    for p in sorted(files):
        try:
            st = os.stat(p)
            key_parts.append((p, st.st_mtime_ns, st.st_size))
        except OSError:
            key_parts.append((p, 0, 0))
    key = tuple(key_parts)
    with _MEMO_LOCK:
        hit = _MEMO.get(key)
    if hit is not None:
        return hit
    proj = build_project(paths, parsed=_LazyParsed(ctxs),
                         use_cache=use_cache)
    with _MEMO_LOCK:
        if len(_MEMO) >= _MEMO_MAX:
            _MEMO.pop(next(iter(_MEMO)))
        _MEMO[key] = proj
    return proj


def clear_memo() -> None:
    with _MEMO_LOCK:
        _MEMO.clear()
