"""BaseModule: the canonical symbolic training loop
(ref: python/mxnet/module/base_module.py — BaseModule.fit/score/predict).

The intermediate-level interface over bind/init_params/init_optimizer/
forward/backward/update, shared by Module and BucketingModule.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import metric as metric_mod
from ..base import MXNetError
from ..callback import BatchEndParam
from ..ndarray import NDArray
from .. import ndarray as nd

__all__ = ["BaseModule"]


def _as_metric(eval_metric):
    if isinstance(eval_metric, metric_mod.EvalMetric):
        return eval_metric
    return metric_mod.create(eval_metric)


def _check_input_names(symbol, names, typename, throw):
    args = set(symbol.list_arguments())
    for name in names:
        if name not in args:
            msg = f"You created Module with Module(..., {typename}_names={names}) " \
                  f"but input with name '{name}' is not found in symbol.list_arguments(). "
            if throw:
                raise ValueError(msg)
            logging.warning(msg)


class BaseModule:
    """ref: BaseModule — subclasses implement bind/init_params/forward/..."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # ---- properties subclasses provide ----------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self) -> List[str]:
        raise NotImplementedError

    @property
    def output_names(self) -> List[str]:
        raise NotImplementedError

    @property
    def data_shapes(self):
        raise NotImplementedError

    @property
    def label_shapes(self):
        raise NotImplementedError

    @property
    def output_shapes(self):
        raise NotImplementedError

    # ---- core abstract ---------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True) -> List[NDArray]:
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError

    # ---- conveniences (ref implementations live here) -------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """ref: BaseModule.score — run inference and accumulate metric."""
        assert self.binded and self.params_initialized
        eval_metric = _as_metric(eval_metric)
        if reset:
            eval_data.reset()
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(param)
        if score_end_callback is not None:
            param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                  eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(param)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """ref: BaseModule.predict."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            output_list.append(outs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            merged = [nd.concatenate([o[i] for o in output_list], axis=0)
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """The canonical train loop (ref: BaseModule.fit; CS3 in SURVEY.md):
        per batch forward/backward/update/metric, per epoch eval+callbacks."""
        assert num_epoch is not None, "please specify number of epochs"
        from .. import initializer as init_mod

        if initializer is None:
            initializer = init_mod.Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if validation_metric is None:
            validation_metric = eval_metric
        eval_metric = _as_metric(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            for data_batch in train_data:
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                          eval_metric=eval_metric,
                                          locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(param)
                nbatch += 1

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)

            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p, allow_missing=False,
                            force_init=True)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

    def install_monitor(self, mon):
        raise NotImplementedError

    def save_params(self, fname: str):
        arg_params, aux_params = self.get_params()
        save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
        save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
        from .. import serialization

        serialization.save_ndarrays(fname, save_dict)

    def load_params(self, fname: str):
        from .. import serialization

        loaded = serialization.load_ndarrays(fname)
        arg_params, aux_params = {}, {}
        for k, v in loaded.items():
            tag, name = k.split(":", 1)
            (arg_params if tag == "arg" else aux_params)[name] = v
        self.set_params(arg_params, aux_params)


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]
