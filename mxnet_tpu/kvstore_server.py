"""KVStore server entry (ref: python/mxnet/kvstore_server.py).

The reference runs dedicated parameter-server processes
(DMLC_ROLE=server) that apply optimizer updates server-side.  Here the
collective substrate subsumes servers: gradients are allreduced in-graph
(parallel/dist.py) and every worker applies the update locally, so a
"server" has nothing to serve.  Launchers that still spawn server roles
(tools/launch.py parity, reference cluster scripts) land in
``_init_kvstore_server_module``, which parks the process until the job
ends instead of crashing the launch.
"""
from __future__ import annotations

import os

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer:
    """API-parity shim: run() blocks for the job's lifetime."""

    def __init__(self, kvstore=None):
        self.kvstore = kvstore

    def run(self):  # pragma: no cover - exercised via launch parity
        from .parallel import dist

        dist.init()  # registers, then returns (server role is absorbed)
        # nothing to serve: wait for the coordinator to wind down
        try:
            dist.barrier("server_park")
        except Exception:
            pass


def _init_kvstore_server_module():
    """ref: kvstore_server._init_kvstore_server_module — called by
    reference launch scripts when DMLC_ROLE=server."""
    if os.environ.get("DMLC_ROLE") == "server":
        KVStoreServer().run()
