"""NDArray: imperative tensor over a JAX/PjRt device buffer.

TPU-native counterpart of the reference NDArray
(ref: include/mxnet/ndarray.h, src/ndarray/ndarray.cc — chunk + engine var
+ shape/dtype/ctx; python/mxnet/ndarray/ndarray.py frontend).

Design notes (idiomatic TPU, not a port):
  * The payload is a ``jax.Array`` living in HBM (or host memory for cpu
    contexts).  JAX dispatch is asynchronous — calling an op returns a
    future-backed array immediately, which is exactly the contract the
    reference's dependency engine provides; ``asnumpy``/``wait_to_read``
    are the only sync points (ref: Engine::WaitForVar).
  * Mutation (in-place ops, sliced assignment) is emulated functionally:
    the op produces a fresh buffer and the NDArray rebinds to it.  XLA's
    buffer donation makes this allocation-free inside jitted programs;
    version-counter semantics (reads-before-write ordering) are inherited
    from JAX's effect ordering.
  * Autograd hooks (attach_grad / .grad / backward) live directly on the
    array, recorded by mxnet_tpu.autograd's tape.
"""
from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, integer_types, numeric_types
from ..context import Context, cpu, current_context

__all__ = ["NDArray", "wrap_outputs", "array", "zeros", "ones", "full",
           "empty", "arange", "from_jax", "concatenate", "stack"]

_DTYPE_ALIASES = {
    "float32": jnp.float32, "float64": jnp.float64, "float16": jnp.float16,
    "bfloat16": jnp.bfloat16, "uint8": jnp.uint8, "int8": jnp.int8,
    "int32": jnp.int32, "int64": jnp.int64, "bool": jnp.bool_,
    None: jnp.float32,
}


_INT32_MAX = 2 ** 31 - 1


def _normalize_basic_key(pval, key):
    """(starts, limits, strides, squeeze) tuples for a fully-basic key,
    or None when the key has advanced components / negative steps."""
    ks = key if isinstance(key, tuple) else (key,)
    if any(k is Ellipsis for k in ks):
        # expand a single Ellipsis to full slices (x[...], x[..., 0])
        pos = next(i for i, k in enumerate(ks) if k is Ellipsis)
        if any(k is Ellipsis for k in ks[pos + 1:]):
            return None
        fill = pval.ndim - (len(ks) - 1)
        ks = ks[:pos] + (slice(None),) * fill + ks[pos + 1:]
    if len(ks) > pval.ndim or not all(
            isinstance(k, (int, np.integer, slice)) for k in ks):
        return None
    starts, limits, strides, squeeze = [], [], [], []
    for i, k in enumerate(ks):
        n = pval.shape[i]
        if isinstance(k, slice):
            st, sp, stp = k.indices(n)
            if stp <= 0:
                return None
            sp = max(sp, st)  # x[10:5] is a valid EMPTY slice, not an error
            starts.append(st)
            limits.append(sp)
            strides.append(stp)
        else:
            k = int(k) + (n if int(k) < 0 else 0)
            starts.append(k)
            limits.append(k + 1)
            strides.append(1)
            squeeze.append(i)
    for i in range(len(ks), pval.ndim):
        starts.append(0)
        limits.append(pval.shape[i])
        strides.append(1)
    return tuple(starts), tuple(limits), tuple(strides), tuple(squeeze)


@functools.lru_cache(maxsize=256)
def _big_slice_fn(starts, limits, strides, squeeze):
    # one jitted fn per distinct slice spec: the lru_cache keeps the
    # function identity stable so jax's own jit cache hits on repeat
    return jax.jit(lambda x: jax.lax.squeeze(
        jax.lax.slice(x, starts, limits, strides), squeeze))


def _index_value(pval, key):
    """pval[key], with a large-offset escape hatch: eager jax lowers even
    static basic slices through dynamic_slice, whose runtime start
    indices are int32 — any offset past 2^31 overflows (nightly
    test_single_dim_beyond_2g_static_slice).  For overflow-risk BASIC
    keys the slice runs as a jitted lax.slice instead, where the bounds
    are static HLO attributes; the jitted fns are lru-cached per slice
    spec so repeated reads (view refreshes) compile once."""
    if max(pval.shape, default=0) <= _INT32_MAX:
        return pval[key]
    norm = _normalize_basic_key(pval, key)
    if norm is None:
        # advanced/negative-step reads would go through jnp's eager
        # int32 gather, whose clamp arithmetic overflows on a >2^31 dim
        # and returns WRONG DATA silently — refuse loudly instead (the
        # write path refuses symmetrically)
        raise MXNetError(
            "indexing an array with a dimension > 2^31-1 supports only "
            f"basic, positive-step keys (shape {pval.shape}, key "
            f"{key!r}); jax's int32 index path would silently return "
            "corrupt data — reshape to dims under 2^31 for advanced "
            "indexing")
    return _big_slice_fn(*norm)(pval)


_BIG_CHUNK = 2 ** 30


@functools.lru_cache(maxsize=256)
def _big_update_fn(shape, ax, norm):
    starts, limits, strides, squeeze = norm
    st, sp = starts[ax], limits[ax]
    inner_key = tuple(
        (0 if i in squeeze else slice(None)) if i == ax
        else (starts[i] if i in squeeze else slice(starts[i], limits[i]))
        for i in range(len(shape)))
    # indexed (target) shape of the assignment, for value broadcasting;
    # the big axis position among the value's (non-squeezed) dims
    idx_shape = tuple(limits[i] - starts[i] for i in range(len(shape))
                      if i not in squeeze)
    axpos = sum(1 for i in range(ax) if i not in squeeze)

    def fn(x, v):
        # static lax.slice bounds are int64-safe HLO attributes.  If the
        # targeted band itself still spans > 2^31 rows (the key did not
        # narrow the big axis, e.g. x[:, 2] = v), it is processed in
        # <= 2^30-row chunks so every scatter sees small dims only —
        # one band.at[].set past 2^31 would hit the exact int32 clamp
        # overflow this helper exists to avoid.
        pieces = [jax.lax.slice_in_dim(x, 0, st, axis=ax)]
        if sp - st <= _INT32_MAX:
            band = jax.lax.slice_in_dim(x, st, sp, axis=ax)
            pieces.append(band.at[inner_key].set(v))
        else:
            vb = jnp.broadcast_to(jnp.asarray(v), idx_shape)
            for cst in range(st, sp, _BIG_CHUNK):
                cen = min(cst + _BIG_CHUNK, sp)
                band = jax.lax.slice_in_dim(x, cst, cen, axis=ax)
                vchunk = jax.lax.slice_in_dim(vb, cst - st, cen - st,
                                              axis=axpos)
                pieces.append(band.at[inner_key].set(vchunk))
        pieces.append(jax.lax.slice_in_dim(x, sp, shape[ax], axis=ax))
        return jnp.concatenate(pieces, axis=ax)

    return jax.jit(fn)


def _update_value(pval, key, value):
    """Functional basic-key update (`pval.at[key].set(value)`) that stays
    CORRECT on arrays with a dimension past 2^31-1.

    jnp's eager scatter converts indices to int32 on the x32 default:
    past-2^31 offsets raise OverflowError, and — measurably worse — even
    SMALL-offset writes on a >2^31 dim are silently DROPPED (the clamp
    arithmetic overflows).  Here the huge axis is handled by static
    slicing the target band out, updating inside it (every dim small
    again), and concatenating back; non-basic keys on such arrays get a
    loud error instead of corruption."""
    if max(pval.shape, default=0) <= _INT32_MAX:
        return pval.at[key].set(value)
    norm = _normalize_basic_key(pval, key)
    big = [i for i, d in enumerate(pval.shape) if d > _INT32_MAX]
    if norm is None or len(big) != 1 \
            or any(s != 1 for s in norm[2]):
        raise MXNetError(
            "indexed assignment on an array with a dimension > 2^31-1 "
            "supports only basic, step-1 indexing with one oversized "
            f"dimension (shape {pval.shape}, key {key!r}); jax's int32 "
            "index path would silently corrupt this write — reshape to "
            "dims under 2^31 for advanced indexing")
    return _big_update_fn(pval.shape, big[0], norm)(pval, value)


def _resolve_dtype(dtype):
    if dtype in _DTYPE_ALIASES:
        return _DTYPE_ALIASES[dtype]
    return jnp.dtype(dtype)


def _ctx_of_jax(arr) -> Context:
    try:
        dev = list(arr.devices())[0]
    except Exception:
        return current_context()
    # Context.device_id is a LOCAL (per-process) position, matching
    # Context.jax_device's local_devices indexing — dev.id is a GLOBAL id
    # and the two differ on non-zero workers of a multi-process job
    if dev.platform == "cpu":
        local = jax.local_devices(backend="cpu")
        return cpu(next((i for i, d in enumerate(local) if d == dev), 0))
    from ..context import tpu

    local = [d for d in jax.local_devices() if d.platform != "cpu"]
    return tpu(next((i for i, d in enumerate(local) if d == dev), 0))


class NDArray:
    """An imperative, device-resident n-dimensional array.

    View semantics (ref: NDArray::Slice/Reshape/At aliasing,
    src/ndarray/ndarray.cc): basic `x[i]`/`x[a:b]`, `x.reshape(...)`,
    `x.slice(...)`, `x.slice_axis(...)` and `x.at(i)` return VIEWS in
    eager mode — writes through a view land in the base array and are
    visible to every overlapping view, like the reference.  Under the
    hood jax arrays are immutable, so a view carries (base, index-spec):
    reads re-derive lazily from the base's version counter, and writes
    rewrite the base functionally (`base.at[key].set`).  Under
    autograd.record these methods return recorded op outputs instead
    (no aliasing) so the tape stays sound."""

    __slots__ = ("_buf", "_ctx", "_ag_grad_req", "_ag_grad", "_ag_node",
                 "_deferred_init", "_base", "_vspec", "_version",
                 "_pversion", "__weakref__")

    # make NDArray win over numpy in mixed operators
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None):
        self._base = None
        self._vspec = None
        self._version = 0
        self._pversion = -1
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            data = jnp.asarray(np.asarray(data), dtype=dtype)
        elif dtype is not None and data.dtype != jnp.dtype(dtype):
            data = data.astype(dtype)
        if ctx is not None and not isinstance(data, jax.core.Tracer):
            # (tracers have no placement — the enclosing trace decides)
            dev = ctx.jax_device
            if (isinstance(data, jax.Array)
                    and not data.is_fully_addressable):
                pass  # global SPMD value: keeps its mesh sharding; the
                #       single-device ctx is advisory only
            elif getattr(data, "devices", None) \
                    and list(data.devices()) != [dev]:
                data = jax.device_put(data, dev)
            elif not isinstance(data, jax.Array):
                data = jax.device_put(data, dev)
        self._buf = data
        self._ctx = ctx or _ctx_of_jax(data)
        self._ag_grad_req = "null"
        self._ag_grad = None
        self._ag_node = None

    # ---- view plumbing ---------------------------------------------------
    @property
    def _data(self):
        """The current jax value; views re-derive from their base when
        the base has changed since the last read."""
        if self._base is not None:
            self._refresh()
        return self._buf

    @_data.setter
    def _data(self, value):
        base = self._base
        if base is None:
            self._buf = value
            self._version += 1
            return
        kind, arg = self._vspec
        pval = base._data  # refreshes the parent chain first
        value = jnp.asarray(value)
        if kind == "index":
            base._data = _update_value(pval, arg,
                                        value.astype(pval.dtype))
        else:  # reshape
            base._data = value.astype(pval.dtype).reshape(pval.shape)
        self._pversion = -1  # force re-derive on next read
        self._refresh()

    def _refresh(self):
        parent = self._base
        pval = parent._data  # recursive: refreshes the whole chain
        if self._pversion == parent._version:
            return
        kind, arg = self._vspec
        self._buf = _index_value(pval, arg) if kind == "index" \
            else pval.reshape(arg)
        self._pversion = parent._version
        self._version += 1

    def _make_view(self, kind: str, arg) -> "NDArray":
        out = NDArray.__new__(NDArray)
        out._base = self
        out._vspec = (kind, arg)
        out._version = 0
        out._pversion = -1
        out._ctx = self._ctx
        out._ag_grad_req = "null"
        out._ag_grad = None
        out._ag_node = None
        pval = self._data
        out._buf = _index_value(pval, arg) if kind == "index" \
            else pval.reshape(arg)
        out._pversion = self._version
        return out

    @property
    def is_view(self) -> bool:
        return self._base is not None

    # ---- core properties -------------------------------------------------
    @property
    def data(self):
        """The underlying jax.Array."""
        return self._data

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(str(self._data.dtype)) if self._data.dtype != jnp.bfloat16 \
            else self._data.dtype

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ctx(self) -> Context:
        return self._ctx

    context = ctx

    @property
    def stype(self) -> str:
        return "default"

    def tostype(self, stype: str) -> "NDArray":
        """Convert storage type (ref: ndarray.py::tostype / cast_storage)."""
        if stype == "default":
            return self
        from .sparse import cast_storage

        return cast_storage(self, stype)

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __repr__(self):
        dims = "x".join(map(str, self.shape))
        return f"\n{np.asarray(self.asnumpy())}\n<NDArray {dims} @{self._ctx}>"

    def __bool__(self):
        if self.size != 1:
            raise MXNetError("The truth value of an NDArray with multiple "
                             "elements is ambiguous.")
        return bool(self.asnumpy().item())

    # ---- sync points (ref: Engine::WaitForVar / asnumpy) ----------------
    def asnumpy(self) -> np.ndarray:
        d = self._data
        if (isinstance(d, jax.Array) and not d.is_fully_addressable
                and d.sharding.is_fully_replicated):
            # multi-process mesh: a replicated global array cannot be
            # fetched whole, but any local shard IS the global value
            return np.asarray(d.addressable_shards[0].data)
        return np.asarray(jax.device_get(d))

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().item()

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        jax.block_until_ready(self._data)
        return self

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # ---- conversions / movement ----------------------------------------
    def astype(self, dtype, copy: bool = True) -> "NDArray":
        dt = _resolve_dtype(dtype)
        if not copy and self._data.dtype == dt:
            return self
        return self._op("cast", dtype=str(jnp.dtype(dt)))

    def copy(self) -> "NDArray":
        return NDArray(jnp.copy(self._data), ctx=self._ctx)

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        if isinstance(other, Context):
            return self.as_in_context(other)
        other._data = jax.device_put(self._data, other.ctx.jax_device)
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self._ctx:
            return self
        return NDArray(jax.device_put(self._data, ctx.jax_device), ctx=ctx)

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def tolist(self):
        return self.asnumpy().tolist()

    # ---- autograd hooks --------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None):
        """ref: ndarray.py::attach_grad — allocate grad & mark as leaf."""
        self._ag_grad_req = grad_req
        self._ag_grad = NDArray(jnp.zeros(self.shape, self._data.dtype),
                                ctx=self._ctx) if grad_req != "null" else None
        self._ag_node = None

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._ag_grad

    @property
    def grad_req(self) -> str:
        return self._ag_grad_req

    def zero_grad(self):
        if self._ag_grad is not None:
            self._ag_grad._data = jnp.zeros_like(self._ag_grad._data)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def detach(self) -> "NDArray":
        out = NDArray(self._data, ctx=self._ctx)
        return out

    # ---- op plumbing -----------------------------------------------------
    def _op(self, name, *others, **attrs):
        from ..ops.registry import invoke

        return invoke(name, self, *others, **attrs)

    def _rop(self, name, other, **attrs):
        from ..ops.registry import invoke

        return invoke(name, other, self, **attrs)

    @staticmethod
    def _pre(other):
        """Normalise the rhs of a binary op: scalars stay python scalars
        (baked into the jitted executable as weak-typed consts)."""
        if isinstance(other, NDArray):
            return other
        if isinstance(other, numeric_types):
            return other
        return NDArray(other)

    # arithmetic — true-scalar rhs routes to *_scalar ops so the executable
    # cache keys on the scalar value via attrs (matches reference
    # _plus_scalar etc.), keeping shapes static; array-likes are wrapped.
    def _binary(self, scalar_op, bcast_op, o):
        if isinstance(o, numeric_types):
            return self._op(scalar_op, scalar=o)
        return self._op(bcast_op, NDArray._pre(o))

    def __add__(self, o):
        return self._binary("_plus_scalar", "broadcast_add", o)

    def __radd__(self, o):
        return self.__add__(o)

    def __sub__(self, o):
        return self._binary("_minus_scalar", "broadcast_sub", o)

    def __rsub__(self, o):
        if isinstance(o, numeric_types):
            return self._op("_rminus_scalar", scalar=o)
        return NDArray._pre(o)._binary("_minus_scalar", "broadcast_sub", self)

    def __mul__(self, o):
        return self._binary("_mul_scalar", "broadcast_mul", o)

    def __rmul__(self, o):
        return self.__mul__(o)

    def __truediv__(self, o):
        return self._binary("_div_scalar", "broadcast_div", o)

    def __rtruediv__(self, o):
        if isinstance(o, numeric_types):
            return self._op("_rdiv_scalar", scalar=o)
        return NDArray._pre(o)._binary("_div_scalar", "broadcast_div", self)

    def __mod__(self, o):
        return self._binary("_mod_scalar", "broadcast_mod", o)

    def __pow__(self, o):
        return self._binary("_power_scalar", "broadcast_power", o)

    def __rpow__(self, o):
        if isinstance(o, numeric_types):
            return self._op("_rpower_scalar", scalar=o)
        return NDArray._pre(o)._binary("_power_scalar", "broadcast_power", self)

    def __neg__(self):
        return self._op("negative")

    def __abs__(self):
        return self._op("abs")

    def __matmul__(self, o):
        return self._op("matmul", NDArray._pre(o))

    def _inplace(self, r: "NDArray") -> "NDArray":
        # carry the tape node so gradients flow through in-place updates
        self._data = r._data
        self._ag_node = r._ag_node
        return self

    def __iadd__(self, o):
        return self._inplace(self + o)

    def __isub__(self, o):
        return self._inplace(self - o)

    def __imul__(self, o):
        return self._inplace(self * o)

    def __itruediv__(self, o):
        return self._inplace(self / o)

    # comparisons
    def __eq__(self, o):
        if o is None:
            return False
        return self._binary("_equal_scalar", "broadcast_equal", o)

    def __ne__(self, o):
        if o is None:
            return True
        return self._binary("_not_equal_scalar", "broadcast_not_equal", o)

    def __gt__(self, o):
        return self._binary("_greater_scalar", "broadcast_greater", o)

    def __ge__(self, o):
        return self._binary("_greater_equal_scalar", "broadcast_greater_equal", o)

    def __lt__(self, o):
        return self._binary("_lesser_scalar", "broadcast_lesser", o)

    def __le__(self, o):
        return self._binary("_lesser_equal_scalar", "broadcast_lesser_equal", o)

    __hash__ = object.__hash__

    @staticmethod
    def _eager_views() -> bool:
        """Views only outside autograd recording (the tape needs real op
        nodes for gradient flow; ref: autograd + view interaction)."""
        from ..autograd import is_recording

        return not is_recording()

    # ---- shape ops -------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        shape = tuple(shape)
        concrete = self._concrete_shape(shape)
        if concrete is not None and self._eager_views():
            return self._make_view("reshape", concrete)
        return self._op("reshape", shape=shape)

    def _concrete_shape(self, shape):
        """Resolve every reference reshape code — 0 (copy dim), -1
        (infer), -2 (copy rest), -3 (merge two), -4 (split) — against
        the current shape, so aliasing does not depend on how the shape
        is spelled.  None when unresolvable (falls to the op path)."""
        cur = list(self.shape)
        shape = list(shape)
        out = []
        si = k = 0
        try:
            while k < len(shape):
                s = shape[k]
                if not isinstance(s, (int, np.integer)):
                    return None
                s = int(s)
                if s == 0:
                    out.append(cur[si]); si += 1
                elif s == -2:
                    out.extend(cur[si:]); si = len(cur)
                elif s == -3:
                    out.append(cur[si] * cur[si + 1]); si += 2
                elif s == -4:
                    a, b = int(shape[k + 1]), int(shape[k + 2])
                    if a == -1:
                        a = cur[si] // b
                    if b == -1:
                        b = cur[si] // a
                    out.extend([a, b]); si += 1; k += 2
                elif s < -4:
                    return None
                else:
                    out.append(s)
                    if s != -1:
                        si += 1
                k += 1
        except (IndexError, ZeroDivisionError):
            return None
        total = 1
        for d in cur:
            total *= d
        if out.count(-1) == 1:
            known = 1
            for d in out:
                if d != -1:
                    known *= d
            if known == 0 or total % known:
                return None
            out[out.index(-1)] = total // known
        elif -1 in out:
            return None
        prod = 1
        for d in out:
            prod *= d
        return tuple(out) if prod == total else None

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return self._op("transpose", axes=tuple(axes) if axes else None)

    @property
    def T(self):
        return self.transpose()

    def flatten(self):
        return self._op("flatten")

    def expand_dims(self, axis):
        return self._op("expand_dims", axis=axis)

    def squeeze(self, axis=None):
        return self._op("squeeze", axis=axis)

    def broadcast_to(self, shape):
        return self._op("broadcast_to", shape=tuple(shape))

    def broadcast_like(self, other):
        return self.broadcast_to(other.shape)

    def swapaxes(self, a1, a2):
        return self._op("swapaxes", dim1=a1, dim2=a2)

    def split(self, num_outputs, axis=0):
        from ..ops.registry import invoke

        return invoke("split", self, num_outputs=num_outputs, axis=axis)

    def tile(self, reps):
        return self._op("tile", reps=tuple(reps) if isinstance(reps, (list, tuple)) else (reps,))

    def repeat(self, repeats, axis=None):
        return self._op("repeat", repeats=repeats, axis=axis)

    def pad(self, mode="constant", pad_width=None, constant_value=0):
        return self._op("pad", mode=mode, pad_width=tuple(pad_width),
                        constant_value=constant_value)

    def slice(self, begin, end, step=None):
        if self._eager_views():
            key = tuple(slice(b, e, s) for b, e, s in
                        zip(begin, end, step or (None,) * len(begin)))
            return self._make_view("index", key)
        return self._op("slice", begin=tuple(begin), end=tuple(end),
                        step=tuple(step) if step else None)

    def slice_axis(self, axis, begin, end):
        if self._eager_views():
            ax = axis + self.ndim if axis < 0 else axis
            key = tuple(slice(None) for _ in range(ax)) + \
                (slice(begin, end),)
            return self._make_view("index", key)
        return self._op("slice_axis", axis=axis, begin=begin, end=end)

    def at(self, idx: int):
        """View of row `idx` sharing storage (ref: NDArray::At); a
        tape-backed copy under autograd.record, like the other views."""
        if self._eager_views():
            return self._make_view("index", int(idx))
        row = self._op("slice_axis", axis=0, begin=int(idx),
                       end=int(idx) + 1)
        return row.reshape(self.shape[1:])

    def take(self, indices, axis=0, mode="clip"):
        return self._op("take", NDArray._pre(indices), axis=axis, mode=mode)

    def pick(self, index, axis=-1, keepdims=False):
        return self._op("pick", NDArray._pre(index), axis=axis, keepdims=keepdims)

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        return self._op("one_hot", depth=depth, on_value=on_value,
                        off_value=off_value)

    # ---- reductions ------------------------------------------------------
    def sum(self, axis=None, keepdims=False):
        return self._op("sum", axis=_norm_axis(axis), keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._op("mean", axis=_norm_axis(axis), keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return self._op("max", axis=_norm_axis(axis), keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return self._op("min", axis=_norm_axis(axis), keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._op("prod", axis=_norm_axis(axis), keepdims=keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return self._op("norm", ord=ord, axis=_norm_axis(axis), keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        return self._op("argmax", axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        return self._op("argmin", axis=axis, keepdims=keepdims)

    # elementwise conveniences
    def exp(self):
        return self._op("exp")

    def log(self):
        return self._op("log")

    def sqrt(self):
        return self._op("sqrt")

    def square(self):
        return self._op("square")

    def relu(self):
        return self._op("relu")

    def sigmoid(self):
        return self._op("sigmoid")

    def tanh(self):
        return self._op("tanh")

    def softmax(self, axis=-1):
        return self._op("softmax", axis=axis)

    def log_softmax(self, axis=-1):
        return self._op("log_softmax", axis=axis)

    def clip(self, a_min, a_max):
        return self._op("clip", a_min=a_min, a_max=a_max)

    def abs(self):
        return self._op("abs")

    def round(self):
        return self._op("round")

    def sign(self):
        return self._op("sign")

    def floor(self):
        return self._op("floor")

    def ceil(self):
        return self._op("ceil")

    def zeros_like(self):
        return self._op("zeros_like")

    def ones_like(self):
        return self._op("ones_like")

    def sort(self, axis=-1, is_ascend=True):
        return self._op("sort", axis=axis, is_ascend=is_ascend)

    def argsort(self, axis=-1, is_ascend=True, dtype="float32"):
        return self._op("argsort", axis=axis, is_ascend=is_ascend,
                        dtype=dtype)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False,
             dtype="float32"):
        return self._op("topk", axis=axis, k=k, ret_typ=ret_typ,
                        is_ascend=is_ascend, dtype=dtype)

    def slice_like(self, shape_like, axes=()):
        return self._op("slice_like", NDArray._pre(shape_like),
                        axes=tuple(axes))

    def dot(self, other, transpose_a=False, transpose_b=False):
        return self._op("dot", NDArray._pre(other), transpose_a=transpose_a,
                        transpose_b=transpose_b)

    @staticmethod
    def _is_basic_key(key) -> bool:
        # np.integer counts: x[np.argmax(...)] must alias exactly like
        # x[int(...)] — the index dtype must not flip the contract
        if isinstance(key, (int, np.integer, slice)) or key is Ellipsis:
            return True
        if isinstance(key, tuple):
            return all(isinstance(k, (int, np.integer, slice))
                       or k is Ellipsis for k in key)
        return False

    # ---- indexing --------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, NDArray):
            key = key.data
        if self._is_basic_key(key) and self._eager_views():
            # basic indexing aliases the base (ref: NDArray::Slice/At)
            return self._make_view("index", key)
        out = _index_value(self._data, key)
        return NDArray(out, ctx=self._ctx)

    def __setitem__(self, key, value):
        """Sliced assignment — functional under the hood (x.at[key].set)."""
        if isinstance(key, NDArray):
            key = key.data
        if isinstance(value, NDArray):
            value = value.data
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            v = jnp.broadcast_to(jnp.asarray(value, self._data.dtype), self.shape)
            self._data = jax.device_put(v, self._ctx.jax_device)
        else:
            self._data = _update_value(
                self._data, key, jnp.asarray(value, self._data.dtype))

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return int(axis)


def wrap_outputs(out, ctx: Optional[Context]):
    """Wrap a pure-fn result (array or tuple/list of arrays) into NDArray(s)."""
    if isinstance(out, (tuple, list)):
        return [NDArray(o, ctx=ctx) for o in out]
    return NDArray(out, ctx=ctx)


def from_jax(arr, ctx: Optional[Context] = None) -> NDArray:
    return NDArray(arr, ctx=ctx)


# ---- creation functions (ref: ndarray creation API) ----------------------

def _creation_ctx(ctx):
    return ctx if ctx is not None else current_context()


def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    if isinstance(source, NDArray):
        out = source.astype(dtype) if dtype else source.copy()
        return out.as_in_context(ctx) if ctx is not None else out
    src = np.asarray(source)
    if dtype is None:
        # TPU-native narrowing defaults: f64->f32, i64->i32 (no x64 mode)
        if src.dtype == np.float64:
            dtype = jnp.float32
        elif src.dtype == np.int64:
            dtype = jnp.int32
        else:
            dtype = src.dtype
    ctx = _creation_ctx(ctx)
    return NDArray(jax.device_put(jnp.asarray(src, dtype=dtype), ctx.jax_device), ctx=ctx)


def zeros(shape, ctx=None, dtype=None) -> NDArray:
    ctx = _creation_ctx(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(jax.device_put(jnp.zeros(shape, _resolve_dtype(dtype)),
                                  ctx.jax_device), ctx=ctx)


def ones(shape, ctx=None, dtype=None) -> NDArray:
    ctx = _creation_ctx(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(jax.device_put(jnp.ones(shape, _resolve_dtype(dtype)),
                                  ctx.jax_device), ctx=ctx)


def full(shape, val, ctx=None, dtype=None) -> NDArray:
    ctx = _creation_ctx(ctx)
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(jax.device_put(jnp.full(shape, val, _resolve_dtype(dtype)),
                                  ctx.jax_device), ctx=ctx)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None) -> NDArray:
    ctx = _creation_ctx(ctx)
    out = jnp.arange(start, stop, step, _resolve_dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return NDArray(jax.device_put(out, ctx.jax_device), ctx=ctx)


def concatenate(arrays: Sequence[NDArray], axis: int = 0) -> NDArray:
    from ..ops.registry import invoke

    return invoke("concat", *arrays, dim=axis)


def stack(*arrays, axis: int = 0) -> NDArray:
    from ..ops.registry import invoke

    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    return invoke("stack", *arrays, axis=axis)
