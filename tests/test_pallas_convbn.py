"""Fused Conv+BN+ReLU unit (ops/pallas_convbn.py) vs the op-granular path.

Oracle strategy (SURVEY.md §4): the composed XLA ops (Convolution +
explicit affine/relu/stat math) are the reference; the fused unit must
match in forward values, BN statistics, and every gradient.  The Pallas
kernel itself runs under MXNET_PALLAS_INTERPRET on the CPU backend.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.ops import pallas_convbn as pcb


def _rand(shape, dtype=np.float32, scale=1.0):
    return (np.random.RandomState(hash(shape) % 2**31).randn(*shape)
            * scale).astype(dtype)


def _ref_unit(x, w, sc, bi, sh, kernel, stride, pad, act_in):
    """Composed op-granular math (the oracle)."""
    if act_in:
        u = (x.astype(jnp.float32) * sc.reshape(1, 1, 1, -1)
             + bi.reshape(1, 1, 1, -1))
        u = jnp.maximum(u, 0.0).astype(x.dtype)
    else:
        u = x
    y = jax.lax.conv_general_dilated(
        u, jnp.transpose(w, (2, 3, 1, 0)), stride,
        [(pad[0], pad[0]), (pad[1], pad[1])],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    yf = y.astype(jnp.float32)
    s1 = jnp.sum(yf, axis=(0, 1, 2))
    d = yf - sh.reshape(1, 1, 1, -1)
    s2 = jnp.sum(d * d, axis=(0, 1, 2))
    return y, s1, s2


CASES = [
    # (shape NHWC, Co, kernel, stride, pad, act_in)
    ((4, 8, 8, 16), 16, (3, 3), (1, 1), (1, 1), True),
    ((4, 8, 8, 16), 32, (1, 1), (1, 1), (0, 0), True),
    ((4, 9, 9, 8), 16, (1, 1), (2, 2), (0, 0), False),
    ((2, 8, 8, 8), 8, (3, 3), (2, 2), (1, 1), True),
    ((1, 7, 7, 24), 12, (3, 3), (1, 1), (1, 1), False),
]


@pytest.mark.parametrize("case", CASES)
def test_fallback_matches_composed(case):
    shape, co, kernel, stride, pad, act_in = case
    x = jnp.asarray(_rand(shape))
    w = jnp.asarray(_rand((co, shape[-1]) + kernel, scale=0.2))
    sc = jnp.asarray(_rand((shape[-1],)) ** 2 + 0.5)
    bi = jnp.asarray(_rand((shape[-1],)))
    sh = jnp.asarray(_rand((co,)))
    y, s1, s2 = pcb.fused_conv_unit(x, w, sc, bi, sh, kernel=kernel,
                                    stride=stride, pad=pad, act_in=act_in)
    yr, s1r, s2r = _ref_unit(x, w, sc, bi, sh, kernel, stride, pad, act_in)
    np.testing.assert_allclose(y, yr, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(s1, s1r, rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(s2, s2r, rtol=1e-4, atol=1e-3)


@pytest.mark.parametrize("case", CASES)
def test_pallas_interpret_matches_fallback(case, monkeypatch):
    shape, co, kernel, stride, pad, act_in = case
    monkeypatch.setenv("MXNET_PALLAS_INTERPRET", "1")
    x = jnp.asarray(_rand(shape))
    w = jnp.asarray(_rand((co, shape[-1]) + kernel, scale=0.2))
    sc = jnp.asarray(_rand((shape[-1],)) ** 2 + 0.5)
    bi = jnp.asarray(_rand((shape[-1],)))
    sh = jnp.asarray(_rand((co,)))
    y, s1, s2 = pcb._pallas_unit(x, w, sc, bi, sh, kernel=kernel,
                                 stride=stride, pad=pad, act_in=act_in,
                                 want_stats=True)
    yr, s1r, s2r = _ref_unit(x, w, sc, bi, sh, kernel, stride, pad, act_in)
    np.testing.assert_allclose(y, yr, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(s1, s1r, rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(s2, s2r, rtol=1e-4, atol=1e-3)


def _per_image_bytes(h, w, ci, ho, wo, co, itemsize=2):
    # mirror of the tap-accumulation working set in pcb._batch_tile
    return ((h + 2) * (w + 2) * ci * itemsize + ho * wo * co * 4
            + ho * wo * ci * itemsize + 2 * h * w * ci * itemsize
            + 2 * ho * wo * co * itemsize)


def test_batch_tile_divides_and_respects_budget():
    # 56x56-stage image: a few MB — must be admitted (nb >= 1) and any
    # nb > 1 must stay inside the budget
    nb = pcb._batch_tile(256, 56, 56, 64, 56, 56, 64)
    assert 256 % nb == 0 and nb >= 1
    assert nb == 1 or nb * _per_image_bytes(56, 56, 64, 56, 56, 64) \
        <= pcb._COLS_BUDGET_BYTES
    nb = pcb._batch_tile(256, 7, 7, 512, 7, 7, 512)
    assert 256 % nb == 0 and nb >= 2
    # 1x1 expansion conv: the fp32 accumulator + y blocks (co=2048)
    # dominate the working set — the budget must count them
    nb = pcb._batch_tile(256, 7, 7, 512, 7, 7, 2048)
    assert nb == 1 or nb * _per_image_bytes(7, 7, 512, 7, 7, 2048) \
        <= pcb._COLS_BUDGET_BYTES
    # nb must divide n even for odd n
    assert pcb._batch_tile(3, 8, 8, 16, 8, 8, 16) in (1, 3)


@pytest.mark.parametrize("act_in", [True, False])
def test_gradients_match_composed(act_in):
    shape, co, kernel, stride, pad = (2, 6, 6, 8), 8, (3, 3), (1, 1), (1, 1)
    x = jnp.asarray(_rand(shape))
    w = jnp.asarray(_rand((co, shape[-1]) + kernel, scale=0.2))
    sc = jnp.asarray(_rand((shape[-1],)) ** 2 + 0.5)
    bi = jnp.asarray(_rand((shape[-1],)))
    sh = jnp.asarray(_rand((co,)))

    # scalar losses touching y, s1 AND s2 so every cotangent path is live
    def loss_fused(x, w, sc, bi):
        y, s1, s2 = pcb.fused_conv_unit(x, w, sc, bi, sh, kernel=kernel,
                                        stride=stride, pad=pad,
                                        act_in=act_in)
        return (jnp.sum(y * y) + jnp.sum(jnp.sin(s1)) + jnp.sum(s2 * 0.1))

    def loss_ref(x, w, sc, bi):
        y, s1, s2 = _ref_unit(x, w, sc, bi, sh, kernel, stride, pad, act_in)
        return (jnp.sum(y * y) + jnp.sum(jnp.sin(s1)) + jnp.sum(s2 * 0.1))

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, w, sc, bi)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, w, sc, bi)
    for a, b, name in zip(gf, gr, ("x", "w", "scale", "bias")):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4,
                                   err_msg=f"grad {name}")


def test_shift_gets_zero_gradient():
    shape, co = (2, 4, 4, 8), 8
    x = jnp.asarray(_rand(shape))
    w = jnp.asarray(_rand((co, 8, 1, 1), scale=0.2))
    sh = jnp.asarray(_rand((co,)))

    def loss(sh):
        _, _, s2 = pcb.fused_conv_unit(x, w, None, None, sh)
        return jnp.sum(s2)

    np.testing.assert_allclose(jax.grad(loss)(sh), np.zeros(co), atol=0)


def test_defaults_are_identity():
    x = jnp.asarray(_rand((2, 4, 4, 8)))
    w = jnp.asarray(_rand((16, 8, 1, 1), scale=0.2))
    y, s1, s2 = pcb.fused_conv_unit(x, w)
    yr, s1r, s2r = _ref_unit(x, w, jnp.ones(8), jnp.zeros(8), jnp.zeros(16),
                             (1, 1), (1, 1), (0, 0), False)
    np.testing.assert_allclose(y, yr, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(s2, s2r, rtol=1e-4, atol=1e-3)


def test_multi_device_mesh_selects_sharded_pallas(monkeypatch):
    """Under a multi-device mesh the fused unit now takes the
    shard_map-wrapped per-shard Pallas kernel (round-4 verdict item #2:
    the flagship optimization must survive dp>1); a single-device or
    no-mesh trace keeps the direct Pallas path; a batch that doesn't
    divide the dp shards falls back to XLA.  Also pins that
    SPMDTrainer's traced step runs under ITS mesh scope even when
    step() is called outside `with mesh:`."""
    import mxnet_tpu as mx
    from mxnet_tpu import parallel

    monkeypatch.setenv("MXNET_PALLAS_INTERPRET", "1")
    monkeypatch.setitem(pcb._STATE, "enabled", None)

    calls = {"pallas": 0, "sharded": 0}
    real = pcb._pallas_unit
    real_sh = pcb._pallas_unit_sharded

    def spy(*a, **k):
        calls["pallas"] += 1
        return real(*a, **k)

    def spy_sh(*a, **k):
        calls["sharded"] += 1
        return real_sh(*a, **k)

    monkeypatch.setattr(pcb, "_pallas_unit", spy)
    monkeypatch.setattr(pcb, "_pallas_unit_sharded", spy_sh)
    x = jnp.asarray(_rand((2, 4, 4, 8)))
    w = jnp.asarray(_rand((8, 8, 1, 1), scale=0.2))

    pcb.fused_conv_unit(x, w)   # warm-up (probe + first call both spy)
    base = calls["pallas"]
    pcb.fused_conv_unit(x, w)                      # no mesh: direct Pallas
    assert calls["pallas"] == base + 1 and calls["sharded"] == 0
    with parallel.make_mesh(dp=2):
        pcb.fused_conv_unit(x, w)                  # dp=2: sharded Pallas
    assert calls["sharded"] == 1
    with parallel.make_mesh(dp=1):
        pcb.fused_conv_unit(x, w)                  # size-1 mesh: direct
    assert calls["pallas"] >= base + 2 and calls["sharded"] == 1
    with parallel.make_mesh(dp=8):
        sh_before = calls["sharded"]
        # batch 2 does not divide 8 dp shards -> XLA fallback, no crash
        y, _, _ = pcb.fused_conv_unit(x, w)
        assert y.shape == (2, 4, 4, 8)
    assert calls["sharded"] == sh_before

    # trainer path: mesh scope is pushed by the trace itself, so the
    # sharded kernel engages even when step() runs outside `with mesh:`
    mesh = parallel.make_mesh(dp=2)
    assert parallel.current_mesh() is None
    from mxnet_tpu.gluon.block import HybridBlock

    class Step(HybridBlock):
        def hybrid_forward(self, F, a):
            y, _s1, _s2 = F.FusedConvUnit(a, jnp.asarray(w))
            return y.astype(jnp.float32).mean()

    blk = Step()
    blk.initialize(ctx=mx.cpu())

    class _Id:
        def __call__(self, out, *l):
            return out

    tr = parallel.SPMDTrainer(blk, _Id(), "sgd", {"learning_rate": 0.1},
                              mesh=mesh, n_labels=0)
    before = calls["sharded"]
    tr.step(tr._place(np.asarray(x), None))        # OUTSIDE with mesh:
    assert calls["sharded"] > before


@pytest.mark.parametrize("axes", [{"dp": 8}, {"dp": 2, "tp": 2, "sp": 2},
                                  {"fsdp": 4, "tp": 2}])
def test_sharded_pallas_matches_fallback_full(axes, monkeypatch):
    """Round-4 verdict item #2 'Done' criterion: fused == unfused to
    tolerance — outputs, ALL gradients, and the BN-stat aux — under the
    8-device CPU mesh in interpret mode, across dp-only, mixed, and
    fsdp batch-sharding layouts."""
    from mxnet_tpu import parallel

    shape, co, kernel, stride, pad = (8, 8, 8, 16), 32, (3, 3), (1, 1), (1, 1)
    x = jnp.asarray(_rand(shape))
    w = jnp.asarray(_rand((co, shape[-1]) + kernel, scale=0.2))
    sc = jnp.asarray(_rand((shape[-1],)) ** 2 + 0.5)
    bi = jnp.asarray(_rand((shape[-1],)))
    sh = jnp.asarray(_rand((co,)))

    def loss(x, w, sc, bi, sh):
        y, s1, s2 = pcb.fused_conv_unit(
            x, w, sc, bi, sh, kernel=kernel, stride=stride, pad=pad,
            act_in=True)
        return ((y.astype(jnp.float32) ** 2).sum()
                + (s1 * s1).sum() * 1e-3 + s2.sum() * 1e-3)

    def all_outputs():
        y, s1, s2 = pcb.fused_conv_unit(
            x, w, sc, bi, sh, kernel=kernel, stride=stride, pad=pad,
            act_in=True)
        g = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w, sc, bi, sh)
        return y, s1, s2, g

    monkeypatch.setenv("MXNET_USE_PALLAS", "0")
    monkeypatch.setitem(pcb._STATE, "enabled", None)
    yr, s1r, s2r, gr = all_outputs()

    monkeypatch.setenv("MXNET_USE_PALLAS", "1")
    monkeypatch.setenv("MXNET_PALLAS_INTERPRET", "1")
    monkeypatch.setitem(pcb._STATE, "enabled", None)
    with parallel.make_mesh(**axes):
        yf, s1f, s2f, gf = all_outputs()

    np.testing.assert_allclose(yf, yr, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(s1f, s1r, rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(s2f, s2r, rtol=1e-4, atol=1e-3)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


BWD_CASES = [
    # (shape NHWC, Co, kernel, pad, act_in, want_stats)
    ((4, 8, 8, 16), 16, (3, 3), (1, 1), True, True),
    ((2, 8, 8, 8), 24, (1, 1), (0, 0), True, True),
    ((2, 6, 6, 8), 8, (3, 3), (1, 1), False, True),
    ((2, 6, 6, 8), 8, (3, 3), (1, 1), True, False),
]


@pytest.mark.parametrize("case", BWD_CASES)
def test_pallas_bwd_matches_xla_bwd(case, monkeypatch):
    """MXNET_FUSED_CONVBN_BWD=1 single-pass backward kernel == the XLA
    linear_transpose backward for every gradient, with a spy proving
    the Pallas path actually engaged (an exception inside it silently
    falls back, which would make this comparison vacuous)."""
    shape, co, kernel, pad, act_in, want_stats = case
    x = jnp.asarray(_rand(shape))
    w = jnp.asarray(_rand((co, shape[-1]) + kernel, scale=0.2))
    sc = jnp.asarray(_rand((shape[-1],)) ** 2 + 0.5)
    bi = jnp.asarray(_rand((shape[-1],)))
    sh = jnp.asarray(_rand((co,)))

    def loss(x, w, sc, bi):
        y, s1, s2 = pcb.fused_conv_unit(
            x, w, sc, bi, sh, kernel=kernel, stride=(1, 1), pad=pad,
            act_in=act_in, want_stats=want_stats)
        return ((y.astype(jnp.float32) ** 2).sum()
                + (s1 * s1).sum() * 1e-3 + s2.sum() * 1e-3)

    monkeypatch.setenv("MXNET_PALLAS_INTERPRET", "1")
    monkeypatch.setitem(pcb._STATE, "enabled", None)

    monkeypatch.setenv("MXNET_FUSED_CONVBN_BWD", "0")
    ref = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w, sc, bi)

    calls = {"bwd": 0}
    real = pcb._pallas_unit_bwd

    def spy(*a, **k):
        calls["bwd"] += 1
        return real(*a, **k)

    monkeypatch.setattr(pcb, "_pallas_unit_bwd", spy)
    monkeypatch.setenv("MXNET_FUSED_CONVBN_BWD", "1")
    got = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w, sc, bi)
    assert calls["bwd"] == 1

    for name, a, b in zip(("gx", "dw", "gscale", "gbias"), got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"{name}")


def test_pallas_bwd_strided_falls_back(monkeypatch):
    """Strided units keep the XLA backward even with the knob on (the
    dgrad of a strided conv needs interior-dilated pads)."""
    monkeypatch.setenv("MXNET_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("MXNET_FUSED_CONVBN_BWD", "1")
    monkeypatch.setitem(pcb._STATE, "enabled", None)
    calls = {"bwd": 0}
    real = pcb._pallas_unit_bwd

    def spy(*a, **k):
        calls["bwd"] += 1
        return real(*a, **k)

    monkeypatch.setattr(pcb, "_pallas_unit_bwd", spy)
    x = jnp.asarray(_rand((2, 8, 8, 8)))
    w = jnp.asarray(_rand((8, 8, 3, 3), scale=0.2))

    def loss(x, w):
        y, s1, s2 = pcb.fused_conv_unit(x, w, kernel=(3, 3),
                                        stride=(2, 2), pad=(1, 1))
        return (y.astype(jnp.float32) ** 2).sum() + s2.sum() * 1e-3

    g = jax.grad(loss, argnums=(0, 1))(x, w)
    assert calls["bwd"] == 0
    assert all(np.isfinite(np.asarray(t)).all() for t in g)


def test_pallas_bwd_multi_program_accumulation(monkeypatch):
    """Force nb < n (tiny VMEM budget) so the cross-program accumulator
    path — pl.when zero-init at program 0, += on dw/gscale/gbias across
    the sequential grid — is actually executed, and still matches the
    XLA backward.  The default budget admits every BWD_CASES batch in
    one program, which would leave that path untested."""
    monkeypatch.setenv("MXNET_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("MXNET_FUSED_CONVBN_BWD", "1")
    monkeypatch.setitem(pcb._STATE, "enabled", None)
    monkeypatch.setattr(pcb, "_COLS_BUDGET_BYTES", 1)  # nb floor = 1

    shape, co, kernel, pad = (4, 6, 6, 8), 8, (3, 3), (1, 1)
    assert pcb._batch_tile_bwd(shape[0], 6, 6, 8, 6, 6, co, 3, 3) == 1
    x = jnp.asarray(_rand(shape))
    w = jnp.asarray(_rand((co, shape[-1]) + kernel, scale=0.2))
    sc = jnp.asarray(_rand((shape[-1],)) ** 2 + 0.5)
    bi = jnp.asarray(_rand((shape[-1],)))
    sh = jnp.asarray(_rand((co,)))

    def loss(x, w, sc, bi):
        y, s1, s2 = pcb.fused_conv_unit(
            x, w, sc, bi, sh, kernel=kernel, stride=(1, 1), pad=pad,
            act_in=True, want_stats=True)
        return ((y.astype(jnp.float32) ** 2).sum()
                + (s1 * s1).sum() * 1e-3 + s2.sum() * 1e-3)

    got = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w, sc, bi)

    monkeypatch.setenv("MXNET_FUSED_CONVBN_BWD", "0")
    ref = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w, sc, bi)
    for name, a, b in zip(("gx", "dw", "gscale", "gbias"), got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"{name}")


@pytest.mark.parametrize("axes", [{"dp": 4}, {"dp": 2, "fsdp": 2, "tp": 2}])
def test_sharded_pallas_bwd_matches_fallback(axes, monkeypatch):
    """MXNET_FUSED_CONVBN_BWD under a multi-device mesh: the per-shard
    backward kernel with psum'd dw/gscale/gbias must equal the XLA
    backward on the unsharded oracle, spy-verified to have engaged."""
    from mxnet_tpu import parallel

    shape, co, kernel, pad = (8, 8, 8, 16), 16, (3, 3), (1, 1)
    x = jnp.asarray(_rand(shape))
    w = jnp.asarray(_rand((co, shape[-1]) + kernel, scale=0.2))
    sc = jnp.asarray(_rand((shape[-1],)) ** 2 + 0.5)
    bi = jnp.asarray(_rand((shape[-1],)))
    sh = jnp.asarray(_rand((co,)))

    def loss(x, w, sc, bi):
        y, s1, s2 = pcb.fused_conv_unit(
            x, w, sc, bi, sh, kernel=kernel, stride=(1, 1), pad=pad,
            act_in=True, want_stats=True)
        return ((y.astype(jnp.float32) ** 2).sum()
                + (s1 * s1).sum() * 1e-3 + s2.sum() * 1e-3)

    monkeypatch.setenv("MXNET_USE_PALLAS", "0")
    monkeypatch.setitem(pcb._STATE, "enabled", None)
    ref = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w, sc, bi)

    calls = {"sharded_bwd": 0}
    real = pcb._pallas_unit_bwd_sharded

    def spy(*a, **k):
        calls["sharded_bwd"] += 1
        return real(*a, **k)

    monkeypatch.setattr(pcb, "_pallas_unit_bwd_sharded", spy)
    monkeypatch.setenv("MXNET_USE_PALLAS", "1")
    monkeypatch.setenv("MXNET_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("MXNET_FUSED_CONVBN_BWD", "1")
    monkeypatch.setitem(pcb._STATE, "enabled", None)
    with parallel.make_mesh(**axes):
        got = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w, sc, bi)
    assert calls["sharded_bwd"] == 1

    for name, a, b in zip(("gx", "dw", "gscale", "gbias"), got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"{name}")
