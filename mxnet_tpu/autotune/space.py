"""Search space derived from the knob registry.

A knob that wants to be tuned declares :class:`~mxnet_tpu.util.env.Tunable`
metadata where the knob itself is declared (``util/env.py``) — the space
is never duplicated beside the registry, so a new tunable knob is one
edit away from being swept.  This module turns that metadata into
proposal generators: uniform random samples over each dimension and
neighborhood mutations of an incumbent config (log-scale knobs double or
halve, categorical knobs flip), both clamped to the declared range.

Configs are plain ``{knob_name: value}`` dicts.  The empty dict is the
canonical "all declared defaults" config — trials inject config entries
into the subprocess environment, so an absent name means the child
resolves that knob exactly as an untuned process would.
"""
from __future__ import annotations

import math
import random
from typing import Any, Dict, Iterable, List, NamedTuple, Optional, Sequence

from ..base import MXNetError
from ..util import env

__all__ = ["Dimension", "dimensions", "sample", "neighbor",
           "priority_from_suspects"]


class Dimension(NamedTuple):
    name: str
    typ: type
    default: Any
    tunable: env.Tunable


def dimensions(names: Optional[Iterable[str]] = None) -> List[Dimension]:
    """The tunable dimensions, from the knob registry.

    ``names`` restricts (and orders) the space — the ``--from-suspects``
    feedback channel passes mxtriage's ranked knob suspects here so the
    sweep spends its budget on the dimensions attribution already
    implicated.  Unknown or non-tunable names raise: a priority list
    naming a knob the space cannot move is a caller bug.
    """
    by_name = {k.name: Dimension(k.name, k.typ, k.default, k.tunable)
               for k in env.tunables()}
    if names is None:
        return [by_name[n] for n in sorted(by_name)]
    out = []
    for n in names:
        if n not in by_name:
            raise MXNetError(
                f"{n!r} is not a tunable knob — declare Tunable "
                "metadata on it in mxnet_tpu/util/env.py (tunable: "
                f"{sorted(by_name)})")
        out.append(by_name[n])
    return out


def _clamp(dim: Dimension, value: float) -> Any:
    t = dim.tunable
    value = min(max(value, t.lo), t.hi)
    return int(round(value)) if dim.typ is int else float(value)


def sample(rng: random.Random, dims: Sequence[Dimension]) -> Dict[str, Any]:
    """One uniform random config over ``dims`` (log dimensions are
    uniform in log space, so 256 KiB..64 MiB doesn't spend 99% of its
    draws above 1 MiB)."""
    out: Dict[str, Any] = {}
    for d in dims:
        t = d.tunable
        if t.choices is not None:
            out[d.name] = rng.choice(list(t.choices))
        elif t.scale == "log":
            out[d.name] = _clamp(
                d, math.exp(rng.uniform(math.log(t.lo), math.log(t.hi))))
        else:
            out[d.name] = _clamp(d, rng.uniform(t.lo, t.hi))
    return out


def neighbor(rng: random.Random, config: Dict[str, Any],
             dims: Sequence[Dimension]) -> Dict[str, Any]:
    """Mutate ONE dimension of ``config`` — the local move successive
    halving interleaves with random restarts.  A name absent from
    ``config`` mutates from the knob's resolved value (declared default,
    or the dynamic default's midpoint when that is None)."""
    out = dict(config)
    d = rng.choice(list(dims))
    t = d.tunable
    if t.choices is not None:
        cur = out.get(d.name, d.default)
        others = [c for c in t.choices if c != cur] or list(t.choices)
        out[d.name] = rng.choice(others)
        return out
    cur = out.get(d.name, d.default)
    if cur is None:  # dynamic default: start from the range midpoint
        cur = math.sqrt(t.lo * t.hi) if t.scale == "log" \
            else (t.lo + t.hi) / 2
    if t.scale == "log":
        down, up = cur * 0.5, cur * 2.0
    else:
        step = (t.hi - t.lo) / 8.0
        down, up = cur - step, cur + step
    nv = _clamp(d, rng.choice((down, up)))
    if nv == cur:  # clamped back onto the incumbent (cur at a bound):
        nv = _clamp(d, down if nv == _clamp(d, up) else up)  # go the other way
    out[d.name] = nv
    return out


def priority_from_suspects(suspects: Iterable[Dict[str, Any]]) -> List[str]:
    """mxtriage feedback channel: filter a PERF_COMPARE.json ``suspects``
    array down to the registered TUNABLE knob names, rank order
    preserved, deduplicated.  Non-knob suspects (metrics, phases) and
    knob suspects without Tunable metadata are skipped — attribution can
    implicate a knob the space cannot move (e.g. a bool master switch
    deliberately left untunable), and that must not crash the sweep."""
    tunable_names = {k.name for k in env.tunables()}
    out: List[str] = []
    for s in suspects:
        if s.get("kind") != "knob":
            continue
        name = s.get("name")
        if name in tunable_names and name not in out:
            out.append(name)
    return out
