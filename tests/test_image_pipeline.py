"""Native image pipeline + im2rec tests
(model: the reference's tests for iter_image_recordio_2 / tools/im2rec)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import lib, recordio
from mxnet_tpu.io import ImageRecordIter

pytestmark = pytest.mark.skipif(
    not lib.image_available(),
    reason="native image pipeline unavailable (no OpenCV toolchain)")


def _make_rec(tmp_path, n=12, size=(24, 32), with_idx=True, seed=0):
    """Synthetic shard: each image is a solid color encoding its label."""
    import cv2

    rng = np.random.RandomState(seed)
    rec_path = str(tmp_path / "data.rec")
    idx_path = str(tmp_path / "data.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    colors = []
    for i in range(n):
        color = rng.randint(30, 225, size=3)
        img = np.full(size + (3,), color[::-1], np.uint8)  # BGR for cv2
        ok, buf = cv2.imencode(".png", img)  # lossless: exact colors
        assert ok
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 5), i, 0), buf.tobytes()))
        colors.append(color)
    rec.close()
    return rec_path, idx_path, np.array(colors)


def test_pipeline_decodes_and_orders(tmp_path):
    rec_path, idx_path, colors = _make_rec(tmp_path)
    pipe = lib.NativeImagePipeline(
        rec_path, None, batch=4, channels=3, height=16, width=16,
        label_width=1, threads=3)
    seen = 0
    while True:
        res = pipe.next()
        if res is None:
            break
        data, label, pad = res
        assert data.shape == (4, 16, 16, 3) and data.dtype == np.uint8
        for b in range(4 - pad):
            i = seen + b
            # solid-color image: every pixel equals the source color (RGB)
            assert np.array_equal(data[b, 0, 0], colors[i])
            assert np.array_equal(data[b], np.broadcast_to(
                colors[i], (16, 16, 3)).astype(np.uint8))
            assert label[b, 0] == float(i % 5)
        seen += 4 - pad
    assert seen == 12
    pipe.close()


def test_pipeline_reset_and_pad(tmp_path):
    rec_path, _, _ = _make_rec(tmp_path, n=10)
    pipe = lib.NativeImagePipeline(
        rec_path, None, batch=4, channels=3, height=8, width=8,
        label_width=1, threads=2)
    for _ in range(2):  # two epochs
        pads, batches = [], 0
        while True:
            res = pipe.next()
            if res is None:
                break
            batches += 1
            pads.append(res[2])
        assert batches == 3           # ceil(10/4)
        assert pads == [0, 0, 2]      # tail batch padded
        pipe.reset()
    pipe.close()


def test_pipeline_shuffle_epochs_differ(tmp_path):
    rec_path, idx_path, _ = _make_rec(tmp_path, n=12)
    pipe = lib.NativeImagePipeline(
        rec_path, idx_path, batch=12, channels=3, height=8, width=8,
        label_width=1, threads=2, shuffle=True, seed=7)
    first = pipe.next()[1][:, 0].copy()
    pipe.reset()
    second = pipe.next()[1][:, 0].copy()
    assert sorted(first) == sorted(second)
    assert not np.array_equal(first, second)  # reshuffled per epoch
    pipe.close()


def test_pipeline_normalize_matches_python(tmp_path):
    """normalize=1 (f32 NCHW + mean/std) must match the Python decode."""
    rec_path, _, colors = _make_rec(tmp_path, n=4, size=(16, 16))
    pipe = lib.NativeImagePipeline(
        rec_path, None, batch=4, channels=3, height=16, width=16,
        label_width=1, threads=2, normalize=True,
        mean_r=123.0, mean_g=117.0, mean_b=104.0,
        std_r=58.0, std_g=57.0, std_b=57.0)
    data, _, _ = pipe.next()
    assert data.shape == (4, 3, 16, 16) and data.dtype == np.float32
    mean = np.array([123.0, 117.0, 104.0], np.float32)
    std = np.array([58.0, 57.0, 57.0], np.float32)
    for b in range(4):
        expect = (colors[b].astype(np.float32) - mean) / std
        got = data[b, :, 0, 0]
        np.testing.assert_allclose(got, expect, rtol=1e-5)
    pipe.close()


def test_image_record_iter_uses_native_pipeline(tmp_path):
    rec_path, idx_path, colors = _make_rec(tmp_path, n=8, size=(20, 20))
    it = ImageRecordIter(
        path_imgrec=rec_path, path_imgidx=idx_path, data_shape=(3, 16, 16),
        batch_size=4, mean_r=10.0, std_r=2.0, preprocess_threads=2)
    assert it._pipe is not None  # fast path engaged
    batch = next(iter(it))
    d = batch.data[0].asnumpy()
    assert d.shape == (4, 3, 16, 16)
    np.testing.assert_allclose(
        d[0, 0, 0, 0], (colors[0][0] - 10.0) / 2.0, rtol=1e-5)
    assert batch.label[0].asnumpy()[1] == 1.0


def test_pipeline_decode_error_is_loud(tmp_path):
    rec_path = str(tmp_path / "bad.rec")
    rec = recordio.MXRecordIO(rec_path, "w")
    rec.write(recordio.pack(recordio.IRHeader(0, 0.0, 0, 0),
                            b"not an image at all"))
    rec.close()
    pipe = lib.NativeImagePipeline(
        rec_path, None, batch=1, channels=3, height=8, width=8,
        label_width=1, threads=1)
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError, match="decode failed"):
        for _ in range(4):  # error surfaces on a subsequent Next
            if pipe.next() is None:
                break
    pipe.close()


def test_im2rec_end_to_end(tmp_path):
    """tools/im2rec.py --list + pack, then read back via ImageRecordIter."""
    import cv2

    root = tmp_path / "images"
    for ci, cat in enumerate(["cat", "dog"]):
        d = root / cat
        d.mkdir(parents=True)
        for i in range(3):
            img = np.full((28, 28, 3), 40 * (ci * 3 + i) + 20, np.uint8)
            cv2.imwrite(str(d / f"{i}.png"), img)
    prefix = str(tmp_path / "ds")
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "im2rec.py")
    env = dict(os.environ, PYTHONPATH=os.pathsep.join(
        [os.path.dirname(tool)] + sys.path))
    r = subprocess.run([sys.executable, tool, prefix, str(root), "--list",
                        "--recursive"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert os.path.isfile(prefix + ".lst")
    r = subprocess.run([sys.executable, tool, prefix, str(root),
                        "--num-thread", "2", "--encoding", ".png"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert os.path.isfile(prefix + ".rec")
    assert os.path.isfile(prefix + ".idx")

    it = ImageRecordIter(path_imgrec=prefix + ".rec",
                         path_imgidx=prefix + ".idx",
                         data_shape=(3, 24, 24), batch_size=3,
                         preprocess_threads=2)
    labels = []
    for batch in it:
        labels.extend(batch.label[0].asnumpy()[:3 - batch.pad].tolist())
    assert sorted(labels) == [0.0, 0.0, 0.0, 1.0, 1.0, 1.0]


def test_dataloader_parallel_workers_ordered():
    """num_workers>1 must give N real workers AND strict sampler order."""
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    x = np.arange(64, dtype=np.float32).reshape(64, 1)
    ds = ArrayDataset(x)
    dl = DataLoader(ds, batch_size=4, num_workers=3)
    out = [b.asnumpy()[:, 0].tolist() for b in dl]
    expect = [x[i:i + 4, 0].tolist() for i in range(0, 64, 4)]
    assert out == expect


def test_dataloader_worker_error_propagates():
    from mxnet_tpu.gluon.data import DataLoader

    class Bad:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            if i == 5:
                raise ValueError("boom at 5")
            return np.zeros(2, np.float32)

    dl = DataLoader(Bad(), batch_size=2, num_workers=2)
    with pytest.raises(ValueError, match="boom at 5"):
        list(dl)


def test_pipeline_resize_equals_short_edge_narrow_image(tmp_path):
    """resize == the image's short edge but smaller than the crop: the
    clamp must still upscale instead of cropping out of bounds."""
    import cv2

    rec_path = str(tmp_path / "narrow.rec")
    rec = recordio.MXRecordIO(rec_path, "w")
    img = np.full((10, 40, 3), 99, np.uint8)  # short edge 10
    ok, buf = cv2.imencode(".png", img)
    assert ok
    rec.write(recordio.pack(recordio.IRHeader(0, 1.0, 0, 0), buf.tobytes()))
    rec.close()
    pipe = lib.NativeImagePipeline(
        rec_path, None, batch=1, channels=3, height=24, width=24,
        label_width=1, threads=1, resize_short=10)
    data, label, pad = pipe.next()
    assert data.shape == (1, 24, 24, 3)
    assert (data == 99).all()
    pipe.close()


def test_pipeline_corrupt_label_count_is_loud(tmp_path):
    """A record whose IRHeader claims more label floats than the record
    holds must raise, not read out of bounds."""
    import struct

    rec_path = str(tmp_path / "corrupt.rec")
    rec = recordio.MXRecordIO(rec_path, "w")
    # flag=100000 labels claimed, 4 bytes of payload
    hdr = struct.pack("<IfQQ", 100000, 0.0, 0, 0)
    rec.write(hdr + b"\x00\x00\x00\x00")
    rec.close()
    pipe = lib.NativeImagePipeline(
        rec_path, None, batch=1, channels=3, height=8, width=8,
        label_width=1, threads=1)
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError, match="label count exceeds"):
        for _ in range(4):
            if pipe.next() is None:
                break
    pipe.close()


def test_pipeline_reset_clears_error(tmp_path):
    """One bad epoch must not poison the pipeline after Reset."""
    import cv2

    rec_path = str(tmp_path / "mixed.rec")
    rec = recordio.MXRecordIO(rec_path, "w")
    rec.write(recordio.pack(recordio.IRHeader(0, 0.0, 0, 0), b"garbage"))
    rec.close()
    pipe = lib.NativeImagePipeline(
        rec_path, None, batch=1, channels=3, height=8, width=8,
        label_width=1, threads=1)
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError):
        for _ in range(4):
            if pipe.next() is None:
                break
    # rewrite the shard with a valid image, reset, read cleanly
    ok, buf = cv2.imencode(".png", np.full((8, 8, 3), 7, np.uint8))
    rec = recordio.MXRecordIO(rec_path, "w")
    rec.write(recordio.pack(recordio.IRHeader(0, 2.0, 0, 0), buf.tobytes()))
    rec.close()
    pipe.reset()
    data, label, pad = pipe.next()
    assert label[0, 0] == 2.0
    pipe.close()


def test_pipeline_corrupt_shard_is_loud_not_fatal(tmp_path):
    """Bad record magic mid-shard raises MXNetError (reader-thread errors
    must never std::terminate the process)."""
    rec_path = str(tmp_path / "badmagic.rec")
    with open(rec_path, "wb") as f:
        f.write(b"\x00" * 64)  # not a recordio stream at all
    pipe = lib.NativeImagePipeline(
        rec_path, None, batch=2, channels=3, height=8, width=8,
        label_width=1, threads=1)
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError, match="magic|truncated"):
        for _ in range(4):
            if pipe.next() is None:
                break
    pipe.close()
