#!/usr/bin/env python
"""Regenerate (or verify) the lint rule catalogue in
docs/static_analysis.md.

Every rule is DECLARED once in the analysis package (``@register_rule``
sets id/name/description on the class); the table between the
``lint-rule-catalog`` markers is GENERATED from that registry — the
same registry-then-docs contract `tools/gen_metric_docs.py` keeps for
the metric catalogue and `tools/mxlint.py --env-docs` keeps for the
knob registry.

    python tools/gen_lint_docs.py           # check (exit 1 on drift)
    python tools/gen_lint_docs.py --write   # rewrite the table

A tier-1 sync test (tests/test_mxlint.py) runs the check, so a PR that
registers a rule cannot ship with a stale catalogue.  The analysis
package is loaded standalone (no mxnet_tpu/__init__, no jax) so the
check costs milliseconds.
"""
from __future__ import annotations

import argparse
import importlib.util
import os
import sys
from typing import List, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_BEGIN = "<!-- BEGIN GENERATED: lint-rule-catalog " \
    "(tools/gen_lint_docs.py --write) -->"
_END = "<!-- END GENERATED: lint-rule-catalog -->"


def _load_analysis():
    if "mxnet_tpu.analysis" in sys.modules:
        return sys.modules["mxnet_tpu.analysis"]
    pkg_dir = os.path.join(_REPO, "mxnet_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "mxnet_tpu.analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["mxnet_tpu.analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def _scope(cls) -> str:
    if ".ir." in cls.__module__:
        return "StableHLO IR"
    if getattr(cls, "cacheable", "") == "file":
        return "file"
    if getattr(cls, "cacheable", "") == "contrib":
        return "cross-file"
    return "project"


def _cached(cls) -> str:
    mode = getattr(cls, "cacheable", "")
    if mode == "file":
        return "yes"
    if mode == "contrib":
        return "yes (contribution)"
    if ".ir." in cls.__module__:
        return "n/a (audits compiled programs, not source)"
    return "no"


def table_markdown(analysis) -> str:
    """The generated block body: one row per registered rule, sorted
    by id.  Pipes in descriptions are escaped so the table survives."""
    rows: List[Tuple[str, ...]] = []
    for rid, cls in sorted(analysis.RULE_REGISTRY.items()):
        desc = " ".join(str(cls.description).split()).replace("|", "\\|")
        rows.append((rid, cls.name, _scope(cls), _cached(cls), desc))
    lines = [
        "| Rule | Name | Scope | Cached | Description |",
        "| --- | --- | --- | --- | --- |",
    ]
    lines.extend("| {} | {} | {} | {} | {} |".format(*r) for r in rows)
    return "\n".join(lines)


def apply_block(path: str, write: bool) -> Tuple[bool, str]:
    """Replace (or verify) the marker-delimited block in ``path``.
    Returns ``(in_sync, rendered_table)``; raises ``ValueError`` when
    the markers are missing or unordered."""
    analysis = _load_analysis()
    table = table_markdown(analysis)
    with open(path, "r", encoding="utf-8") as f:
        doc = f.read()
    try:
        lo = doc.index(_BEGIN)
        hi = doc.index(_END)
    except ValueError:
        raise ValueError(f"{path}: lint-rule-catalog markers not found")
    if hi < lo:
        raise ValueError(f"{path}: END marker precedes BEGIN marker")
    current = doc[lo + len(_BEGIN):hi].strip("\n")
    if current == table:
        return True, table
    if write:
        new_doc = doc[:lo] + _BEGIN + "\n" + table + "\n" + doc[hi:]
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(new_doc)
        os.replace(tmp, path)
    return False, table


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--write", action="store_true",
                    help="rewrite the generated block in place")
    ap.add_argument("--path",
                    default=os.path.join(_REPO, "docs",
                                         "static_analysis.md"),
                    help="docs file (default: docs/static_analysis.md)")
    args = ap.parse_args(argv)
    try:
        ok, _ = apply_block(args.path, write=args.write)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if ok:
        print("lint rule catalogue in sync")
        return 0
    if args.write:
        print("lint rule catalogue regenerated")
        return 0
    print("lint rule catalogue OUT OF SYNC — run "
          "`python tools/gen_lint_docs.py --write`", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
