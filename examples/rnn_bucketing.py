"""Bucketed RNN language model via the SYMBOLIC Module path
(ref: example/rnn/bucketing/lstm_bucketing.py — the reference's flagship
BucketingModule workflow).

Char-level LM: sentences are bucketed by length, one executor compiled
per bucket (here: one XLA program per bucket, all sharing parameters —
the executor-per-bucket design of the reference), trained with
Module.fit over the fused RNN op.

Usage:
  python examples/rnn_bucketing.py                 # TPU, synthetic text
  python examples/rnn_bucketing.py --cpu --small   # CPU smoke (CI)
  python examples/rnn_bucketing.py --text corpus.txt --epochs 10
      # REAL-DATA path: any plain-text file, one sentence per line
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
logging.basicConfig(level=logging.INFO, format="%(message)s")


class BucketSentenceIter:
    """Minimal bucketed iterator (ref: BucketSentenceIter in
    example/rnn/bucketing): sentences of encoded ids grouped into the
    smallest bucket that fits, batches padded to the bucket length."""

    def __init__(self, sentences, batch_size, buckets, vocab_size,
                 invalid_label=0):
        import numpy as np

        from mxnet_tpu.io import DataDesc

        self.batch_size = batch_size
        self.buckets = sorted(buckets)
        self.vocab_size = vocab_size
        self.data = {b: [] for b in self.buckets}
        for s in sentences:
            if len(s) < 2:
                continue
            bk = next((b for b in self.buckets if len(s) <= b + 1), None)
            if bk is None:
                continue
            row = np.full(bk + 1, invalid_label, np.float32)
            row[:len(s)] = s
            self.data[bk].append(row)
        self.default_bucket_key = self.buckets[-1]
        self.provide_data = [DataDesc(
            "data", (batch_size, self.default_bucket_key))]
        self.provide_label = [DataDesc(
            "softmax_label", (batch_size, self.default_bucket_key))]
        self.reset()

    def reset(self):
        import numpy as np

        self._plan = []
        for bk, rows in self.data.items():
            np.random.shuffle(rows)
            for i in range(0, len(rows) - self.batch_size + 1,
                           self.batch_size):
                self._plan.append((bk, i))
        np.random.shuffle(self._plan)
        self._cursor = 0

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        import numpy as np

        from mxnet_tpu import nd
        from mxnet_tpu.io import DataBatch, DataDesc

        if self._cursor >= len(self._plan):
            raise StopIteration
        bk, i = self._plan[self._cursor]
        self._cursor += 1
        rows = np.stack(self.data[bk][i:i + self.batch_size])
        data, label = rows[:, :-1], rows[:, 1:]
        return DataBatch(
            data=[nd.array(data)], label=[nd.array(label)],
            bucket_key=bk,
            provide_data=[DataDesc("data", data.shape)],
            provide_label=[DataDesc("softmax_label", label.shape)])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--text", default=None,
                    help="plain-text file, one sentence per line")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-hidden", type=int, default=200)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--cells", action="store_true",
                    help="build the graph with the legacy mx.rnn cell "
                         "API (unrolled LSTMCell stack, the reference "
                         "lstm_bucketing.py design) instead of the "
                         "fused RNN op")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import mxnet_tpu as mx

    np.random.seed(args.seed)  # Xavier init + bucket shuffles deterministic
    mx.random.seed(args.seed)

    if args.small:
        args.batch_size, args.num_hidden, args.num_layers = 8, 32, 1
        buckets = [8, 16]
    else:
        buckets = [10, 20, 30, 40, 60]

    # ---- corpus -> encoded sentences -----------------------------------
    if args.text:
        with open(args.text) as f:
            lines = [line.strip() for line in f if line.strip()]
    else:  # synthetic: repeated alphabet runs are very learnable
        rng = np.random.RandomState(0)
        alpha = "abcdefghij"
        lines = []
        for _ in range(300 if args.small else 2000):
            start = rng.randint(len(alpha))
            n = rng.randint(4, (buckets[-1] - 1))
            lines.append("".join(alpha[(start + k) % len(alpha)]
                                 for k in range(n)))
    chars = sorted(set("".join(lines)))
    vocab = {c: i + 1 for i, c in enumerate(chars)}  # 0 = pad
    vocab_size = len(vocab) + 1
    sentences = [[vocab[c] for c in line] for line in lines]
    train_iter = BucketSentenceIter(sentences, args.batch_size, buckets,
                                    vocab_size)

    # ---- symbol generator: one graph per bucket length -----------------
    def sym_gen(seq_len):
        data = mx.sym.var("data")
        label = mx.sym.var("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab_size,
                                 output_dim=args.num_hidden, name="embed")
        if args.cells:
            # legacy mx.rnn cell path (ref: lstm_bucketing.py): per-bucket
            # unrolled LSTMCell stack; params shared across buckets by name
            stack = mx.rnn.SequentialRNNCell()
            for i in range(args.num_layers):
                stack.add(mx.rnn.LSTMCell(args.num_hidden,
                                          prefix=f"lstm_l{i}_"))
            out, _states = stack.unroll(seq_len, embed, layout="NTC")
        else:
            rnn_in = mx.sym.transpose(embed, axes=(1, 0, 2))  # (T, N, H)
            out = mx.sym.RNN(rnn_in, state_size=args.num_hidden,
                             num_layers=args.num_layers, mode="lstm",
                             state_outputs=False, name="lstm")
            out = mx.sym.transpose(out, axes=(1, 0, 2))       # (N, T, H)
        out = mx.sym.reshape(out, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(out, num_hidden=vocab_size,
                                     name="pred")
        label_f = mx.sym.reshape(label, shape=(-1,))
        sm = mx.sym.SoftmaxOutput(pred, label_f, name="softmax")
        return sm, ("data",), ("softmax_label",)

    ctx = mx.cpu() if args.cpu else mx.tpu(0)
    model = mx.module.BucketingModule(
        sym_gen=sym_gen, default_bucket_key=train_iter.default_bucket_key,
        context=ctx)

    metric = mx.metric.Perplexity(ignore_label=0)
    model.fit(train_iter, eval_metric=metric,
              optimizer="adam",
              optimizer_params={"learning_rate": args.lr},
              initializer=mx.initializer.Xavier(),
              num_epoch=args.epochs,
              batch_end_callback=mx.callback.Speedometer(
                  args.batch_size, 10))
    train_iter.reset()
    final = model.score(train_iter, mx.metric.Perplexity(ignore_label=0))
    print(f"final {final[0][0]}={final[0][1]:.3f}")


if __name__ == "__main__":
    main()
