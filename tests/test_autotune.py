"""mxtune (ISSUE 16): goodput-optimal knob autotuning.

Covers the registry-side pieces (Tunable metadata, env-overlay
precedence, unknown-env hygiene), the search space, the
successive-halving searcher (pruning, crash containment, pinned
default), the config store (round-trip, corrupt-entry quarantine), the
mxprof tuned-config stamp, the MXNET_PREFETCH_DEPTH DataLoader knob,
and — in the slow lane — the subprocess proof that a fresh process
with a populated store boots already-tuned.
"""
import importlib.util
import json
import os
import random
import subprocess
import sys
import warnings

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autotune
from mxnet_tpu.util import env

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_overlay():
    """Every test starts and ends with no tuned overlay installed."""
    env.clear_overlay()
    yield
    env.clear_overlay()


# ---------------------------------------------------------------------------
# knob-registry hygiene (satellite 1)
# ---------------------------------------------------------------------------

class TestRegistryHygiene:
    def test_duplicate_registration_raises_loudly(self):
        with pytest.raises(mx.MXNetError, match="already registered"):
            env.declare("MXNET_PREFETCH_DEPTH", int, None, "dupe")

    def test_unknown_env_warns_once_with_did_you_mean(self, monkeypatch):
        monkeypatch.setenv("MXNET_PREFTCH_DEPTH", "4")  # typo'd knob
        monkeypatch.setattr(env, "_warned_unknown_env", False)
        with pytest.warns(RuntimeWarning,
                          match="did you mean MXNET_PREFETCH_DEPTH"):
            env.resolved()
        # once per process: the second resolved() is silent
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            env.resolved()

    def test_harness_control_vars_are_exempt(self, monkeypatch):
        monkeypatch.setenv("MXNET_NIGHTLY", "1")
        monkeypatch.setenv("MXNET_TEST_SEED", "0")
        monkeypatch.setattr(env, "_warned_unknown_env", False)
        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            env.resolved()

    def test_tunable_metadata_rides_the_registry(self):
        names = {k.name for k in env.tunables()}
        assert "MXNET_PREFETCH_DEPTH" in names
        assert "MXNET_FUSED_BUCKET_BYTES" in names
        k = next(k for k in env.tunables()
                 if k.name == "MXNET_FUSED_BUCKET_BYTES")
        assert k.tunable.scale == "log"
        assert k.tunable.lo < k.default < k.tunable.hi


# ---------------------------------------------------------------------------
# env-overlay precedence (tentpole + satellite tests)
# ---------------------------------------------------------------------------

class TestOverlayPrecedence:
    def test_explicit_env_beats_overlay_beats_default(self, monkeypatch):
        assert env.get_int("MXNET_ZERO_MIN_SIZE") == 2048  # default
        info = env.apply_overlay({"MXNET_ZERO_MIN_SIZE": 4096})
        assert info["applied"] == ["MXNET_ZERO_MIN_SIZE"]
        assert env.get_int("MXNET_ZERO_MIN_SIZE") == 4096   # overlay
        monkeypatch.setenv("MXNET_ZERO_MIN_SIZE", "1024")
        assert env.get_int("MXNET_ZERO_MIN_SIZE") == 1024   # env wins

    def test_env_set_before_apply_is_shadowed(self, monkeypatch):
        monkeypatch.setenv("MXNET_ZERO_MIN_SIZE", "1024")
        info = env.apply_overlay({"MXNET_ZERO_MIN_SIZE": 4096})
        assert info["shadowed"] == ["MXNET_ZERO_MIN_SIZE"]
        assert env.get_int("MXNET_ZERO_MIN_SIZE") == 1024

    def test_empty_string_env_means_unset_so_overlay_applies(
            self, monkeypatch):
        # launchers export VAR="" as 'use the default' — the overlay IS
        # the default then
        monkeypatch.setenv("MXNET_ZERO_MIN_SIZE", "")
        env.apply_overlay({"MXNET_ZERO_MIN_SIZE": 4096})
        assert env.get_int("MXNET_ZERO_MIN_SIZE") == 4096

    def test_unregistered_names_ignored_not_fatal(self):
        info = env.apply_overlay({"MXNET_GONE_KNOB": 7,
                                  "MXNET_ZERO_MIN_SIZE": 4096})
        assert info["ignored"] == ["MXNET_GONE_KNOB"]
        assert info["applied"] == ["MXNET_ZERO_MIN_SIZE"]

    def test_bool_and_float_values_convert_like_env(self):
        env.apply_overlay({"MXNET_FUSED_OPTIMIZER": True,
                           "MXNET_RETRY_BASE_MS": 75.5})
        assert env.get_bool("MXNET_FUSED_OPTIMIZER") is True
        assert env.get_float("MXNET_RETRY_BASE_MS") == 75.5

    def test_clear_overlay_restores_defaults(self):
        env.apply_overlay({"MXNET_ZERO_MIN_SIZE": 4096})
        env.clear_overlay()
        assert env.get_int("MXNET_ZERO_MIN_SIZE") == 2048
        assert env.overlay_info() is None

    def test_fingerprint_stable_across_application_order(self):
        cfg = {"MXNET_ZERO_MIN_SIZE": 4096,
               "MXNET_RETRY_BASE_MS": 75.0,
               "MXNET_FUSED_CACHE_MAX": 128}
        env.apply_overlay(cfg)
        fp_once = env.fingerprint()
        env.clear_overlay()
        for name in reversed(sorted(cfg)):  # one at a time, reversed
            env.apply_overlay({name: cfg[name]})
        assert env.fingerprint() == fp_once
        # and the config's own identity is order-independent too
        assert autotune.config_fingerprint(cfg) == \
            autotune.config_fingerprint(
                dict(reversed(list(cfg.items()))))


# ---------------------------------------------------------------------------
# search space
# ---------------------------------------------------------------------------

class TestSpace:
    def test_sample_respects_declared_bounds(self):
        dims = autotune.dimensions()
        rng = random.Random(0)
        for _ in range(20):
            cfg = autotune.sample(rng, dims)
            for d in dims:
                v = cfg[d.name]
                if d.tunable.choices is not None:
                    assert v in d.tunable.choices
                    continue
                assert d.tunable.lo <= v <= d.tunable.hi
                assert isinstance(v, int) if d.typ is int else True

    def test_neighbor_moves_one_dimension_within_bounds(self):
        dims = autotune.dimensions()
        rng = random.Random(1)
        base = autotune.sample(rng, dims)
        for _ in range(20):
            nxt = autotune.neighbor(rng, base, dims)
            changed = [n for n in nxt if nxt[n] != base.get(n)]
            assert len(changed) == 1
            d = next(d for d in dims if d.name == changed[0])
            if d.tunable.choices is not None:
                assert nxt[changed[0]] in d.tunable.choices
            else:
                assert d.tunable.lo <= nxt[changed[0]] <= d.tunable.hi

    def test_dimensions_subset_orders_and_validates(self):
        dims = autotune.dimensions(["MXNET_PREFETCH_DEPTH",
                                    "MXNET_FUSED_BUCKET_BYTES"])
        assert [d.name for d in dims] == ["MXNET_PREFETCH_DEPTH",
                                          "MXNET_FUSED_BUCKET_BYTES"]
        with pytest.raises(mx.MXNetError, match="not a tunable"):
            autotune.dimensions(["MXNET_ENGINE_TYPE"])

    def test_priority_from_suspects_filters_to_tunables(self):
        suspects = [
            {"kind": "phase", "name": "grad-allreduce", "score": 9},
            {"kind": "knob", "name": "MXNET_FUSED_BUCKET_BYTES",
             "score": 5},
            {"kind": "knob", "name": "MXNET_ENGINE_TYPE", "score": 5},
            {"kind": "knob", "name": "MXNET_FUSED_BUCKET_BYTES",
             "score": 4},  # dupe, rank preserved
            {"kind": "knob", "name": "MXNET_PREFETCH_DEPTH",
             "score": 3},
        ]
        assert autotune.priority_from_suspects(suspects) == \
            ["MXNET_FUSED_BUCKET_BYTES", "MXNET_PREFETCH_DEPTH"]


# ---------------------------------------------------------------------------
# successive halving
# ---------------------------------------------------------------------------

def _bucket_dims():
    return autotune.dimensions(["MXNET_FUSED_BUCKET_BYTES"])


class TestSearch:
    def test_halving_prunes_seeded_slow_config(self):
        """A runner where small bucket-bytes wins: the sweep must find
        a config beating the 4MiB default, and must have pruned arms
        along the way."""
        def runner(config, budget):
            v = config.get("MXNET_FUSED_BUCKET_BYTES", 4 << 20)
            return {"objective": 1e7 / v, "ok": True}

        rep = autotune.successive_halving(
            runner, _bucket_dims(), rng=random.Random(3),
            n_initial=8, rungs=3)
        assert rep["ok"]
        assert rep["best_objective"] >= rep["default_objective"]
        assert rep["delta"] >= 0
        assert rep["pruned"] > 0
        assert len(rep["trajectory"]) == 3
        # budgets grow per rung
        assert rep["trajectory"][1]["budget"] == \
            2 * rep["trajectory"][0]["budget"]
        assert rep["best_config"]["MXNET_FUSED_BUCKET_BYTES"] < 4 << 20

    def test_crashed_trial_counted_not_fatal(self):
        def crasher(config, budget):
            if config:  # every non-default arm dies
                raise RuntimeError("simulated OOM")
            return {"objective": 0.9}

        rep = autotune.successive_halving(
            crasher, _bucket_dims(), rng=random.Random(4),
            n_initial=6, rungs=2)
        assert rep["ok"]
        assert rep["crashed"] > 0
        assert rep["best_config"] == {}  # default survives and wins
        assert rep["best_objective"] == 0.9

    def test_timeout_style_none_result_is_pruned(self):
        def timeouter(config, budget):
            return None if config else {"objective": 0.5}

        rep = autotune.successive_halving(
            timeouter, _bucket_dims(), rng=random.Random(5),
            n_initial=4, rungs=2)
        assert rep["ok"] and rep["best_config"] == {}
        assert rep["crashed"] == rep["trials"] - 2  # default runs twice

    def test_default_always_remeasured_at_final_rung(self):
        calls = []

        def runner(config, budget):
            calls.append((not config, budget))
            # default is deliberately WORST: it must still be measured
            # at every rung despite ranking last
            return {"objective": 0.1 if not config else 0.9}

        rep = autotune.successive_halving(
            runner, _bucket_dims(), rng=random.Random(6),
            n_initial=6, rungs=3)
        budgets = sorted(b for is_default, b in calls if is_default)
        assert len(budgets) == 3  # one default measurement per rung
        assert rep["default_objective"] == 0.1
        assert rep["delta"] == pytest.approx(0.8)

    def test_tiebreak_orders_equal_objectives(self):
        def runner(config, budget):
            mfu = 0.9 if config else 0.1
            return {"objective": 0.5, "tiebreak": (mfu,)}

        rep = autotune.successive_halving(
            runner, _bucket_dims(), rng=random.Random(7),
            n_initial=4, rungs=2)
        assert rep["best_config"] != {}
        assert rep["delta"] == 0.0  # ties the default on the objective


# ---------------------------------------------------------------------------
# config store
# ---------------------------------------------------------------------------

class TestStore:
    def _key(self, scenario="mlp_train", version="v1", platform="cpu"):
        return autotune.entry_key(scenario=scenario, mesh=[8],
                                  device_kind="host",
                                  framework_version=version,
                                  platform=platform)

    def test_round_trip(self, tmp_path):
        s = autotune.ConfigStore(str(tmp_path))
        cfg = {"MXNET_ZERO_MIN_SIZE": 4096, "MXNET_PREFETCH_DEPTH": 6}
        s.put(self._key(), cfg, 0.93, meta={"quick": True})
        e = s.get(self._key())
        assert e["config"] == cfg
        assert e["objective"] == 0.93
        assert e["config_fingerprint"] == \
            autotune.config_fingerprint(cfg)
        assert s.stats["hits"] == 1 and s.stats["corrupt"] == 0

    def test_miss_on_absent_key(self, tmp_path):
        s = autotune.ConfigStore(str(tmp_path))
        assert s.get(self._key()) is None
        assert s.stats["misses"] == 1

    def test_corrupt_entry_quarantined_and_missed(self, tmp_path):
        s = autotune.ConfigStore(str(tmp_path))
        path = s.put(self._key(), {"MXNET_ZERO_MIN_SIZE": 4096}, 0.9)
        with open(path, "wb") as f:
            f.write(b'{"not": "an entry"}')
        assert s.get(self._key()) is None  # a miss, never an error
        assert s.stats["corrupt"] == 1
        assert os.path.exists(path + ".corrupt")
        assert not os.path.exists(path)
        # a tampered config (fingerprint mismatch) is also corrupt
        path2 = s.put(self._key("other"), {"MXNET_ZERO_MIN_SIZE": 1}, 1)
        blob = json.load(open(path2))
        blob["config"]["MXNET_ZERO_MIN_SIZE"] = 9999
        with open(path2, "w") as f:
            json.dump(blob, f)
        assert s.get(self._key("other")) is None
        assert s.stats["corrupt"] == 2

    def test_best_for_startup_matching(self, tmp_path):
        s = autotune.ConfigStore(str(tmp_path))
        s.put(self._key(version="OLD"), {"MXNET_ZERO_MIN_SIZE": 1}, 1)
        s.put(self._key(platform="tpu"), {"MXNET_ZERO_MIN_SIZE": 2}, 1)
        s.put(self._key(platform="cpu"), {"MXNET_ZERO_MIN_SIZE": 3}, 1)
        # version must match exactly; this platform's entry preferred
        e = s.best_for_startup(framework_version="v1", platform="cpu")
        assert e["config"] == {"MXNET_ZERO_MIN_SIZE": 3}
        # a pinned scenario that matches nothing: None, never a guess
        assert s.best_for_startup(scenario="resnet",
                                  framework_version="v1") is None
        assert s.best_for_startup(framework_version="v9") is None


# ---------------------------------------------------------------------------
# mxprof stamp + prefetch knob
# ---------------------------------------------------------------------------

class TestTunedConfigStamp:
    def test_dump_carries_tuned_fingerprint_and_overlay_knobs(self):
        from mxnet_tpu.telemetry import mxprof

        mxprof.enable()
        cfg = {"MXNET_ZERO_MIN_SIZE": 4096}
        env.apply_overlay(cfg, fingerprint=autotune.config_fingerprint(
            cfg), source="test-store")
        d = mxprof.snapshot(live_hbm=False, include_records=False)
        assert d["tuned_config"]["fingerprint"] == \
            autotune.config_fingerprint(cfg)
        assert d["tuned_config"]["source"] == "test-store"
        assert d["tuned_config"]["applied"] == ["MXNET_ZERO_MIN_SIZE"]
        # the overlaid knob rides the knobs dict (attribution sees the
        # tuned VALUE, not just the fingerprint)
        assert d["knobs"]["MXNET_ZERO_MIN_SIZE"] == 4096
        env.clear_overlay()
        d2 = mxprof.snapshot(live_hbm=False, include_records=False)
        assert "tuned_config" not in d2


class TestPrefetchKnob:
    def test_default_preserved_without_knob(self):
        from mxnet_tpu.gluon.data import DataLoader

        ds = [np.zeros(2, np.float32)] * 8
        dl = DataLoader(ds, batch_size=2, num_workers=3)
        assert dl._prefetch == 6  # 2 * num_workers, the dynamic default
        assert DataLoader(ds, batch_size=2)._prefetch == 0

    def test_knob_plumbs_both_pools(self, monkeypatch):
        from mxnet_tpu.gluon.data import DataLoader

        monkeypatch.setenv("MXNET_PREFETCH_DEPTH", "5")
        ds = [np.zeros(2, np.float32)] * 8
        for pool in ("thread", "process"):
            dl = DataLoader(ds, batch_size=2, num_workers=2,
                            worker_pool=pool)
            assert dl._prefetch == 5, pool

    def test_explicit_argument_beats_knob(self, monkeypatch):
        from mxnet_tpu.gluon.data import DataLoader

        monkeypatch.setenv("MXNET_PREFETCH_DEPTH", "5")
        ds = [np.zeros(2, np.float32)] * 8
        dl = DataLoader(ds, batch_size=2, num_workers=2, prefetch=1)
        assert dl._prefetch == 1

    def test_overlay_feeds_knob_and_loader_still_works(self):
        from mxnet_tpu.gluon.data import DataLoader

        env.apply_overlay({"MXNET_PREFETCH_DEPTH": 3})
        ds = [np.full(2, i, np.float32) for i in range(8)]
        dl = DataLoader(ds, batch_size=2, num_workers=2,
                        worker_pool="thread")
        assert dl._prefetch == 3
        batches = list(dl)
        assert len(batches) == 4  # tuned depth changes no semantics


# ---------------------------------------------------------------------------
# CLI plumbing (fast: no sweep subprocesses)
# ---------------------------------------------------------------------------

def _load_cli():
    spec = importlib.util.spec_from_file_location(
        "autotune_cli_under_test",
        os.path.join(_REPO, "tools", "autotune.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestCliPlumbing:
    def test_from_suspects_reads_artifact_array(self, tmp_path):
        cli = _load_cli()
        rep = {"ok": False, "suspects": [
            {"kind": "phase", "name": "forward", "score": 9},
            {"kind": "knob", "name": "MXNET_PREFETCH_DEPTH",
             "score": 5},
        ]}
        p = tmp_path / "PERF_COMPARE.json"
        p.write_text(json.dumps(rep))
        logs = []
        assert cli._priority_from_file(str(p), logs.append) == \
            ["MXNET_PREFETCH_DEPTH"]

    def test_from_suspects_without_tunables_falls_back(self, tmp_path):
        cli = _load_cli()
        p = tmp_path / "PERF_COMPARE.json"
        p.write_text(json.dumps({"ok": True, "suspects": []}))
        logs = []
        assert cli._priority_from_file(str(p), logs.append) is None
        assert any("suspects" in m for m in logs)

    def test_unknown_scenario_usage_error(self, capsys):
        cli = _load_cli()
        assert cli.main(["--scenarios", "nope"]) == 2


# ---------------------------------------------------------------------------
# slow lane: subprocess proofs (nightly)
# ---------------------------------------------------------------------------

_BOOT_SNIPPET = r"""
import json
import mxnet_tpu as mx
from mxnet_tpu.telemetry import mxprof
from mxnet_tpu.util import env
d = mxprof.snapshot(live_hbm=False, include_records=False)
print(json.dumps({
    "tuned_config": d.get("tuned_config"),
    "prefetch": env.get_int("MXNET_PREFETCH_DEPTH"),
    "zero_min": env.get_int("MXNET_ZERO_MIN_SIZE"),
}))
"""


def _boot_env(store_dir, **extra):
    """A child env with ZERO manual MXNET_* knob settings: only the
    store pointer and the mxprof dump switch survive."""
    child = {k: v for k, v in os.environ.items()
             if not k.startswith("MXNET_")}
    child["JAX_PLATFORMS"] = "cpu"
    child["MXNET_AUTOTUNE_DIR"] = str(store_dir)
    child["MXNET_MXPROF"] = "1"
    child.update(extra)
    return child


@pytest.mark.slow
class TestBootTuned:
    def _populate(self, tmp_path, cfg):
        store = autotune.ConfigStore(str(tmp_path))
        key = autotune.entry_key(scenario="mlp_train", mesh=[1],
                                 device_kind="",
                                 framework_version=mx.__version__,
                                 platform="cpu")
        store.put(key, cfg, 0.95)
        return autotune.config_fingerprint(cfg)

    def test_fresh_process_boots_with_tuned_overlay(self, tmp_path):
        """The acceptance proof: a fresh process + a populated store +
        zero manual knob env = tuned overlay applied, fingerprint
        visible in its mxprof dump."""
        cfg = {"MXNET_PREFETCH_DEPTH": 6, "MXNET_ZERO_MIN_SIZE": 4096}
        fp = self._populate(tmp_path, cfg)
        p = subprocess.run([sys.executable, "-c", _BOOT_SNIPPET],
                           capture_output=True, text=True, timeout=180,
                           env=_boot_env(tmp_path), cwd=_REPO)
        assert p.returncode == 0, p.stderr[-2000:]
        got = json.loads(p.stdout.strip().splitlines()[-1])
        assert got["tuned_config"]["fingerprint"] == fp
        assert sorted(got["tuned_config"]["applied"]) == sorted(cfg)
        assert got["prefetch"] == 6
        assert got["zero_min"] == 4096

    def test_explicit_env_shadows_stored_winner(self, tmp_path):
        self._populate(tmp_path, {"MXNET_PREFETCH_DEPTH": 6,
                                  "MXNET_ZERO_MIN_SIZE": 4096})
        p = subprocess.run(
            [sys.executable, "-c", _BOOT_SNIPPET],
            capture_output=True, text=True, timeout=180,
            env=_boot_env(tmp_path, MXNET_PREFETCH_DEPTH="9"),
            cwd=_REPO)
        assert p.returncode == 0, p.stderr[-2000:]
        got = json.loads(p.stdout.strip().splitlines()[-1])
        assert got["prefetch"] == 9          # operator's explicit env
        assert got["zero_min"] == 4096       # overlay fills the rest
        assert got["tuned_config"]["shadowed"] == \
            ["MXNET_PREFETCH_DEPTH"]

    def test_autotune_off_boots_on_defaults(self, tmp_path):
        self._populate(tmp_path, {"MXNET_ZERO_MIN_SIZE": 4096})
        p = subprocess.run(
            [sys.executable, "-c", _BOOT_SNIPPET],
            capture_output=True, text=True, timeout=180,
            env=_boot_env(tmp_path, MXNET_AUTOTUNE="0"), cwd=_REPO)
        assert p.returncode == 0, p.stderr[-2000:]
        got = json.loads(p.stdout.strip().splitlines()[-1])
        assert got["tuned_config"] is None
        assert got["zero_min"] == 2048


@pytest.mark.slow
class TestCliSweep:
    def test_quick_sweep_emits_gated_artifact_and_persists(
            self, tmp_path):
        out = tmp_path / "AUTOTUNE.json"
        store = tmp_path / "store"
        p = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools",
                                          "autotune.py"),
             "--quick", "--scenarios", "io_bound",
             "--store-dir", str(store), "--out", str(out)],
            capture_output=True, text=True, timeout=560, cwd=_REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert p.returncode == 0, (p.stdout[-2000:], p.stderr[-2000:])
        rep = json.load(open(out))
        assert rep["gate_ok"] is True
        row = rep["scenarios"]["io_bound"]
        assert row["ok"] and row["delta"] >= 0
        assert row["trajectory"] and row["trials"] >= 4
        assert "MXNET_PREFETCH_DEPTH" in row["dims"]
        # the winner is on disk and startup-matchable
        s = autotune.ConfigStore(str(store))
        e = s.best_for_startup(framework_version=mx.__version__,
                               platform="cpu")
        assert e is not None and e["config"] == row["best_config"]
