"""Monitor: tensor-stats tapping during training
(ref: python/mxnet/monitor.py).

The reference installs executor monitor callbacks on every op output.  In
the TPU design the bound graph is one fused XLA program, so interior
activations are not observable without disabling fusion; the monitor taps
the observable surface instead: parameters, gradients and head outputs of
the installed module(s).  (Interior tapping = bind the symbol's
``get_internals()`` — documented escape hatch, same as the reference's
``Symbol.get_internals`` trick.)
"""
from __future__ import annotations

import logging
import re
from typing import Callable, List, Optional, Tuple

from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval: int, stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False):
        if stat_func is None:
            def stat_func(x):
                return x.norm() / (x.size ** 0.5)  # ref default: mean |x|-ish

        self.interval = interval
        self.stat_func = stat_func
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue: List[Tuple[int, str, NDArray]] = []
        self._modules = []

    def install(self, module):
        self._modules.append(module)

    def tic(self):
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []
        self.step += 1

    def toc(self) -> List[Tuple[int, str, str]]:
        if not self.activated:
            return []
        self.activated = False
        for mod in self._modules:
            try:
                args, auxs = mod.get_params()
            except Exception:
                continue
            group = getattr(mod, "_exec_group", None)
            for name, arr in args.items():
                if self.re_pattern.match(name):
                    self.queue.append((self.step, name, self.stat_func(arr)))
            if group is not None:
                for name in list(args):
                    grads = group.grad_arrays_of(name)
                    if grads and self.re_pattern.match(name + "_grad"):
                        self.queue.append((self.step, name + "_grad",
                                           self.stat_func(grads[0])))
                try:
                    for oname, out in zip(mod.output_names,
                                          mod.get_outputs()):
                        if self.re_pattern.match(oname):
                            self.queue.append((self.step, oname,
                                               self.stat_func(out)))
                except Exception:
                    pass
        res = []
        queue = sorted(self.queue, key=lambda x: x[1]) if self.sort \
            else self.queue
        for n, k, v in queue:
            if isinstance(v, NDArray):
                v = v.asnumpy()
            res.append((n, k, str(v)))
        self.queue = []
        return res

    def toc_print(self):
        for n, k, v in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, v)
