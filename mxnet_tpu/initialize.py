"""Library initialization: crash signal handlers and fork safety.

TPU-native counterpart of the reference's `src/initialize.cc`
(SURVEY.md N17): the reference installs SIGSEGV/SIGBUS handlers that
print a C++ stack trace (gated by env `MXNET_USE_SIGNAL_HANDLER`) and
`pthread_atfork` handlers that stop the engine before a fork so a
DataLoader worker child is not born holding dead engine threads.

Here the crash-trace role is played by :mod:`faulthandler` (dumps every
Python thread's stack on SIGSEGV/SIGFPE/SIGABRT/SIGBUS/SIGILL — the
useful trace for a ctypes/XLA crash is the Python side that issued the
call), and fork safety by `os.register_at_fork` hooks installed in
:mod:`mxnet_tpu.lib`: before a fork every live native object is
quiesced (the engine drains its queues so no worker thread holds a
mutex at the fork instant), and in the child, engines are rebuilt with
fresh worker threads while file-backed readers/pipelines are
invalidated so use raises a clear MXNetError instead of crashing on a
handle whose threads/offsets did not survive.

Runs once at package import (`mxnet_tpu/__init__.py`).
"""
from __future__ import annotations

from .util import env

__all__ = ["initialize", "signal_handlers_enabled"]

_DONE = False
_FAULTHANDLER_ENABLED = False


def signal_handlers_enabled() -> bool:
    return _FAULTHANDLER_ENABLED


def initialize() -> None:
    """Idempotent library init (signal handlers + fork hooks)."""
    global _DONE, _FAULTHANDLER_ENABLED
    if _DONE:
        return
    _DONE = True
    if env.get_bool("MXNET_USE_SIGNAL_HANDLER"):
        try:
            import faulthandler

            if not faulthandler.is_enabled():
                faulthandler.enable(all_threads=True)
            _FAULTHANDLER_ENABLED = True
        except Exception:  # pragma: no cover - e.g. no usable stderr
            _FAULTHANDLER_ENABLED = False
    from . import lib

    lib.install_fork_handlers()
