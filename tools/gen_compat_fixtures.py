"""Generate the checkpoint backwards-compatibility fixtures
(tests/nightly/fixtures/): a symbolic checkpoint (prefix-symbol.json +
prefix-0001.params), a Gluon save_parameters file, Trainer optimizer
states, and an expectations JSON with exact sampled values.

Run ONCE per era and COMMIT the outputs — future rounds load these bytes
to prove the serialization formats still read older-era files (ref:
tests/nightly/model_backwards_compatibility_check).  Regenerating
overwrites the era being guarded, so only do it intentionally.

The deploy fixture's meta.json records the exporting jax version
(written by contrib.deploy.export_model): jax.export's serialized-
artifact compat window is bounded, and the nightly uses the recorded
version to tell "regenerate the fixture" (container's jax moved past
the window) from a real deserialization regression.
"""
from __future__ import annotations

import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
FIX = os.path.join(_REPO, "tests", "nightly", "fixtures")


def main():
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, model, nd, symbol as sym

    os.makedirs(FIX, exist_ok=True)
    np.random.seed(42)
    mx.random.seed(42)
    # --only-deploy adds the round-5 deploy fixture WITHOUT regenerating
    # the era-guarded checkpoint fixtures (their bytes are the point)
    only_deploy = "--only-deploy" in sys.argv
    expect = {}
    expect_path = os.path.join(FIX, "expect.json")
    if only_deploy and os.path.exists(expect_path):
        with open(expect_path) as f:
            expect = json.load(f)
    if only_deploy:
        return _gen_deploy(np, mx, gluon, nd, expect, expect_path)

    # ---- symbolic checkpoint (model.save_checkpoint format) ----
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    arg_params = {
        "fc1_weight": nd.array(np.random.randn(8, 6).astype("float32")),
        "fc1_bias": nd.array(np.random.randn(8).astype("float32")),
        "fc2_weight": nd.array(np.random.randn(4, 8).astype("float32")),
        "fc2_bias": nd.array(np.random.randn(4).astype("float32")),
    }
    prefix = os.path.join(FIX, "mlp")
    model.save_checkpoint(prefix, 1, net, arg_params, {})
    x = np.random.rand(2, 6).astype("float32")
    ex = net.bind(mx.cpu(), {"data": nd.array(x), **arg_params})
    out = ex.forward()[0].asnumpy()
    expect["symbolic"] = {
        "input": x.tolist(), "output": out.tolist(),
        "arg_sample": {k: float(v.asnumpy().ravel()[0])
                       for k, v in arg_params.items()},
    }

    # ---- gluon save_parameters ----
    gnet = gluon.nn.HybridSequential(prefix="compat_")
    with gnet.name_scope():
        gnet.add(gluon.nn.Dense(8, activation="relu", in_units=6))
        gnet.add(gluon.nn.Dense(4, in_units=8))
    gnet.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    gnet(nd.array(x))
    gpath = os.path.join(FIX, "gluon_mlp.params")
    gnet.save_parameters(gpath)
    expect["gluon"] = {"input": x.tolist(),
                       "output": gnet(nd.array(x)).asnumpy().tolist()}

    # ---- trainer optimizer states ----
    trainer = gluon.Trainer(gnet.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    with autograd.record():
        loss = (gnet(nd.array(x)) ** 2).sum()
    loss.backward()
    trainer.step(2)
    spath = os.path.join(FIX, "trainer.states")
    trainer.save_states(spath)
    expect["trainer"] = {
        "post_step_output": gnet(nd.array(x)).asnumpy().tolist()}
    gnet.save_parameters(os.path.join(FIX, "gluon_mlp_post_step.params"))

    _gen_deploy(np, mx, gluon, nd, expect,
                os.path.join(FIX, "expect.json"))
    print(f"fixtures written to {FIX}")
    return 0


def _gen_deploy(np, mx, gluon, nd, expect, expect_path):
    """Deploy artifact fixture (round 5: the versioned-StableHLO promise
    — future rounds must keep serving THESE bytes)."""
    from mxnet_tpu.contrib import deploy

    rng = np.random.RandomState(77)
    dnet = gluon.nn.HybridSequential(prefix="deployfix_")
    with dnet.name_scope():
        dnet.add(gluon.nn.Dense(8, activation="relu", in_units=6))
        dnet.add(gluon.nn.Dense(3, in_units=8))
    dnet.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    dx = rng.rand(2, 6).astype("float32")
    deploy.export_model(dnet, os.path.join(FIX, "deploy_mlp"),
                        [nd.array(dx)])
    expect["deploy"] = {"input": dx.tolist(),
                        "output": dnet(nd.array(dx)).asnumpy().tolist()}
    with open(expect_path, "w") as f:
        json.dump(expect, f, indent=1)
    print(f"deploy fixture written to {os.path.join(FIX, 'deploy_mlp')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
