"""DataParallelExecutorGroup: batch slicing across devices
(ref: python/mxnet/module/executor_group.py).

Compat-path data parallelism for the Module API: the batch is sliced
across contexts, one GraphExecutor per context, gradients aggregated by
the caller (Module.update via KVStore).  Each executor's forward/backward
is ONE async XLA dispatch, so slices overlap on device even though Python
drives them sequentially.  The TPU-idiomatic performance path is SPMD over
a Mesh (mxnet_tpu.parallel.SPMDTrainer) — this group exists for API
parity and multi-executor semantics (SURVEY.md §2d).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError
from ..context import Context
from ..io import DataDesc
from ..ndarray import NDArray
from .. import ndarray as nd

__all__ = ["DataParallelExecutorGroup"]


def _split_slice(batch_size: int, n: int):
    """Even slices of the batch axis (ref: executor_group._split_input_slice)."""
    step = (batch_size + n - 1) // n
    slices = []
    for i in range(n):
        lo = min(i * step, batch_size)
        hi = min((i + 1) * step, batch_size)
        slices.append(slice(lo, hi))
    return slices


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts: Sequence[Context], data_shapes,
                 label_shapes=None, param_names=None, for_training=True,
                 inputs_need_grad=False, fixed_param_names=None,
                 grad_req="write", logger=None):
        self.symbol = symbol
        self.contexts = list(contexts)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.param_names = list(param_names or [])
        self.fixed_param_names = set(fixed_param_names or [])

        self.data_names = [d.name for d in data_shapes]
        self.label_names = [l.name for l in (label_shapes or [])]
        self.batch_size = data_shapes[0].shape[0]
        self.slices = _split_slice(self.batch_size, len(self.contexts))

        arg_names = symbol.list_arguments()
        self.arg_names = arg_names
        self.aux_names = symbol.list_auxiliary_states()

        req: Dict[str, str] = {}
        for name in arg_names:
            if name in self.fixed_param_names:
                req[name] = "null"
            elif name in self.data_names:
                req[name] = "write" if inputs_need_grad else "null"
            elif name in self.label_names:
                req[name] = "null"
            else:
                req[name] = grad_req if for_training else "null"
        self._grad_req = req

        # infer full shapes once from the (whole-batch) descs, then rescale
        # the batch axis per slice
        shape_kwargs = {d.name: d.shape for d in data_shapes}
        shape_kwargs.update({l.name: l.shape for l in (label_shapes or [])})
        full_arg_shapes, _, full_aux_shapes = symbol.infer_shape(**shape_kwargs)
        name2shape = dict(zip(arg_names, full_arg_shapes))

        self.execs = []
        for ctx, sl in zip(self.contexts, self.slices):
            args = {}
            nslice = sl.stop - sl.start
            for name in arg_names:
                shp = list(name2shape[name])
                if name in self.data_names or name in self.label_names:
                    shp[0] = nslice
                args[name] = nd.zeros(tuple(shp), ctx=ctx)
            aux = [nd.zeros(s, ctx=ctx) for s in full_aux_shapes]
            self.execs.append(symbol.bind(ctx, args, grad_req=req,
                                          aux_states=aux))

    # ---- param sync ------------------------------------------------------
    def set_params(self, arg_params, aux_params, allow_extra=False):
        for ex in self.execs:
            ex.copy_params_from(arg_params, aux_params,
                                allow_extra_params=allow_extra)

    def get_params(self, arg_params: Dict[str, NDArray],
                   aux_params: Dict[str, NDArray]):
        """Copy averaged params out of the executors (first device wins —
        they are kept in sync by update)."""
        ex = self.execs[0]
        for name in self.param_names:
            if name in ex.arg_dict:
                arg_params[name] = ex.arg_dict[name].copy()
        for name, arr in ex.aux_dict.items():
            aux_params[name] = arr.copy()

    # ---- execution -------------------------------------------------------
    def forward(self, data_batch, is_train: Optional[bool] = None):
        if is_train is None:
            is_train = self.for_training
        for ex, sl in zip(self.execs, self.slices):
            feed = {}
            for name, arr in zip(self.data_names, data_batch.data):
                feed[name] = arr[sl]
            if is_train and data_batch.label:
                for name, arr in zip(self.label_names, data_batch.label):
                    feed[name] = arr[sl]
            ex.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        for i, (ex, sl) in enumerate(zip(self.execs, self.slices)):
            og = None
            if out_grads is not None:
                og = [g[sl] for g in out_grads]
            ex.backward(out_grads=og)

    def get_outputs(self, merge_multi_context=True):
        if merge_multi_context:
            n_out = len(self.execs[0].outputs)
            return [nd.concatenate([ex.outputs[i].as_in_context(
                self.contexts[0]) for ex in self.execs], axis=0)
                for i in range(n_out)]
        return [[ex.outputs[i] for ex in self.execs]
                for i in range(len(self.execs[0].outputs))]

    def get_input_grads(self, merge_multi_context=True):
        if not self.inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True first")
        grads = []
        for name in self.data_names:
            per_dev = [ex.grad_dict[name] for ex in self.execs]
            if merge_multi_context:
                grads.append(nd.concatenate(
                    [g.as_in_context(self.contexts[0]) for g in per_dev],
                    axis=0))
            else:
                grads.append(per_dev)
        return grads

    def grad_arrays_of(self, name: str) -> List[NDArray]:
        return [ex.grad_dict[name] for ex in self.execs
                if ex.grad_dict.get(name) is not None]

    def update_metric(self, eval_metric, labels):
        # evaluate on merged outputs vs the whole-batch labels (the
        # reference slices labels per device; merged is equivalent)
        outs = self.get_outputs(merge_multi_context=True)
        eval_metric.update(labels, outs)
