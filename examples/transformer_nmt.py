"""Transformer NMT training example (BASELINE config 5).

Synthetic sequence-to-sequence task (reverse-copy) with BUCKETED batches:
each (src_len, tgt_len) bucket compiles once (the XLA jit cache is the
executor-per-bucket design of the reference's BucketingModule) and is
reused across epochs.  The reference-era equivalent is Sockeye's train.py
/ example/rnn/bucketing.

Usage:
  python examples/transformer_nmt.py                # TPU, transformer-base
  python examples/transformer_nmt.py --cpu --small  # CPU smoke (CI)
"""
from __future__ import annotations

import argparse
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--epochs", type=int, default=3)
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import Trainer
    from mxnet_tpu.gluon.model_zoo.transformer import (LabelSmoothedCELoss,
                                                       get_transformer_model)

    ctx = mx.cpu() if args.cpu else mx.tpu(0)
    if args.small:
        args.vocab, args.batch_size = 100, 8
        net = get_transformer_model("transformer_base",
                                    src_vocab_size=args.vocab, units=32,
                                    hidden_size=64, num_layers=2,
                                    num_heads=4, max_length=32, dropout=0.0)
        buckets = [8, 12, 16]
    else:
        net = get_transformer_model("transformer_base",
                                    src_vocab_size=args.vocab,
                                    max_length=256)
        buckets = [16, 32, 64, 128]
    net.initialize(mx.initializer.Xavier(), ctx=ctx)

    loss_fn = LabelSmoothedCELoss(smoothing=0.1)
    trainer = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-3})

    rng = np.random.RandomState(0)
    BOS = 1

    def make_batch(seq_len):
        """reverse-copy task: tgt = reversed(src)."""
        b = args.batch_size
        src = rng.randint(3, args.vocab, (b, seq_len)).astype("float32")
        tgt_out = src[:, ::-1].copy()
        tgt_in = np.concatenate([np.full((b, 1), BOS), tgt_out[:, :-1]],
                                axis=1).astype("float32")
        vlen = np.full(b, seq_len, "float32")
        return (nd.array(src, ctx=ctx), nd.array(tgt_in, ctx=ctx),
                nd.array(tgt_out, ctx=ctx), nd.array(vlen, ctx=ctx))

    for epoch in range(args.epochs):
        total, tokens, tic = 0.0, 0, time.time()
        for it in range(6):
            seq_len = buckets[it % len(buckets)]  # rotate buckets
            src, tgt_in, tgt_out, vlen = make_batch(seq_len)
            with autograd.record():
                logits = net(src, tgt_in, vlen, vlen)
                loss = loss_fn(logits, tgt_out).mean()
            loss.backward()
            trainer.step(args.batch_size)
            total += float(loss.asnumpy())
            tokens += args.batch_size * seq_len
        print(f"epoch {epoch}: avg-loss={total / 6:.4f} "
              f"{tokens / (time.time() - tic):.0f} tok/s "
              f"(buckets {buckets})")


if __name__ == "__main__":
    main()
