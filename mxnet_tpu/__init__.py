"""mxnet_tpu — a TPU-native deep-learning framework with the capabilities
of the Apache-MXNet-1.x lineage reference (`fegin/mxnet`).

Imperative NDArray with device contexts (`mx.tpu()`), tape autograd, Gluon
Block/HybridBlock/Trainer with hybridize()->XLA jit, Module compat, a
KVStore lowered to XLA collectives over ICI/DCN, data pipeline, optimizers,
metrics, model zoo.  See SURVEY.md at the repo root for the full layer map.

Conventional import:  import mxnet_tpu as mx
"""
from __future__ import annotations

import os as _os

__version__ = "0.1.0"

from . import base
from .base import MXNetError
from . import util  # knob registry (util.env) — see docs/env_vars.md

# mxsan must engage BEFORE the submodule imports below so every
# module-level lock and tracked cache the framework builds is
# instrumented (enabling later only covers what is constructed later).
# Known gap: locks constructed while importing `base`/`util` above
# (e.g. the knob registry's own _LOCK) predate the patch and stay
# uninstrumented — the registry must exist to read the knob at all.
if util.env.get_bool("MXNET_SAN"):
    from .analysis import sanitizer as _mxsan

    _mxsan.enable(suppress=tuple(
        s.strip() for s in
        (util.env.get_str("MXNET_SAN_SUPPRESS") or "").split(",")
        if s.strip()))

# mxtune: apply the stored tuned knob config (if the config store has a
# matching winner) BEFORE the submodule imports below read their knobs.
# The overlay only fills knobs the process env leaves unset — explicit
# MXNET_* settings always win — and this call never raises and never
# initializes an accelerator backend.  See docs/autotune.md.
if util.env.get_bool("MXNET_AUTOTUNE"):
    from .autotune import startup as _mxtune_startup

    _mxtune_startup.apply_startup_overlay(framework_version=__version__)

from . import context
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import ops
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray, waitall
from . import autograd
from . import random
from . import profiler
from . import telemetry
from . import serialization
from . import operator
from . import storage
from . import initialize as _initialize

_initialize.initialize()

if _os.environ.get("DMLC_ROLE") == "server":
    # reference semantics: a server-role process parks inside the import
    # (kvstore_server._init_kvstore_server_module) until the tracker
    # ends the job — it must NOT fall through into the training script
    from . import kvstore_server as _kvstore_server  # noqa: F401

__all__ = [
    "MXNetError", "Context", "cpu", "gpu", "tpu", "current_context",
    "num_gpus", "num_tpus", "nd", "ndarray", "NDArray", "waitall",
    "autograd", "random", "profiler", "telemetry",
]


def __getattr__(name):
    # Subsystems that import lazily to keep `import mxnet_tpu` light and to
    # tolerate partial builds during bring-up.
    import importlib

    lazy = {"gluon", "optimizer", "initializer", "metric", "kvstore",
            "lr_scheduler", "io", "image", "symbol", "module", "parallel",
            "callback", "model", "test_utils", "engine", "runtime",
            "visualization", "recordio", "contrib", "monitor", "name", "rnn",
            "attribute", "resource", "rtc", "kvstore_server", "serving",
            "resilience", "compile_cache"}
    if name == "sym":
        mod = importlib.import_module(".symbol", __name__)
        globals()["sym"] = mod
        return mod
    if name == "kv":
        mod = importlib.import_module(".kvstore", __name__)
        globals()["kv"] = mod
        return mod
    if name == "AttrScope":
        from .attribute import AttrScope

        globals()["AttrScope"] = AttrScope
        return AttrScope
    if name in ("mod", "viz"):
        target = {"mod": "module", "viz": "visualization"}[name]
        mod = importlib.import_module(f".{target}", __name__)
        globals()[name] = mod
        return mod
    if name == "mon":
        mod = importlib.import_module(".monitor", __name__)
        globals()["mon"] = mod
        return mod
    if name in lazy:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu' has no attribute {name!r}")
