"""Basic Gluon layers (ref: python/mxnet/gluon/nn/basic_layers.py):
Sequential, HybridSequential, Dense, Dropout, BatchNorm, InstanceNorm,
LayerNorm, GroupNorm, Embedding, Flatten, Activation, LeakyReLU, PReLU,
ELU, SELU, GELU, Swish, Lambda, HybridLambda."""
from __future__ import annotations

from typing import Optional

from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "GroupNorm", "Embedding", "Flatten",
           "Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "GELU", "Swish",
           "SiLU", "Lambda", "HybridLambda", "Identity"]


class Sequential(Block):
    """Imperative stack of blocks."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def forward(self, x, *args):
        for b in self._children.values():
            x = b(x)
        return x

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def hybrid_forward(self, F, x, *args):
        for b in self._children.values():
            x = b(x)
        return x

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """ref: nn/basic_layers.py::Dense over FullyConnected."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._units = units
        self._in_units = in_units
        self._flatten = flatten
        self._use_bias = use_bias
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            self.act = Activation(activation, prefix=activation + "_") \
                if activation is not None else None

    def _infer_param_shapes(self, x, *args):
        in_units = int(x.size // x.shape[0]) if self._flatten else int(x.shape[-1])
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               flatten=self._flatten, no_bias=bias is None)
        if self.act is not None:
            out = self.act(out)
        return out


class Activation(HybridBlock):
    def __init__(self, activation, prefix=None, params=None):
        self._act_type = activation
        super().__init__(prefix, params)

    def _alias(self):
        return self._act_type or "activation"

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        self._rate = rate
        self._axes = tuple(axes)

    def hybrid_forward(self, F, x):
        if self._rate == 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)


class BatchNorm(HybridBlock):
    """ref: nn/basic_layers.py::BatchNorm. Moving stats are functional-state
    (see block.py TraceScope contract)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix, params)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,),
                init=running_mean_initializer, grad_req="null",
                allow_deferred_init=True)
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,),
                init=running_variance_initializer, grad_req="null",
                allow_deferred_init=True)

    def _infer_param_shapes(self, x, *args):
        c = int(x.shape[self._axis])
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def cast(self, dtype):
        if dtype in ("float16", "bfloat16"):
            dtype = "float32"  # keep BN stats in fp32 (matches reference)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           eps=self._epsilon, momentum=self._momentum,
                           fix_gamma=not self._scale,
                           use_global_stats=self._use_global_stats,
                           axis=self._axis,
                           _aux_params=(self.running_mean, self.running_var))


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def _infer_param_shapes(self, x, *args):
        c = int(x.shape[1])
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def _infer_param_shapes(self, x, *args):
        c = int(x.shape[self._axis])
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def _infer_param_shapes(self, x, *args):
        c = int(x.shape[1])
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix, params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.flatten(x)


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, prefix=None, params=None):
        super().__init__(prefix, params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, prefix=None, params=None):
        super().__init__(prefix, params)
        from ... import initializer as init_mod

        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(1,),
                init=alpha_initializer or init_mod.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, approximation="erf", prefix=None, params=None):
        super().__init__(prefix, params)
        self._approx = approximation

    def hybrid_forward(self, F, x):
        return F.Activation(
            x, act_type="gelu" if self._approx == "erf" else "gelu_tanh")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(x * self._beta)


SiLU = Swish


class Lambda(Block):
    def __init__(self, function, prefix=None, params=None):
        super().__init__(prefix, params)
        if isinstance(function, str):
            from ... import ndarray as nd

            function = getattr(nd, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None, params=None):
        super().__init__(prefix, params)
        self._func_name = function if isinstance(function, str) else None
        self._func = function

    def hybrid_forward(self, F, x, *args):
        if self._func_name is not None:
            return getattr(F, self._func_name)(x, *args)
        return self._func(F, x, *args)
