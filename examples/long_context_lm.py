"""Long-context causal LM with sequence-parallel attention.

Demonstrates the long-context path end-to-end: a small transformer LM
whose attention runs RING (K/V rotation, O(L/n) memory) or ULYSSES
(all-to-all head re-sharding) sequence parallelism over the 'sp' mesh
axis, trained as ONE jitted SPMD program (fwd+bwd+update) via
parallel.SPMDTrainer on a dp x sp mesh.  The reference era handled long
sequences with bucketing + grad mirroring (SURVEY §5); this is the
attention-era counterpart the task statement makes first-class.

Usage:
  python examples/long_context_lm.py                   # TPU (1 chip: sp=1)
  python examples/long_context_lm.py --cpu --sp 4      # 8 virtual devices
  python examples/long_context_lm.py --method ulysses
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--method", default="ring", choices=["ring", "ulysses"])
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--sp", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--units", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    if args.cpu:
        import os

        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_"
                                     f"count={args.dp * args.sp}")
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import numpy as np
    import mxnet_tpu as mx

    # clamp the mesh to the devices that actually exist (1-chip TPU:
    # dp=1 sp=1 — the advertised single-chip invocation)
    n_dev = jax.device_count()
    while args.dp * args.sp > n_dev and args.sp > 1:
        args.sp //= 2
    while args.dp * args.sp > n_dev and args.dp > 1:
        args.dp //= 2
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.block import HybridBlock

    U, H, V, L = args.units, args.heads, args.vocab, args.seq_len

    class SPBlock(HybridBlock):
        """Pre-LN transformer block; attention is sequence-parallel."""

        def __init__(self, method):
            super().__init__()
            self._method = method
            with self.name_scope():
                self.ln1 = nn.LayerNorm(in_channels=U)
                self.qkv = nn.Dense(3 * U, flatten=False, in_units=U)
                self.proj = nn.Dense(U, flatten=False, in_units=U)
                self.ln2 = nn.LayerNorm(in_channels=U)
                self.fc1 = nn.Dense(4 * U, flatten=False, in_units=U,
                                    activation="relu")
                self.fc2 = nn.Dense(U, flatten=False, in_units=4 * U)

        def hybrid_forward(self, F, x):
            import jax.numpy as jnp

            from mxnet_tpu.parallel import ring, ulysses

            h = self.ln1(x)
            qkv = self.qkv(h)                       # [B, L, 3U]
            b, l = qkv.shape[0], qkv.shape[1]
            q, k, v = jnp.split(qkv, 3, axis=-1)

            def heads(t):                           # [B,L,U] -> [B,H,L,D]
                return jnp.transpose(
                    t.reshape(b, l, H, U // H), (0, 2, 1, 3))

            att_fn = (ring.ring_attention_sharded if self._method == "ring"
                      else ulysses.ulysses_attention_sharded)
            o = att_fn(heads(q), heads(k), heads(v), causal=True)
            o = jnp.transpose(o, (0, 2, 1, 3)).reshape(b, l, U)
            x = x + self.proj(o)
            return x + self.fc2(self.fc1(self.ln2(x)))

    class LM(HybridBlock):
        def __init__(self, method):
            super().__init__()
            with self.name_scope():
                self.embed = nn.Embedding(V, U)
                self.blocks = nn.HybridSequential(prefix="")
                for _ in range(args.layers):
                    self.blocks.add(SPBlock(method))
                self.ln = nn.LayerNorm(in_channels=U)
                self.head = nn.Dense(V, flatten=False, in_units=U)

        def hybrid_forward(self, F, tokens, labels):
            import jax
            import jax.numpy as jnp

            x = self.blocks(self.embed(tokens))
            logits = self.head(self.ln(x))
            lsm = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            nll = -jnp.take_along_axis(
                lsm, labels[..., None].astype(jnp.int32), -1)[..., 0]
            return nll.mean()

    class _Id:
        def __call__(self, out, *a):
            return out

    np.random.seed(0)
    mx.random.seed(0)
    net = LM(args.method)
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())

    rng = np.random.RandomState(1)
    # synthetic next-token task with local structure (learnable fast)
    toks = rng.randint(4, V, (args.batch_size, L + 2)).astype(np.int32)
    toks[:, 1::2] = (toks[:, 0::2][:, :toks[:, 1::2].shape[1]] + 1) % V
    toks = toks[:, :L + 1]
    tokens, labels = toks[:, :-1], toks[:, 1:]

    mesh = parallel.make_mesh(dp=args.dp, sp=args.sp)
    with mesh:
        trainer = parallel.SPMDTrainer(net, _Id(), "adam",
                                       {"learning_rate": 3e-3}, n_labels=0)
        t_d = trainer._place(tokens, None)
        l_d = trainer._place(labels, None)
        first = last = None
        for step in range(args.steps):
            tic = time.time()
            loss = trainer.step(t_d, l_d)
            lval = float(loss.asnumpy())
            first = first if first is not None else lval
            last = lval
            print(f"step {step}: loss={lval:.4f} "
                  f"({time.time() - tic:.2f}s, {args.method}, "
                  f"dp={args.dp} sp={args.sp}, L={L})")
    print(f"loss {first:.4f} -> {last:.4f}")
    assert last < first, "no learning progress"
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
