"""Random sampling ops (ref: src/operator/random/sample_op.cc).

Each op takes an explicit threefry key as its first input (threaded by the
frontend from mxnet_tpu.random) — stateless under the hood, stateful at the
MXNet-compatible API surface.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _dt(dtype):
    return jnp.dtype(dtype if dtype not in (None, "None") else "float32")


@register_op("_random_uniform", differentiable=False, aliases=("random_uniform",))
def _uniform(key, low=0.0, high=1.0, shape=(), dtype="float32"):
    """Draw `shape` samples uniformly from the half-open interval
    [low, high)."""
    return jax.random.uniform(key, tuple(shape), _dt(dtype), low, high)


@register_op("_random_normal", differentiable=False,
             aliases=("random_normal", "normal_op"))
def _normal(key, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    """Draw `shape` samples from the normal distribution
    N(loc, scale^2)."""
    return loc + scale * jax.random.normal(key, tuple(shape), _dt(dtype))


@register_op("_random_randint", differentiable=False)
def _randint(key, low=0, high=1, shape=(), dtype="int32"):
    """Draw `shape` integers uniformly from [low, high)."""
    return jax.random.randint(key, tuple(shape), low, high, _dt(dtype))


@register_op("_random_gamma", differentiable=False)
def _gamma(key, alpha=1.0, beta=1.0, shape=(), dtype="float32"):
    """Draw `shape` samples from Gamma(alpha) scaled by `beta`
    (beta is the scale parameter, reference convention)."""
    return jax.random.gamma(key, alpha, tuple(shape), _dt(dtype)) * beta


@register_op("_random_exponential", differentiable=False)
def _exponential(key, lam=1.0, shape=(), dtype="float32"):
    """Draw `shape` samples from Exponential(lam) (rate
    parameterization: mean 1/lam)."""
    return jax.random.exponential(key, tuple(shape), _dt(dtype)) / lam


@register_op("_random_poisson", differentiable=False)
def _poisson(key, lam=1.0, shape=(), dtype="float32"):
    """Draw `shape` samples from Poisson(lam), cast to `dtype`."""
    return jax.random.poisson(key, lam, tuple(shape)).astype(_dt(dtype))


@register_op("_random_bernoulli", differentiable=False)
def _bernoulli(key, p=0.5, shape=(), dtype="float32"):
    """Draw `shape` Bernoulli(p) trials as 0/1 values in `dtype`."""
    return jax.random.bernoulli(key, p, tuple(shape)).astype(_dt(dtype))


def _multinomial_nout(attrs):
    return 2 if attrs.get("get_prob", False) else 1


@register_op("_sample_multinomial", differentiable=False,
             num_outputs=_multinomial_nout)
def _multinomial(key, data, shape=(), get_prob=False, dtype="int32"):
    """Sample category indices from the (unnormalized) distribution(s)
    in `data`; with get_prob=True also return the log-probability of
    each draw (the REINFORCE use case)."""
    logits = jnp.log(jnp.maximum(data, 1e-30))
    n = int(shape[0]) if shape else 1
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
    else:
        out = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                     shape=(data.shape[0], n))
    if shape == ():
        out = out.squeeze(-1) if data.ndim > 1 else out[0]
    sample = out.astype(_dt(dtype))
    if get_prob:
        logp = jax.nn.log_softmax(logits, axis=-1)
        if data.ndim == 1:
            lp = jnp.take(logp, out)
        else:
            lp = jnp.take_along_axis(
                logp, out.reshape(data.shape[0], -1).astype(jnp.int32),
                axis=-1).reshape(out.shape)
        return sample, lp
    return sample


@register_op("_shuffle", differentiable=False, aliases=("shuffle",))
def _shuffle(key, data):
    """Randomly permute `data` along its first axis."""
    return jax.random.permutation(key, data, axis=0)


@register_op("_random_gumbel", differentiable=False)
def _gumbel(key, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    """Draw `shape` samples from Gumbel(loc, scale)."""
    return loc + scale * jax.random.gumbel(key, tuple(shape), _dt(dtype))


@register_op("_random_laplace", differentiable=False)
def _laplace(key, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    """Draw `shape` samples from Laplace(loc, scale)."""
    return loc + scale * jax.random.laplace(key, tuple(shape), _dt(dtype))


@register_op("_random_negative_binomial", differentiable=False)
def _neg_binomial(key, k=1, p=1.0, shape=(), dtype="float32"):
    """Draw `shape` samples from NegativeBinomial(k, p) via the
    Gamma–Poisson mixture."""
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k, tuple(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, tuple(shape)).astype(_dt(dtype))


# ---------------------------------------------------------------------------
# sample_* — per-row parameterized draws: params of shape S produce
# output S + shape (ref: src/operator/random/sample_op.cc multisample)
# ---------------------------------------------------------------------------

def _multisample(key, shape, dtype, draw, *params):
    shape = tuple(shape)
    p0 = jnp.asarray(params[0])
    flat = [jnp.asarray(p).reshape(-1).astype(jnp.float32)
            for p in params]
    n = flat[0].shape[0]
    keys = jax.random.split(key, n)

    def one(k, *ps):
        return draw(k, shape, *ps)

    out = jax.vmap(one)(keys, *flat)
    return out.reshape(tuple(p0.shape) + shape).astype(_dt(dtype))


@register_op("_sample_uniform", differentiable=False,
             aliases=("sample_uniform",))
def _sample_uniform(key, low, high, shape=(), dtype="float32"):
    """Per-row uniform draws: each (low[i], high[i]) pair yields
    `shape` samples; output shape is low.shape + shape."""
    return _multisample(
        key, shape, dtype,
        lambda k, s, lo, hi: jax.random.uniform(k, s, jnp.float32, lo, hi),
        low, high)


@register_op("_sample_normal", differentiable=False,
             aliases=("sample_normal",))
def _sample_normal(key, mu, sigma, shape=(), dtype="float32"):
    """Per-row normal draws: each (mu[i], sigma[i]) pair yields
    `shape` samples; output shape is mu.shape + shape."""
    return _multisample(
        key, shape, dtype,
        lambda k, s, m, sd: m + sd * jax.random.normal(k, s),
        mu, sigma)


@register_op("_sample_gamma", differentiable=False,
             aliases=("sample_gamma",))
def _sample_gamma(key, alpha, beta, shape=(), dtype="float32"):
    """Per-row gamma draws from Gamma(alpha[i]) scaled by beta[i]
    (beta is the SCALE parameter, reference convention)."""
    return _multisample(
        key, shape, dtype,
        lambda k, s, a, b: b * jax.random.gamma(k, a, s),
        alpha, beta)


@register_op("_sample_exponential", differentiable=False,
             aliases=("sample_exponential",))
def _sample_exponential(key, lam, shape=(), dtype="float32"):
    """Per-row exponential draws with rate lam[i]; output shape is
    lam.shape + shape."""
    return _multisample(
        key, shape, dtype,
        lambda k, s, l: jax.random.exponential(k, s) / l, lam)


@register_op("_sample_poisson", differentiable=False,
             aliases=("sample_poisson",))
def _sample_poisson(key, lam, shape=(), dtype="float32"):
    """Per-row Poisson draws with rate lam[i]; output shape is
    lam.shape + shape."""
    return _multisample(
        key, shape, dtype,
        lambda k, s, l: jax.random.poisson(k, l, s).astype(jnp.float32),
        lam)


@register_op("_sample_negative_binomial", differentiable=False,
             aliases=("sample_negative_binomial",))
def _sample_negative_binomial(key, k, p, shape=(), dtype="float32"):
    """Per-row negative-binomial draws: NB(k[i], p[i]) =
    Poisson(lambda), lambda ~ Gamma(k, (1-p)/p)."""
    def draw(kk, s, kv, pv):
        k1, k2 = jax.random.split(kk)
        lam = jax.random.gamma(k1, kv, s) * (1.0 - pv) / pv
        return jax.random.poisson(k2, lam, s).astype(jnp.float32)

    return _multisample(key, shape, dtype, draw, k, p)


@register_op("_sample_generalized_negative_binomial",
             differentiable=False,
             aliases=("sample_generalized_negative_binomial",))
def _sample_gen_negative_binomial(key, mu, alpha, shape=(),
                                  dtype="float32"):
    """Per-row generalized-negative-binomial draws: GNB(mu, alpha) is
    Poisson with Gamma(1/alpha, mu*alpha) mixed rate."""
    def draw(kk, s, m, a):
        k1, k2 = jax.random.split(kk)
        lam = jax.random.gamma(k1, 1.0 / a, s) * m * a
        return jax.random.poisson(k2, lam, s).astype(jnp.float32)

    return _multisample(key, shape, dtype, draw, mu, alpha)


# ---------------------------------------------------------------------------
# _random_pdf_* — evaluate the density of samples under row-wise
# parameters (ref: src/operator/random/pdf_op.cc)
# ---------------------------------------------------------------------------

def _pdf(logpdf, sample, params, is_log):
    sample = jnp.asarray(sample, jnp.float32)
    ps = [jnp.asarray(p, jnp.float32) for p in params]
    if ps and ps[0].ndim and ps[0].ndim < sample.ndim:
        extra = sample.ndim - ps[0].ndim
        ps = [p.reshape(p.shape + (1,) * extra) for p in ps]
    out = logpdf(sample, *ps)
    return out if is_log else jnp.exp(out)


@register_op("_random_pdf_uniform", aliases=("random_pdf_uniform",))
def _pdf_uniform(sample, low, high, is_log=False):
    """Density of `sample` under Uniform(low, high), row-wise
    parameters; is_log=True returns the log-density."""
    from jax.scipy.stats import uniform as U

    return _pdf(lambda x, lo, hi: U.logpdf(x, lo, hi - lo), sample,
                (low, high), is_log)


@register_op("_random_pdf_normal", aliases=("random_pdf_normal",))
def _pdf_normal(sample, mu, sigma, is_log=False):
    """Density of `sample` under N(mu, sigma^2), row-wise parameters;
    is_log=True returns the log-density."""
    from jax.scipy.stats import norm

    return _pdf(norm.logpdf, sample, (mu, sigma), is_log)


@register_op("_random_pdf_gamma", aliases=("random_pdf_gamma",))
def _pdf_gamma(sample, alpha, beta, is_log=False):
    """Density of `sample` under Gamma(alpha, scale=beta), row-wise
    parameters; is_log=True returns the log-density."""
    from jax.scipy.stats import gamma

    return _pdf(lambda x, a, b: gamma.logpdf(x, a, scale=b), sample,
                (alpha, beta), is_log)


@register_op("_random_pdf_exponential", aliases=("random_pdf_exponential",))
def _pdf_exponential(sample, lam, is_log=False):
    """Density of `sample` under Exponential(lam) (rate
    parameterization); is_log=True returns the log-density."""
    from jax.scipy.stats import expon

    return _pdf(lambda x, l: expon.logpdf(x, scale=1.0 / l), sample,
                (lam,), is_log)


@register_op("_random_pdf_poisson", aliases=("random_pdf_poisson",))
def _pdf_poisson(sample, lam, is_log=False):
    """Probability mass of `sample` under Poisson(lam), row-wise
    parameters; is_log=True returns the log-mass."""
    from jax.scipy.stats import poisson

    return _pdf(lambda x, l: poisson.logpmf(x, l), sample, (lam,),
                is_log)


@register_op("_random_pdf_negative_binomial", aliases=("random_pdf_negative_binomial",))
def _pdf_negative_binomial(sample, k, p, is_log=False):
    """Probability mass of `sample` under NegativeBinomial(k, p),
    row-wise parameters; is_log=True returns the log-mass."""
    from jax.scipy.stats import nbinom

    return _pdf(lambda x, kv, pv: nbinom.logpmf(x, kv, pv), sample,
                (k, p), is_log)
