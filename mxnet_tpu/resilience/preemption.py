"""Preemption signal: one process-wide flag between "the machine is
going away" and "the training loop noticed".

TPU slices get preempted with a grace window (SIGTERM, then the kill).
The contract here is the smallest one that makes resume safe: a flag
that is SET asynchronously (by a real signal handler installed via
:func:`install`, or by the chaos harness's ``trainer.preempt`` action)
and OBSERVED synchronously at a step boundary by the Trainer's
auto-checkpoint hook, which saves and raises :class:`Preempted`.
Nothing is interrupted mid-step — a checkpoint is only ever cut at a
step boundary, which is what makes the resumed trajectory bit-equal to
an uninterrupted run.
"""
from __future__ import annotations

import signal as _signal
import threading
from typing import Optional

from ..base import MXNetError

__all__ = ["Preempted", "install", "trigger", "triggered", "reason",
           "trigger_time", "clear"]


class Preempted(MXNetError):
    """Raised at the step boundary after the preemption checkpoint is
    on disk.  ``checkpoint_dir`` names the saved step directory (None
    when no auto-checkpointer was attached)."""

    def __init__(self, msg: str, checkpoint_dir: Optional[str] = None):
        super().__init__(msg)
        self.checkpoint_dir = checkpoint_dir


_FLAG = threading.Event()
# RLock, not Lock: a signal handler runs ON the main thread between
# bytecodes — if it fires while clear() holds the lock, trigger() must
# re-enter rather than deadlock against its own thread
_LOCK = threading.RLock()
_REASON = [""]  # last trigger reason; writes hold _LOCK
# when the trigger fired: (time.time(), time.monotonic()) — the start
# mark of the mxgoodput preemption_recovery window (SIGTERM -> first
# post-resume step).  The unix half is persisted into the preemption
# checkpoint's meta so a FRESH process can still measure the downtime.
_TRIGGER_T: list = [None]
_INSTALLED = [False]


def install(signals=(getattr(_signal, "SIGTERM", None),)) -> None:
    """Install signal handlers that set the preemption flag (idempotent;
    main thread only — CPython restricts signal.signal to it).  The
    previous handler is chained so a supervisor's own teardown still
    runs."""
    with _LOCK:
        if _INSTALLED[0]:
            return
        _INSTALLED[0] = True
    for sig in signals:
        if sig is None:
            continue
        prev = _signal.getsignal(sig)

        def _handler(signum, frame, _prev=prev):
            trigger(reason=f"signal {signum}")
            if callable(_prev):
                _prev(signum, frame)

        _signal.signal(sig, _handler)


def trigger(reason: str = "simulated") -> None:
    """Set the flag (signal handler / chaos / tests).  The FIRST
    trigger wins both the window time and the reason: chained SIGTERM
    handlers (elastic wind-down chaining a previously installed
    preemption.install handler) re-enter trigger(), and the second
    handler's generic 'signal 15' must not overwrite the classified
    'peer-failure: ...' reason the recovery accounting routes on."""
    import time as _time

    first = False
    with _LOCK:
        if _TRIGGER_T[0] is None:  # first trigger wins
            _REASON[0] = reason
            _TRIGGER_T[0] = (_time.time(), _time.monotonic())
            first = True
    _FLAG.set()
    if first:
        from ..telemetry import mxblackbox as _bb

        if _bb._ACTIVE:
            # trigger() can run from a SIGTERM handler: the signal
            # path enqueues; nothing here takes the journal lock
            _bb.emit_from_signal("preemption",
                                 f"preemption stamp: {reason}",
                                 reason=reason)


def triggered() -> bool:
    return _FLAG.is_set()


def reason() -> str:
    return _REASON[0]


def trigger_time():
    """``(unix_seconds, monotonic_seconds)`` of the first trigger, or
    None — what opens the goodput recovery window and what the
    preemption checkpoint meta persists."""
    return _TRIGGER_T[0]


def clear() -> None:
    """Reset after a handled preemption (resume() calls this)."""
    with _LOCK:
        _REASON[0] = ""
        _TRIGGER_T[0] = None
    _FLAG.clear()
