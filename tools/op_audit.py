"""Op-surface audit: diff our registry against the reference checkout.

VERDICT r3 item 7: the reference mount (/root/reference) has been empty
every round, so no op-name diff has ever been computable.  This script
is the standing audit that runs THE MOMENT the mount appears:

    python tools/op_audit.py [--reference /root/reference] [--out OP_AUDIT.json]

With the mount empty it still writes the artifact, recording our full
op inventory (names + aliases) and `reference_empty: true`, so every
round leaves an auditable record either way.

Against a real checkout it extracts registered op names from the
reference sources — NNVM_REGISTER_OP(name) / MXNET_REGISTER_*
registrations and .add_alias("name") in src/operator/** — and reports:
  * missing: reference ops with no counterpart here (the gap list)
  * extra:   ops we register that the reference does not (beyond-parity)
Underscore-variant blindness is avoided by comparing canonicalized
names (leading '_contrib_'/'_np_' prefixes kept, case preserved).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

_REG_PATTERNS = [
    re.compile(r'NNVM_REGISTER_OP\(\s*([A-Za-z0-9_]+)\s*\)'),
    re.compile(r'MXNET_REGISTER_SIMPLE_OP\(\s*([A-Za-z0-9_]+)'),
    re.compile(r'MXNET_OPERATOR_REGISTER_[A-Z_]+\(\s*([A-Za-z0-9_]+)'),
    re.compile(r'\.add_alias\(\s*"([A-Za-z0-9_]+)"\s*\)'),
    re.compile(r'MXNET_REGISTER_OP_PROPERTY\(\s*([A-Za-z0-9_]+)'),
]


def reference_ops(ref_root):
    names = set()
    op_dir = os.path.join(ref_root, "src", "operator")
    roots = [op_dir] if os.path.isdir(op_dir) else [ref_root]
    for root in roots:
        for dirpath, _dirs, files in os.walk(root):
            for fn in files:
                if not fn.endswith((".cc", ".cu", ".h", ".cuh")):
                    continue
                try:
                    with open(os.path.join(dirpath, fn),
                              errors="replace") as f:
                        text = f.read()
                except OSError:
                    continue
                for pat in _REG_PATTERNS:
                    names.update(pat.findall(text))
    return names


def our_ops():
    from mxnet_tpu.ops.registry import get_op, list_ops

    names = set()
    for n in list_ops():
        names.add(n)
        op = get_op(n)
        names.update(getattr(op, "aliases", ()) or ())
    return names


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference")
    ap.add_argument("--out", default=os.path.join(_REPO, "OP_AUDIT.json"))
    args = ap.parse_args()

    ours = our_ops()
    empty = not (os.path.isdir(args.reference) and os.listdir(args.reference))
    report = {"when": time.strftime("%Y-%m-%d %H:%M:%S"),
              "reference": args.reference, "reference_empty": empty,
              "our_op_count": len(ours)}
    if empty:
        report["note"] = ("reference mount empty (every round so far) — "
                          "re-run this script when it appears; our full "
                          "inventory recorded below")
        report["our_ops"] = sorted(ours)
    else:
        theirs = reference_ops(args.reference)
        missing = sorted(theirs - ours)
        extra = sorted(ours - theirs)
        report.update({"reference_op_count": len(theirs),
                       "missing_count": len(missing),
                       "missing": missing, "extra_count": len(extra),
                       "extra": extra})
        print(f"reference ops: {len(theirs)}  ours: {len(ours)}  "
              f"missing: {len(missing)}  extra: {len(extra)}")
        for n in missing[:50]:
            print(f"  MISSING {n}")

    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(f"wrote {args.out} (reference_empty={empty})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
