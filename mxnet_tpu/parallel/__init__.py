"""mxnet_tpu.parallel — the distributed layer, TPU-native.

The reference (SURVEY.md §2d/§2e) is a data-parallel framework with three
gradient-sync transports behind the KVStore interface (in-process reduce —
src/kvstore/comm.h; NCCL — src/kvstore/kvstore_nccl.h; ps-lite parameter
server — src/kvstore/kvstore_dist.h) plus placement-based model parallelism
(`group2ctx` + nnvm PlaceDevice).

The TPU-native design inverts this: ONE collective substrate — XLA
collectives over ICI within a slice, DCN across slices — under explicit
`jax.sharding` annotations on a device mesh.  Modules:

  mesh      — DeviceMesh: named-axis device meshes (dp/fsdp/tp/pp/sp/ep)
  sharding  — PartitionSpec rules: regex -> spec param sharding,
              batch sharding, constraint helpers
  spmd      — SPMDTrainer: whole-training-step-in-one-XLA-program
              (forward+backward+psum+optimizer), the TPU perf path that
              subsumes Trainer+KVStore for scale-out
  dist      — multi-host bootstrap (jax.distributed) keeping the
              reference launcher's DMLC_* env contract, DCN allreduce,
              barrier
  ulysses   — all-to-all sequence parallelism (DeepSpeed-Ulysses layout)
  moe       — expert-parallel top-1 MoE over 'ep' (GShard dense dispatch)
  ring      — ring attention: sequence/context parallelism over the 'sp'
              mesh axis via shard_map + ppermute (beyond-reference)
  pipeline  — pipeline parallelism over the 'pp' axis (beyond-reference)
"""
from __future__ import annotations

from .mesh import DeviceMesh, make_mesh, current_mesh, get_mesh
from .sharding import (ShardingRules, named_sharding, replicated,
                       shard_batch, constraint, DEFAULT_RULES)
from .spmd import SPMDTrainer, functional_optimizer
from .checkpoint import save_sharded, load_sharded
from . import dist
from . import ring
from . import ulysses
from . import moe
from . import pipeline

__all__ = [
    "DeviceMesh", "make_mesh", "current_mesh", "get_mesh",
    "ShardingRules", "named_sharding", "replicated", "shard_batch",
    "constraint", "DEFAULT_RULES",
    "SPMDTrainer", "functional_optimizer",
    "dist", "ring", "ulysses", "moe", "pipeline",
]
