"""Per-device resource manager (SURVEY.md N15).

TPU-native counterpart of the reference's `src/resource.cc`
`ResourceManager` with its resource kinds:

- ``kRandom`` — per-device PRNG state.  Here: a deterministic
  :class:`~mxnet_tpu.random.KeyProvider` per :class:`Context`, derived
  by folding the device id into the root seed (stateless threefry —
  the TPU-native PRNG; no device-resident generator state to manage).
- ``kParallelRandom`` — batched keys for ops that draw many independent
  streams in one launch (the reference keeps one generator per OMP
  thread; here one folded key per lane, vectorized).
- ``kTempSpace`` — per-device scratch.  On TPU, *device* scratch is
  XLA's job (allocated inside each executable; nothing to pool), so
  the manager serves the remaining real need: reusable **host** staging
  scratch for custom ops / IO paths.  Buffers are per-(context, thread)
  and grow-only, the reference's temp-space discipline.
- ``kCuDNNDropoutDesc`` has no TPU analogue (dropout is a fused
  stateless op); requesting it raises with that explanation.
"""
from __future__ import annotations

import threading
from typing import Dict, Tuple

import numpy as np

from .base import MXNetError
from .context import Context, current_context

__all__ = ["ResourceManager", "resource_manager"]

_KINDS = ("temp_space", "random", "parallel_random")


class ResourceManager:
    """Owns per-context resources; one process-wide instance
    (``resource_manager()``)."""

    def __init__(self, root_seed: int = 0):
        self._lock = threading.Lock()
        self._root_seed = int(root_seed)
        self._rand: Dict[Tuple[str, int], "object"] = {}
        self._tls = threading.local()

    # -- kRandom ---------------------------------------------------------
    def seed(self, seed_state: int, ctx: Context = None) -> None:
        """Reseed the per-device streams (ref: MXRandomSeedContext).
        With ``ctx`` only that device's stream is reset; without, all
        streams restart from the new root.  Existing providers are reset
        IN PLACE so references already handed out follow the reseed."""
        with self._lock:
            if ctx is None:
                self._root_seed = int(seed_state)
                for key, prov in self._rand.items():
                    prov.reset(self._derive_key(key))
            else:
                key = (ctx.device_type, ctx.device_id)
                root = self._derive_key(key, root=int(seed_state))
                if key in self._rand:
                    self._rand[key].reset(root)
                else:
                    from .random import KeyProvider

                    self._rand[key] = KeyProvider(root)

    def _derive_key(self, key: Tuple[str, int], root: int = None):
        import zlib

        import jax

        root_key = jax.random.PRNGKey(
            self._root_seed if root is None else root)
        # fold device type+id in so every device gets an independent,
        # reproducible stream (ref: per-device mshadow Random seeds);
        # crc32, not hash(): stable across processes (PYTHONHASHSEED)
        folded = jax.random.fold_in(
            jax.random.fold_in(root_key,
                               zlib.crc32(key[0].encode()) & 0x7FFFFFFF),
            key[1])
        return folded

    def _make_provider(self, key: Tuple[str, int]):
        from .random import KeyProvider

        return KeyProvider(self._derive_key(key))

    def random(self, ctx: Context = None):
        """kRandom: the device's KeyProvider."""
        ctx = ctx or current_context()
        key = (ctx.device_type, ctx.device_id)
        with self._lock:
            if key not in self._rand:
                self._rand[key] = self._make_provider(key)
            return self._rand[key]

    def parallel_random(self, n: int, ctx: Context = None):
        """kParallelRandom: `n` independent keys in one draw
        (shape [n, 2] uint32)."""
        import jax

        base = self.random(ctx).next_key()
        return jax.random.split(base, int(n))

    def rng_state(self) -> dict:
        """JSON-able snapshot of every device stream's position plus
        the root seed — the checkpoint/resume contract for kRandom
        (resilience.AutoCheckpoint): a resumed job's draws continue the
        interrupted stream instead of restarting it."""
        import jax

        with self._lock:
            return {
                "root_seed": self._root_seed,
                "streams": {
                    f"{k[0]}:{k[1]}": np.asarray(
                        jax.device_get(p.get_key())).tolist()
                    for k, p in self._rand.items()},
            }

    def set_rng_state(self, state: dict) -> None:
        """Restore a :meth:`rng_state` snapshot.  Existing providers
        reset IN PLACE (handed-out references follow); streams for
        devices the snapshot has never seen derive from the restored
        root seed as usual."""
        import jax.numpy as jnp

        from .random import KeyProvider

        with self._lock:
            self._root_seed = int(state["root_seed"])
            for name, raw in state.get("streams", {}).items():
                dev_type, _, dev_id = name.rpartition(":")
                key = (dev_type, int(dev_id))
                arr = jnp.asarray(np.asarray(raw, dtype=np.uint32))
                if key in self._rand:
                    self._rand[key].reset(arr)
                else:
                    self._rand[key] = KeyProvider(arr)

    # -- kTempSpace ------------------------------------------------------
    def temp_space(self, nbytes: int, ctx: Context = None) -> np.ndarray:
        """Host staging scratch, reused across requests on the same
        (context, thread) and grown monotonically — callers must not
        assume contents survive the next request (ref temp-space
        contract).  Returns a uint8 view of length `nbytes`."""
        ctx = ctx or current_context()
        key = (ctx.device_type, ctx.device_id)
        pool = getattr(self._tls, "pool", None)
        if pool is None:
            pool = self._tls.pool = {}
        buf = pool.get(key)
        if buf is None or buf.nbytes < nbytes:
            buf = pool[key] = np.empty((max(int(nbytes), 1),), np.uint8)
        return buf[:nbytes]

    # -- generic front door (reference Resource::Request style) ----------
    def request(self, kind: str, ctx: Context = None, **kw):
        if kind == "temp_space":
            return self.temp_space(kw.get("nbytes", 0), ctx)
        if kind == "random":
            return self.random(ctx)
        if kind == "parallel_random":
            return self.parallel_random(kw.get("n", 1), ctx)
        if kind == "cudnn_dropout_desc":
            raise MXNetError(
                "resource kind 'cudnn_dropout_desc' has no TPU analogue "
                "(dropout is a fused stateless op; no descriptor state)")
        raise MXNetError(
            f"unknown resource kind {kind!r}; expected one of {_KINDS}")


_MANAGER = None
_MANAGER_LOCK = threading.Lock()


def resource_manager() -> ResourceManager:
    global _MANAGER
    if _MANAGER is None:
        with _MANAGER_LOCK:
            if _MANAGER is None:
                _MANAGER = ResourceManager()
    return _MANAGER
