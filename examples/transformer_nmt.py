"""Transformer NMT training example (BASELINE config 5).

Synthetic sequence-to-sequence task (reverse-copy) with BUCKETED batches:
each (src_len, tgt_len) bucket compiles once (the XLA jit cache is the
executor-per-bucket design of the reference's BucketingModule) and is
reused across epochs.  The reference-era equivalent is Sockeye's train.py
/ example/rnn/bucketing.

Usage:
  python examples/transformer_nmt.py                # TPU, transformer-base
  python examples/transformer_nmt.py --cpu --small  # CPU smoke (CI)
  python examples/transformer_nmt.py --src train.de --tgt train.en
      # REAL-DATA path: parallel corpus, one sentence per line; vocabs
      # built from the data, batches bucketed by source length
"""
from __future__ import annotations

import argparse
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--src", default=None,
                    help="source-language text file (one sentence/line)")
    ap.add_argument("--tgt", default=None,
                    help="target-language text file, parallel to --src")
    args = ap.parse_args()
    if bool(args.src) != bool(args.tgt):
        ap.error("--src and --tgt must be given together")

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import Trainer
    from mxnet_tpu.gluon.model_zoo.transformer import (LabelSmoothedCELoss,
                                                       get_transformer_model)

    ctx = mx.cpu() if args.cpu else mx.tpu(0)
    if args.small:
        args.vocab, args.batch_size = 100, 8
        net = get_transformer_model("transformer_base",
                                    src_vocab_size=args.vocab, units=32,
                                    hidden_size=64, num_layers=2,
                                    num_heads=4, max_length=32, dropout=0.0)
        buckets = [8, 12, 16]
    else:
        net = get_transformer_model("transformer_base",
                                    src_vocab_size=args.vocab,
                                    max_length=256)
        buckets = [16, 32, 64, 128]
    net.initialize(mx.initializer.Xavier(), ctx=ctx)

    loss_fn = LabelSmoothedCELoss(smoothing=0.1)
    trainer = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-3})

    rng = np.random.RandomState(0)
    PAD, BOS = 0, 1

    if args.src:
        # ---- real-data path: parallel corpus, length-bucketed --------
        def read_vocab(path):
            from collections import Counter

            counts = Counter()
            lines = []
            with open(path) as f:
                for line in f:
                    toks = line.split()
                    lines.append(toks)
                    counts.update(toks)
            vocab = {w: i + 3 for i, (w, _) in enumerate(
                counts.most_common(args.vocab - 3))}
            return lines, vocab

        src_lines, src_vocab = read_vocab(args.src)
        tgt_lines, tgt_vocab = read_vocab(args.tgt)
        if len(src_lines) != len(tgt_lines):
            raise SystemExit("--src/--tgt line counts differ")
        UNK = 2
        pairs = []
        for s_toks, t_toks in zip(src_lines, tgt_lines):
            s = [src_vocab.get(w, UNK) for w in s_toks]
            t = [tgt_vocab.get(w, UNK) for w in t_toks]
            if s and t and len(s) <= buckets[-1] and len(t) <= buckets[-1]:
                pairs.append((s, t))
        by_bucket = {bk: [] for bk in buckets}
        for s, t in pairs:
            bk = next(bk for bk in buckets
                      if len(s) <= bk and len(t) <= bk)
            by_bucket[bk].append((s, t))

        def batches():
            for bk, items in by_bucket.items():
                rng.shuffle(items)
                for i in range(0, len(items) - args.batch_size + 1,
                               args.batch_size):
                    chunk = items[i:i + args.batch_size]
                    b = len(chunk)
                    src = np.full((b, bk), PAD, "float32")
                    tgt_out = np.full((b, bk), PAD, "float32")
                    tgt_in = np.full((b, bk), PAD, "float32")
                    slen = np.zeros(b, "float32")
                    tlen = np.zeros(b, "float32")
                    for j, (s, t) in enumerate(chunk):
                        src[j, :len(s)] = s
                        tgt_out[j, :len(t)] = t
                        tgt_in[j, 0] = BOS
                        tgt_in[j, 1:len(t)] = t[:-1]
                        slen[j], tlen[j] = len(s), len(t)
                    # loss mask: only real target positions count (PAD
                    # would otherwise dominate long buckets)
                    mask = (np.arange(bk)[None, :]
                            < tlen[:, None]).astype("float32")
                    yield (nd.array(src, ctx=ctx),
                           nd.array(tgt_in, ctx=ctx),
                           nd.array(tgt_out, ctx=ctx),
                           nd.array(slen, ctx=ctx),
                           nd.array(tlen, ctx=ctx),
                           nd.array(mask, ctx=ctx), int(tlen.sum()))
    else:
        # ---- synthetic reverse-copy task -----------------------------
        def make_batch(seq_len):
            b = args.batch_size
            src = rng.randint(3, args.vocab, (b, seq_len)).astype("float32")
            tgt_out = src[:, ::-1].copy()
            tgt_in = np.concatenate([np.full((b, 1), BOS),
                                     tgt_out[:, :-1]],
                                    axis=1).astype("float32")
            vlen = np.full(b, seq_len, "float32")
            mask = nd.array(np.ones((b, seq_len), "float32"), ctx=ctx)
            return (nd.array(src, ctx=ctx), nd.array(tgt_in, ctx=ctx),
                    nd.array(tgt_out, ctx=ctx), nd.array(vlen, ctx=ctx),
                    nd.array(vlen, ctx=ctx), mask, b * seq_len)

        def batches():
            for it in range(6):
                yield make_batch(buckets[it % len(buckets)])

    for epoch in range(args.epochs):
        total, tokens, steps, tic = 0.0, 0, 0, time.time()
        for src, tgt_in, tgt_out, slen, tlen, mask, ntok in batches():
            with autograd.record():
                logits = net(src, tgt_in, slen, tlen)
                per = loss_fn(logits, tgt_out, mask)  # per-token (b, s)
                loss = per.sum() / nd.maximum(mask.sum(), 1.0)
            loss.backward()
            trainer.step(args.batch_size)
            total += float(loss.asnumpy())
            tokens += ntok
            steps += 1
        print(f"epoch {epoch}: avg-loss={total / max(steps, 1):.4f} "
              f"{tokens / (time.time() - tic):.0f} tok/s "
              f"(buckets {buckets})")


if __name__ == "__main__":
    main()
