"""mxhealth (ISSUE 11): in-graph numerics telemetry, anomaly
detection, the declarative alert engine, and /statusz.

Tier-1 coverage:
  * detector units — rolling median/MAD spikes (never absorbed into
    their own baseline), ratio drift, merged-trace stragglers;
  * in-graph numerics on the fused path — norms match a numpy oracle,
    the nonfinite count is exact, the fetch cadence honors
    MXNET_HEALTH_EVERY, enabling health costs exactly one recompile
    and lr changes still never recompile;
  * the three policies against a chaos-seeded NaN at a known step:
    record (detected on exactly that step), raise (NonFiniteGradient
    from that step, params at pre-step values), skip_step (detected
    once, params np.array_equal to an uninterrupted twin);
  * the same detection + bit-consistency on the SPMD mesh path;
  * the alert engine state machine (pending/for_/firing/resolved,
    gauges, quantile rules over merged histogram children);
  * GET /statusz (build info, model rows, firing alerts, drain-aware
    503);
  * the 3% health-overhead gate on the step path (mxprof-gate style).

Process-spawning e2e (2-rank straggler detection on real merged
traces, the alert soak, the real serving p99 breach) is slow-marked —
the nightly health stage runs it.
"""
import importlib.util
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, profiler, telemetry
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.resilience import chaos
from mxnet_tpu.telemetry import alerts, instruments as _ins, mxhealth
from mxnet_tpu.telemetry.mxhealth import (HealthMonitor, RollingMAD,
                                          NonFiniteGradient,
                                          ratio_drift,
                                          stragglers_from_merge)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _health_detached():
    """Every test starts and ends with mxhealth off — the other test
    files (and the fused-step executable cache signatures) depend on
    the disabled state being truly disabled."""
    mxhealth.disable()
    telemetry.disable()
    yield
    mxhealth.disable()
    telemetry.disable()
    chaos.reset_stats()


def _mlp(in_units=16, out=4, ctx=None):
    net = nn.Dense(out, in_units=in_units)
    net.initialize(ctx=ctx)
    return net


def _run(policy, inject_at=None, drop=None, steps=6, every=1,
         lr=0.01, seed=0, in_units=16):
    """Tiny fused-path training run under mxhealth; returns
    (monitor, raised, params, pre_step_params[inject_at])."""
    np.random.seed(seed)
    mx.random.seed(seed)
    net = _mlp(in_units=in_units)
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": lr, "momentum": 0.9})
    batches = [nd.array(np.random.rand(8, in_units).astype("float32"))
               for _ in range(steps)]
    mon = mxhealth.enable(policy=policy, every=every, fresh=True)
    raised = None
    pre = None
    scope = chaos.inject("trainer.numerics", at=inject_at) \
        if inject_at else None
    try:
        if scope is not None:
            scope.__enter__()
        done = 0
        for i, x in enumerate(batches):
            if drop is not None and i + 1 == drop:
                continue
            if inject_at is not None and done + 1 == inject_at:
                pre = [p.data().asnumpy()
                       for p in net.collect_params().values()]
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
            try:
                tr.step(8)
            except NonFiniteGradient as e:
                raised = e
                break
            done += 1
    finally:
        if scope is not None:
            scope.__exit__(None, None, None)
    mxhealth.flush()
    params = [p.data().asnumpy()
              for p in net.collect_params().values()]
    return mon, raised, params, pre


# ---------------------------------------------------------------------------
# detector units
# ---------------------------------------------------------------------------

class TestDetectors:
    def test_mad_warmup_then_spike(self):
        det = RollingMAD(window=32, k=6.0, min_samples=8)
        for i in range(8):
            assert det.update(1.0 + 0.01 * (i % 3)) is None
        hit = det.update(50.0)
        assert hit is not None and hit["value"] == 50.0
        assert hit["threshold"] < 50.0

    def test_spike_not_absorbed_into_baseline(self):
        """A diverging run keeps being judged against the last healthy
        window — the spike must not normalize itself."""
        det = RollingMAD(window=32, k=6.0, min_samples=8)
        for _ in range(10):
            det.update(1.0)
        assert det.update(100.0) is not None
        # still a spike on the NEXT sample: 100 was not absorbed
        assert det.update(100.0) is not None

    def test_flat_window_rel_floor(self):
        """A bit-identical warmup window (MAD == 0) must not flag the
        first femto-scale wobble."""
        det = RollingMAD(window=32, k=6.0, min_samples=8)
        for _ in range(10):
            det.update(1.0)
        assert det.update(1.0 + 1e-9) is None

    def test_ratio_drift(self):
        assert ratio_drift(0.5, 1.0, 0.1)["ratio"] == 0.5
        assert ratio_drift(0.05, 1.0, 0.1) is None
        assert ratio_drift(0.5, 0.0, 0.1) is None  # fresh zero net
        assert ratio_drift(0.5, 1.0, 0.0) is None  # disabled

    def test_stragglers_from_merge(self):
        info = {"skew": [
            {"cat": "training", "name": "backward",
             "per_rank_ms": {"0": 100.0, "1": 210.0, "2": 102.0}},
            {"cat": "training", "name": "forward",
             "per_rank_ms": {"0": 50.0, "1": 51.0, "2": 50.0}},
            {"cat": "operator", "name": "BatchNorm",
             "per_rank_ms": {"0": 10.0, "1": 40.0}},  # not a phase
        ]}
        found = stragglers_from_merge(info)
        assert len(found) == 1
        assert found[0]["rank"] == 1 and found[0]["phase"] == "backward"

    def test_straggler_min_ms_floor(self):
        """Microsecond skew on an idle box never flags."""
        info = {"skew": [{"cat": "training", "name": "backward",
                          "per_rank_ms": {"0": 0.01, "1": 0.5}}]}
        assert stragglers_from_merge(info) == []


# ---------------------------------------------------------------------------
# in-graph numerics (fused path)
# ---------------------------------------------------------------------------

class TestInGraphNumerics:
    def test_norms_match_numpy_oracle(self):
        """The in-graph grad/param norms must equal a host recompute
        from the actual gradient/weight buffers."""
        np.random.seed(0)
        net = _mlp()
        tr = Trainer(net.collect_params(), "sgd",
                     {"learning_rate": 0.05})
        x = nd.array(np.random.rand(8, 16).astype("float32"))
        mon = mxhealth.enable(policy="record", every=1, fresh=True)
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        w_before = [p.data().asnumpy()
                    for p in net.collect_params().values()]
        grads = [p.grad().asnumpy()
                 for p in net.collect_params().values()]
        tr.step(8)  # rescale_grad = 1/8
        assert mxhealth.flush()
        (s,) = mon.samples()
        gn = np.sqrt(sum(float((g ** 2).sum()) for g in grads))
        pn = np.sqrt(sum(float((w ** 2).sum()) for w in w_before))
        w_after = [p.data().asnumpy()
                   for p in net.collect_params().values()]
        un = np.sqrt(sum(float(((a - b) ** 2).sum())
                         for a, b in zip(w_after, w_before)))
        assert s["grad_norm"] == pytest.approx(gn, rel=1e-5)
        assert s["param_norm"] == pytest.approx(pn, rel=1e-5)
        assert s["update_norm"] == pytest.approx(un, rel=1e-4)
        assert s["nonfinite"] == 0

    def test_nonfinite_count_exact(self):
        """The in-graph counter reports the exact number of nonfinite
        gradient values, not just a flag."""
        np.random.seed(0)
        net = _mlp(in_units=3, out=2)  # weight (2,3) + bias (2,)
        tr = Trainer(net.collect_params(), "sgd",
                     {"learning_rate": 0.01})
        x = nd.array(np.random.rand(4, 3).astype("float32"))
        mon = mxhealth.enable(policy="record", every=1, fresh=True)
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        # poison exactly 2 values of the weight gradient
        wparam = next(iter(net.collect_params().values()))
        g = np.array(wparam.grad().asnumpy())
        g.flat[0] = np.nan
        g.flat[1] = np.inf
        wparam.grad()[:] = nd.array(g)
        tr.step(4)
        assert mxhealth.flush()
        (s,) = mon.samples()
        assert s["nonfinite"] == 2

    def test_fetch_cadence(self):
        mon, _, _, _ = _run("record", steps=7, every=3)
        assert mon.step_count() == 7
        assert [s["step"] for s in mon.samples()] == [1, 4, 7]

    def test_one_recompile_to_enable_and_lr_changes_stay_free(self):
        """Toggling health = exactly one new executable; an lr change
        with health on reuses it (the no-recompile guarantee)."""
        from mxnet_tpu.optimizer import fused as _fused

        np.random.seed(0)
        # a shape no other test uses: the executable cache is
        # process-wide and a signature collision would hide the compile
        net = _mlp(in_units=17, out=5)
        tr = Trainer(net.collect_params(), "sgd",
                     {"learning_rate": 0.05, "momentum": 0.9})
        x = nd.array(np.random.rand(8, 17).astype("float32"))

        def step():
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
            tr.step(8)

        step()  # plain program compiled
        base = _fused.compile_stats()["count"]
        mxhealth.enable(policy="record", every=1, fresh=True)
        step()  # health program: one fresh compile
        after_enable = _fused.compile_stats()["count"]
        assert after_enable == base + 1
        tr.set_learning_rate(0.001)
        step()
        step()
        assert _fused.compile_stats()["count"] == after_enable
        mxhealth.flush()

    def test_gauges_updated(self):
        _run("record", steps=3)
        assert _ins.grad_norm().value > 0
        assert _ins.param_norm().value > 0

    def test_loss_spike_detection(self):
        mon = mxhealth.enable(policy="record", fresh=True)
        for _ in range(10):
            mxhealth.observe_loss(1.0)
        mxhealth.observe_loss(500.0)
        assert mxhealth.flush()
        evs = mon.events("loss-spike")
        assert len(evs) == 1 and evs[0]["value"] == 500.0


# ---------------------------------------------------------------------------
# the three nonfinite policies (chaos trainer.numerics fixture)
# ---------------------------------------------------------------------------

class TestPolicies:
    def test_record_detects_exact_step(self):
        mon, raised, _, _ = _run("record", inject_at=3)
        assert raised is None
        evs = mon.events("nonfinite")
        # detection starts at the injected step; the NaN params then
        # cascade (that is what the record policy permits)
        assert evs[0]["step"] == 3
        assert evs[0]["action"] == "record"
        assert _ins.nonfinite_total().value > 0

    def test_raise_stops_at_exact_step_with_prestep_params(self):
        mon, raised, params, pre = _run("raise", inject_at=3)
        assert isinstance(raised, NonFiniteGradient)
        assert raised.step == 3
        # raised BEFORE writeback: params stayed at their pre-step
        # (post-step-2) values, no NaN ever landed
        assert pre is not None
        assert all(np.array_equal(a, b) for a, b in zip(params, pre))
        assert all(np.isfinite(p).all() for p in params)

    def test_skip_step_bit_consistent_with_twin(self):
        mon, raised, p_skip, _ = _run("skip_step", inject_at=3)
        assert raised is None
        evs = mon.events("nonfinite")
        # exactly ONE detection, at the injected step: the guard kept
        # the NaN out of the params, so later steps are clean
        assert [e["step"] for e in evs] == [3]
        assert evs[0]["action"] == "skip_step"
        assert mon.report()["skipped_steps"] == 1
        assert _ins.health_steps_skipped_total().value >= 1
        # the uninterrupted twin trains the same batch schedule minus
        # the corrupted batch — bit-identical params
        _, _, p_twin, _ = _run("skip_step", drop=3)
        assert all(np.array_equal(a, b)
                   for a, b in zip(p_skip, p_twin))

    def test_raise_checks_every_step_despite_cadence(self):
        """The fetch cadence must not defer the raise policy: a NaN on
        a cadence-skipped step would be written back and the raise
        would fire steps late, violating the pre-step-params promise."""
        mon, raised, params, pre = _run("raise", inject_at=2, every=5)
        assert isinstance(raised, NonFiniteGradient)
        assert raised.step == 2
        assert all(np.array_equal(a, b) for a, b in zip(params, pre))

    def test_fetch_queue_bounded(self):
        """A wedged device sync must not let the fetch queue pin
        payloads without bound — past the ring cap, new samples are
        dropped and counted."""
        stall = threading.Event()

        class _Sleepy:
            def __array__(self, *a, **k):
                stall.wait(timeout=10.0)
                return np.zeros((1,), np.float32)

        mon = HealthMonitor(policy="record", every=1, ring=8)
        try:
            for _ in range(50):
                mon.on_step("t", {"gn2": _Sleepy(), "un2": _Sleepy(),
                                  "pn2": _Sleepy(),
                                  "nonfinite": np.float32(0)})
            assert len(mon._queue) <= 8
            rep_dropped = mon._fetch_dropped
            assert rep_dropped >= 50 - 8 - 1  # one may be in flight
        finally:
            stall.set()
        assert mon.flush(timeout=30.0)
        assert mon.report()["fetch_dropped"] == rep_dropped

    def test_skip_on_off_cadence_step_still_counted(self):
        """skip_step + MXNET_HEALTH_EVERY>1: a step the in-graph guard
        rejects on a NON-sampled step must still be detected and
        counted — a silently-discarded training step would otherwise
        be invisible (verdict 'healthy').  Clean off-cadence steps
        stay out of the ring, so the cadence still bounds memory."""
        mon, raised, p_skip, _ = _run("skip_step", inject_at=3,
                                      steps=6, every=4)
        assert raised is None
        assert [e["step"] for e in mon.events("nonfinite")] == [3]
        assert mon.report()["skipped_steps"] == 1
        assert mon.verdict() == "unhealthy"
        # ring holds the cadence samples (1, 5) plus the nonfinite
        # step (3) — clean off-cadence steps were discarded unrecorded
        assert [s["step"] for s in mon.samples()] == [1, 3, 5]
        _, _, p_twin, _ = _run("skip_step", drop=3, steps=6, every=4)
        assert all(np.array_equal(a, b)
                   for a, b in zip(p_skip, p_twin))

    def test_chaos_site_counts(self):
        chaos.reset_stats()
        _run("record", inject_at=2, steps=3)
        st = chaos.stats()["trainer.numerics"]
        assert st["injected"] == 1 and st["calls"] == 3


# ---------------------------------------------------------------------------
# SPMD mesh path
# ---------------------------------------------------------------------------

def _run_spmd(policy, inject_at=None, drop=None, steps=4):
    np.random.seed(0)
    mx.random.seed(0)
    ctxs = [mx.cpu(0), mx.cpu(1)]
    net = _mlp(in_units=64, ctx=ctxs)
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.01, "momentum": 0.9}, spmd=True)
    batches = [nd.array(np.random.rand(8, 64).astype("float32"))
               for _ in range(steps)]
    mon = mxhealth.enable(policy=policy, every=1, fresh=True)
    raised = None
    scope = chaos.inject("trainer.numerics", at=inject_at) \
        if inject_at else None
    try:
        if scope is not None:
            scope.__enter__()
        for i, xg in enumerate(batches):
            if drop is not None and i + 1 == drop:
                continue
            losses = []
            with autograd.record():
                for xr, c in zip((xg[:4], xg[4:]), ctxs):
                    losses.append(
                        (net(xr.as_in_context(c)) ** 2).sum())
            for l in losses:
                l.backward()
            try:
                tr.step(8)
            except NonFiniteGradient as e:
                raised = e
                break
    finally:
        if scope is not None:
            scope.__exit__(None, None, None)
    mxhealth.flush()
    params = [p.list_data()[0].asnumpy()
              for p in net.collect_params().values()]
    return mon, raised, params


class TestSpmdHealth:
    def test_spmd_detects_on_mesh_program(self):
        mon, raised, _ = _run_spmd("record", inject_at=2)
        assert raised is None
        evs = mon.events("nonfinite")
        assert evs and evs[0]["step"] == 2
        assert evs[0]["site"] == "optimizer.spmd_step"

    def test_spmd_skip_step_bit_consistent(self):
        mon, raised, p_skip = _run_spmd("skip_step", inject_at=2)
        assert raised is None
        assert [e["step"] for e in mon.events("nonfinite")] == [2]
        assert mon.report()["skipped_steps"] == 1
        _, _, p_twin = _run_spmd("skip_step", drop=2)
        assert all(np.array_equal(a, b)
                   for a, b in zip(p_skip, p_twin))

    def test_spmd_raise_exact_step(self):
        mon, raised, params = _run_spmd("raise", inject_at=2)
        assert isinstance(raised, NonFiniteGradient)
        assert raised.step == 2
        assert all(np.isfinite(p).all() for p in params)


# ---------------------------------------------------------------------------
# alert engine
# ---------------------------------------------------------------------------

class TestAlertEngine:
    def test_fire_after_for_duration_and_clear(self):
        clock = [0.0]
        eng = alerts.AlertEngine(clock=lambda: clock[0])
        g = _ins.serving_queue_depth("alert-test", 1)
        g.set(0)
        eng.add_rule("qd", metric="mx_serving_queue_depth",
                     labels={"model": "alert-test"}, op=">",
                     threshold=5, for_=2.0, severity="warning")
        assert eng.tick() == []
        g.set(10)
        assert eng.tick() == []  # pending, inside the for-window
        assert eng.rules()[0]["state"] == "pending"
        clock[0] = 3.0
        evs = eng.tick()
        assert [e["state"] for e in evs] == ["firing"]
        assert _ins.alerts_firing("qd", "warning").value == 1
        assert _ins.alerts_total("qd", "warning").value == 1
        assert eng.firing()[0]["name"] == "qd"
        g.set(0)
        evs = eng.tick()
        assert [e["state"] for e in evs] == ["resolved"]
        assert _ins.alerts_firing("qd", "warning").value == 0
        assert eng.firing() == []
        # the event history carries the whole story as JSON
        states = [e["state"] for e in eng.events()]
        assert states == ["firing", "resolved"]
        json.dumps(eng.events())  # JSON-able contract

    def test_flap_inside_for_window_never_fires(self):
        clock = [0.0]
        eng = alerts.AlertEngine(clock=lambda: clock[0])
        g = _ins.serving_queue_depth("alert-flap", 1)
        eng.add_rule("flap", metric="mx_serving_queue_depth",
                     labels={"model": "alert-flap"}, op=">",
                     threshold=5, for_=2.0)
        g.set(10)
        eng.tick()
        g.set(0)
        eng.tick()  # recovered before for_ elapsed
        clock[0] = 5.0
        g.set(10)
        assert eng.tick() == []  # pending restarts, does not fire
        assert eng.events() == []

    def test_unborn_metric_stays_inactive(self):
        eng = alerts.AlertEngine()
        eng.add_rule("ghost", metric="mx_no_such_family", op=">",
                     threshold=0)
        assert eng.tick() == []
        assert eng.rules()[0]["state"] == "inactive"

    def test_quantile_rule_merges_children(self):
        h = _ins.serving_request_latency("alert-q", 1)
        h.reset()
        for _ in range(200):
            h.observe(0.001)
        eng = alerts.AlertEngine()
        eng.add_rule("p99", severity="page",
                     metric="p99:mx_serving_request_latency_seconds",
                     labels={"model": "alert-q"}, op=">",
                     threshold=0.025)
        assert eng.tick() == []
        for _ in range(30):
            h.observe(0.5)  # breach the tail
        evs = eng.tick()
        assert [e["state"] for e in evs] == ["firing"]
        assert evs[0]["value"] > 0.025

    def test_callable_predicate(self):
        eng = alerts.AlertEngine()
        eng.add_rule("pred", predicate=lambda m: True, severity="info")
        assert [e["rule"] for e in eng.tick()] == ["pred"]

    def test_rule_validation(self):
        eng = alerts.AlertEngine()
        with pytest.raises(mx.base.MXNetError):
            eng.add_rule("both", metric="x", predicate=lambda m: True)
        with pytest.raises(mx.base.MXNetError):
            eng.add_rule("noop", metric="x", op="~")

    def test_replace_firing_rule_clears_gauge(self):
        eng = alerts.AlertEngine()
        g = _ins.serving_queue_depth("alert-rep", 1)
        g.set(10)
        eng.add_rule("r", metric="mx_serving_queue_depth",
                     labels={"model": "alert-rep"}, op=">", threshold=1)
        eng.tick()
        assert _ins.alerts_firing("r", "warning").value == 1
        eng.add_rule("r", metric="mx_serving_queue_depth",
                     labels={"model": "alert-rep"}, op=">",
                     threshold=99)
        assert _ins.alerts_firing("r", "warning").value == 0

    def test_stock_training_rules_fire_and_resolve_on_delta(self):
        """The training rules are increase-rules over monotone
        counters: fire while the counter grows, RESOLVE when the
        growth stops (a raw-value rule would page forever after one
        transient NaN)."""
        eng = alerts.AlertEngine()
        alerts.training_health_rules(eng)
        eng.tick()  # baseline the counters
        _run("record", inject_at=2, steps=3)
        fired = {e["rule"] for e in eng.tick()}
        assert "nonfinite_gradients" in fired
        # growth stopped: the page clears instead of sticking forever
        resolved = {e["rule"] for e in eng.tick()
                    if e["state"] == "resolved"}
        assert "nonfinite_gradients" in resolved
        assert _ins.alerts_firing("nonfinite_gradients",
                                  "page").value == 0

    def test_breaker_rule_uses_max_not_sum(self):
        """Two HALF-OPEN breakers (state 1 each) must not sum into a
        fake OPEN (2)."""
        _ins.breaker_state("bk-a", 1).set(1)
        _ins.breaker_state("bk-b", 1).set(1)
        eng = alerts.AlertEngine()
        alerts.serving_slo_rules(eng)
        assert not [e for e in eng.tick()
                    if e["rule"] == "serving_breaker_open"]
        _ins.breaker_state("bk-a", 1).set(2)  # a real OPEN
        fired = {e["rule"] for e in eng.tick()}
        assert "serving_breaker_open" in fired
        _ins.breaker_state("bk-a", 1).set(0)
        _ins.breaker_state("bk-b", 1).set(0)
        eng.tick()

    def test_replace_firing_rule_pairs_resolved_event(self):
        eng = alerts.AlertEngine()
        g = _ins.serving_queue_depth("alert-pair", 1)
        g.set(10)
        eng.add_rule("pair", metric="mx_serving_queue_depth",
                     labels={"model": "alert-pair"}, op=">",
                     threshold=1)
        eng.tick()
        eng.add_rule("pair", metric="mx_serving_queue_depth",
                     labels={"model": "alert-pair"}, op=">",
                     threshold=99)
        states = [e["state"] for e in eng.events()]
        assert states == ["firing", "resolved"]

    def test_evaluate_error_holds_state_no_flap(self):
        """A transiently-failing rule must HOLD its firing state, not
        emit a spurious resolve and re-fire (a flapping page)."""
        broken = [False]

        def pred(view):
            if broken[0]:
                raise RuntimeError("transient registry hiccup")
            return True

        eng = alerts.AlertEngine()
        eng.add_rule("holdme", predicate=pred, severity="page")
        assert [e["state"] for e in eng.tick()] == ["firing"]
        broken[0] = True
        assert eng.tick() == []  # held, not resolved
        assert eng.rules()[0]["state"] == "firing"
        assert _ins.alerts_firing("holdme", "page").value == 1
        broken[0] = False
        assert eng.tick() == []  # still firing, still no transition
        assert _ins.alerts_total("holdme", "page").value == 1

    def test_background_ticker(self):
        eng = alerts.AlertEngine()
        g = _ins.serving_queue_depth("alert-tick", 1)
        g.set(10)
        eng.add_rule("tick", metric="mx_serving_queue_depth",
                     labels={"model": "alert-tick"}, op=">",
                     threshold=1)
        eng.start(interval_s=0.01)
        try:
            deadline = time.time() + 5.0
            while not eng.firing() and time.time() < deadline:
                time.sleep(0.01)
            assert eng.firing()
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# /statusz
# ---------------------------------------------------------------------------

class TestStatusz:
    @pytest.fixture()
    def served(self, tmp_path):
        from mxnet_tpu import serving
        from mxnet_tpu.contrib import deploy
        from mxnet_tpu.serving.http import serve_http

        net = _mlp(in_units=4, out=2)
        deploy.export_model(
            net, str(tmp_path),
            [nd.array(np.ones((4, 4), "float32"))],
            dynamic_batch=True)
        repo = serving.ModelRepository()
        repo.add("statusz-m", str(tmp_path))
        srv = serving.InferenceServer(
            repo, serving.ServingConfig(max_batch_size=4,
                                        batch_timeout_ms=1.0))
        httpd = serve_http(srv, port=0)
        host, port = httpd.server_address
        try:
            yield srv, f"http://{host}:{port}"
        finally:
            srv.shutdown()
            httpd.shutdown()

    def test_statusz_renders(self, served):
        srv, base = served
        srv.infer("statusz-m",
                  [nd.array(np.ones((1, 4), "float32"))])
        body = urllib.request.urlopen(f"{base}/statusz").read().decode()
        assert "mxnet_tpu statusz" in body
        assert "build:" in body and "jax=" in body
        assert "statusz-m v1" in body
        assert "alerts:" in body

    def test_statusz_shows_firing_alert(self, served):
        srv, base = served
        eng = alerts.default_engine()
        g = _ins.serving_queue_depth("statusz-alert", 1)
        g.set(10)
        eng.add_rule("statusz_demo", metric="mx_serving_queue_depth",
                     labels={"model": "statusz-alert"}, op=">",
                     threshold=1, severity="page",
                     description="statusz fixture")
        try:
            body = urllib.request.urlopen(
                f"{base}/statusz").read().decode()
            assert "FIRING [page] statusz_demo" in body
        finally:
            eng.remove_rule("statusz_demo")

    def test_statusz_drain_aware(self, served):
        srv, base = served
        srv.shutdown()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/statusz")
        assert ei.value.code == 503
        assert b"DRAINING" in ei.value.read()


# ---------------------------------------------------------------------------
# the 3% health-overhead gate (acceptance)
# ---------------------------------------------------------------------------

def test_mxhealth_overhead_within_3pct_of_disabled():
    """With mxhealth enabled at the default cadence, a fused/SPMD step
    must cost within 3% of disabled.  Same style as the mxprof gate: a
    step's XLA dispatch jitters >10% on this box, so the health DELTA
    is measured directly — the exact per-step host work health adds
    (the monitor feed with a realistic payload, queued and drained by
    the fetch thread) must cost under 3% of the measured disabled step
    wall.  (The in-graph norm reductions ride the already-dispatched
    program; on the host side the feed is the only addition.)"""
    import gc

    np.random.seed(0)
    net = _mlp()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.1, "momentum": 0.9})
    x = nd.array(np.random.rand(16, 16).astype("float32"))

    def one_step():
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        tr.step(16)
        return loss.asnumpy()

    for _ in range(5):
        one_step()
    assert not mxhealth.enabled() and not telemetry.enabled() \
        and not profiler.is_running()

    def best_window(loops, reps, fn):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(loops):
                fn()
            best = min(best, time.perf_counter() - t0)
        return best

    gc.disable()
    try:
        t_step = best_window(20, 5, one_step) / 20
        mon = mxhealth.enable(policy="record", every=1, fresh=True)
        # a realistic fused payload: per-param norm-square vectors for
        # a 50-param net + the nonfinite scalar (host numpy here — the
        # gate measures the feed/queue/ring machinery, which is the
        # per-step host cost health adds)
        payload = {"gn2": np.random.rand(50).astype("float32"),
                   "un2": np.random.rand(50).astype("float32"),
                   "pn2": np.random.rand(50).astype("float32"),
                   "nonfinite": np.float32(0.0), "guarded": False}

        def per_step_feed():
            mon.on_step("optimizer.fused_step", dict(payload))

        t_feed = best_window(2000, 7, per_step_feed) / 2000
        mon.flush()
    finally:
        gc.enable()
        mxhealth.disable()
    assert t_feed <= 0.03 * t_step, \
        (f"per-step health feed {t_feed * 1e6:.2f}us vs step "
         f"{t_step * 1e6:.1f}us — mxhealth overhead "
         f"{t_feed / t_step * 100:.2f}% exceeds the 3% budget")


# ---------------------------------------------------------------------------
# health_report tool (fast smoke; the strict run is the nightly's)
# ---------------------------------------------------------------------------

def _load_health_report():
    spec = importlib.util.spec_from_file_location(
        "health_report_under_test",
        os.path.join(_REPO, "tools", "health_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestHealthReportTool:
    def test_alert_and_straggler_stages(self):
        hr = _load_health_report()
        assert hr.stage_alert_engine()["ok"]
        st = hr.stage_straggler(None)
        assert st["ok"] and st["stragglers"][0]["rank"] == 1

    def test_committed_artifact_gates(self):
        """The committed HEALTH.json must carry a passing gate —
        perf_compare's strict lanes diff against it."""
        with open(os.path.join(_REPO, "HEALTH.json")) as f:
            rep = json.load(f)
        assert rep["gate_ok"] is True
        assert set(rep["stages"]) >= {
            "clean_run", "nonfinite_record", "nonfinite_raise",
            "nonfinite_skip", "alert_engine", "straggler"}


# ---------------------------------------------------------------------------
# nightly (slow): process-spawning e2e
# ---------------------------------------------------------------------------

_CHILD = r"""
import os, sys, time
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, profiler, telemetry
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.telemetry import tracing

rank = int(sys.argv[1])
out = sys.argv[2]
slow = rank == 1
tracing.set_rank(rank)
telemetry.enable()
net = nn.Dense(8, in_units=32)
net.initialize()
tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.01})
x = nd.array(np.random.rand(8, 32).astype("float32"))

def one_step():
    with autograd.record():
        with tracing.span("forward", cat="training"):
            out_ = net(x)
            if slow:
                time.sleep(0.12)  # the straggling rank's forward stalls
        loss = (out_ ** 2).sum()
    loss.backward()
    tr.step(8)
    loss.asnumpy()

one_step()  # warm the executables OUTSIDE the capture: first-step
one_step()  # compile wall must not masquerade as cross-rank skew
profiler.start()
for _ in range(3):
    one_step()
profiler.stop()
profiler.dump(finished=True, filename=out)
"""


@pytest.mark.slow
def test_two_rank_straggler_detection_on_merged_traces(tmp_path):
    """Real 2-process e2e: two ranks dump real training traces, rank 1
    deliberately stalls; trace_report --merge's skew table must let
    the straggler detector flag exactly rank 1."""
    paths = []
    # sequential children: on this 1-core box two concurrent ranks
    # starve each other and incidental skew (not the injected stall)
    # flags phases at random
    for rank in (0, 1):
        p = str(tmp_path / f"r{rank}.json")
        paths.append(p)
        r = subprocess.run(
            [sys.executable, "-c", _CHILD, str(rank), p],
            cwd=_REPO, capture_output=True, timeout=300)
        assert r.returncode == 0, r.stderr.decode()[-2000:]

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import trace_report as tr

    loaded = [tr.load_trace(p) for p in paths]
    _, info, errs = tr.merge_loaded(loaded)
    assert not errs
    # min_ms=50: the injected stall is 3 steps x 120ms; box noise in
    # the other (sub-ms compute) phases stays far under the floor
    found = stragglers_from_merge(info, min_ms=50.0)
    assert found, f"no straggler found in {info['skew'][:4]}"
    assert {f["rank"] for f in found} == {1}
    phases = {f["phase"] for f in found}
    assert "forward" in phases


@pytest.mark.slow
def test_health_report_tool_strict(tmp_path):
    """The nightly invocation shape: strict gate, fresh artifact."""
    out = str(tmp_path / "HEALTH.json")
    p = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools",
                                      "health_report.py"),
         "--out", out],
        capture_output=True, text=True, timeout=600, cwd=_REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
    rep = json.load(open(out))
    assert rep["gate_ok"] is True
    assert all(s["ok"] for s in rep["stages"].values())


@pytest.mark.slow
def test_alert_engine_soak():
    """Ticker soak: an oscillating metric over ~2s of 10ms ticks must
    produce exactly paired fire/resolve transitions and never strand
    the firing gauge."""
    eng = alerts.AlertEngine()
    g = _ins.serving_queue_depth("alert-soak", 1)
    g.set(0)
    eng.add_rule("soak", metric="mx_serving_queue_depth",
                 labels={"model": "alert-soak"}, op=">", threshold=5)
    eng.start(interval_s=0.01)
    try:
        for _ in range(5):
            g.set(10)
            time.sleep(0.2)
            g.set(0)
            time.sleep(0.2)
    finally:
        time.sleep(0.1)
        eng.stop()
    eng.tick()  # settle
    evs = eng.events()
    fires = [e for e in evs if e["state"] == "firing"]
    resolves = [e for e in evs if e["state"] == "resolved"]
    assert len(fires) >= 3
    assert abs(len(fires) - len(resolves)) <= 1
    assert _ins.alerts_firing("soak", "warning").value == 0
    # strict alternation: never two fires without a resolve between
    for a, b in zip(evs, evs[1:]):
        assert a["state"] != b["state"]


@pytest.mark.slow
def test_serving_p99_breach_fires_and_clears(tmp_path):
    """A real serving p99 breach: chaos-injected slow executors push
    p99 over the SLO (rule fires); a flood of fast requests pulls the
    merged-histogram p99 back under it (rule resolves)."""
    from mxnet_tpu import serving
    from mxnet_tpu.contrib import deploy

    net = _mlp(in_units=4, out=2)
    deploy.export_model(net, str(tmp_path),
                        [nd.array(np.ones((4, 4), "float32"))],
                        dynamic_batch=True)
    repo = serving.ModelRepository()
    repo.add("p99-m", str(tmp_path))
    srv = serving.InferenceServer(
        repo, serving.ServingConfig(max_batch_size=4,
                                    batch_timeout_ms=1.0))
    _ins.serving_request_latency("p99-m", 1).reset()
    eng = alerts.AlertEngine()
    eng.add_rule("p99_slo", severity="page",
                 metric="p99:mx_serving_request_latency_seconds",
                 labels={"model": "p99-m"}, op=">", threshold=0.1)
    xs = [nd.array(np.ones((1, 4), "float32"))]
    try:
        srv.infer("p99-m", xs)  # warm the executor
        with chaos.inject("serving.execute", times=4, action="hang",
                          duration=0.4):
            for _ in range(4):
                srv.infer("p99-m", xs)
        fired = eng.tick()
        assert [e["state"] for e in fired] == ["firing"], \
            f"p99 did not breach: {eng.rules()}"
        # recovery: enough fast requests that the 4 slow ones fall out
        # of the 99th percentile of the cumulative histogram
        for _ in range(600):
            srv.infer("p99-m", xs)
        resolved = eng.tick()
        assert [e["state"] for e in resolved] == ["resolved"], \
            f"p99 did not recover: {eng.rules()}"
    finally:
        srv.shutdown()
