"""Automatic mixed precision (ref: python/mxnet/contrib/amp/).

TPU-native AMP differs from the reference's fp16 recipe in one decisive
way: the half type here is **bfloat16**, which keeps fp32's exponent
range — so gradients cannot underflow the way fp16 gradients do, and
loss scaling is a NO-OP by default (scale=1).  What remains of the
reference surface:

- ``init()`` — select the target dtype (bfloat16) for subsequent
  conversions; kept for script compatibility.
- ``convert_hybrid_block(block)`` / ``convert_model(sym, arg, aux)`` —
  cast parameters to the half type while keeping normalization-layer
  params and aux stats in fp32 (the reference's FP32 "blacklist" role:
  BN/LN statistics must accumulate in full precision).
- ``scale_loss(loss, trainer)`` + ``init_trainer`` / ``unscale`` — the
  dynamic loss-scaler protocol, functional for users who explicitly ask
  for fp16-style scaling (overflow check via ``multi_all_finite``,
  growth/backoff schedule), defaulting to the bf16 no-op.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

from ..base import MXNetError

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_model", "convert_hybrid_block", "LossScaler"]

_FP32_PARAM_HINTS = ("gamma", "beta", "mean", "var", "moving", "running")

_TARGET = {"dtype": None}


def init(target_dtype: str = "bfloat16"):
    """Select the AMP half type (ref: amp.init).  float16 requests map
    to bfloat16 — the TPU-native half type."""
    if target_dtype in ("float16", "fp16"):
        target_dtype = "bfloat16"
    if target_dtype not in ("bfloat16",):
        raise MXNetError(f"amp.init: unsupported target {target_dtype!r} "
                         "(bfloat16 is the TPU half type)")
    _TARGET["dtype"] = target_dtype


def _keep_fp32(name: str) -> bool:
    return any(h in name for h in _FP32_PARAM_HINTS)


def convert_hybrid_block(block, target_dtype: str = None):
    """Cast a Block's parameters to the half type in place, keeping
    normalization params/statistics fp32 (ref: amp.convert_hybrid_block).
    Returns the block."""
    dt = target_dtype or _TARGET["dtype"] or "bfloat16"
    if dt in ("float16", "fp16"):
        dt = "bfloat16"
    for name, p in block.collect_params().items():
        if _keep_fp32(name):
            continue
        p.cast(dt)
    return block


def convert_model(sym, arg_params, aux_params, target_dtype: str = None):
    """Cast a symbolic model's arg params to the half type (aux stats and
    normalization params stay fp32) — ref: amp.convert_model.
    Returns (sym, arg_params, aux_params)."""
    dt = target_dtype or _TARGET["dtype"] or "bfloat16"
    if dt in ("float16", "fp16"):
        dt = "bfloat16"
    new_args = {k: (v if _keep_fp32(k) else v.astype(dt))
                for k, v in arg_params.items()}
    return sym, new_args, dict(aux_params)


class LossScaler:
    """Dynamic loss scaler (ref: amp/loss_scaler.py).  On bf16 the safe
    default is scale=1 (no underflow risk); the growth/backoff schedule
    is only active when constructed with an explicit init_scale > 1."""

    def __init__(self, init_scale: float = 1.0, scale_factor: float = 2.0,
                 scale_window: int = 2000):
        self.loss_scale = float(init_scale)
        self._factor = scale_factor
        self._window = scale_window
        self._unskipped = 0
        # scale=1 (the bf16 default) means DISABLED: the growth schedule
        # must never self-activate out of the documented no-op state
        self._dynamic = self.loss_scale > 1.0

    def has_overflow(self, params) -> bool:
        """True if any gradient is non-finite (multi_all_finite probe)."""
        from .. import nd

        grads = [p.grad() for p in params if p.grad_req != "null"]
        if not grads:
            return False
        ok = nd.multi_all_finite(*grads, num_arrays=len(grads))
        return float(ok.asnumpy()[0]) == 0.0

    def update_scale(self, overflow: bool):
        if not self._dynamic:
            return
        if overflow:
            self.loss_scale = max(self.loss_scale / self._factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._window:
                self.loss_scale *= self._factor
                self._unskipped = 0


def init_trainer(trainer, init_scale: float = 1.0):
    """Attach a LossScaler to a Trainer (ref: amp.init_trainer)."""
    trainer._amp_loss_scaler = LossScaler(init_scale=init_scale)


@contextmanager
def scale_loss(loss, trainer):
    """Scale the loss before backward (ref: amp.scale_loss):

        with amp.scale_loss(loss, trainer) as scaled:
            scaled.backward()
        amp.unscale(trainer)          # before trainer.step
    """
    scaler: Optional[LossScaler] = getattr(trainer, "_amp_loss_scaler",
                                           None)
    if scaler is None or scaler.loss_scale == 1.0:
        yield loss
        return
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale


def unscale(trainer):
    """Divide accumulated gradients by the loss scale and advance the
    dynamic schedule; skips the division entirely at scale=1 (bf16)."""
    scaler: Optional[LossScaler] = getattr(trainer, "_amp_loss_scaler",
                                           None)
    if scaler is None:
        return
    params = [p for p in trainer._params]
    overflow = scaler.has_overflow(params) if scaler.loss_scale != 1.0 \
        else False
    if scaler.loss_scale != 1.0:
        inv = 1.0 / scaler.loss_scale
        for p in params:
            if p.grad_req != "null":
                g = p.grad()
                g._data = g._data * inv
    scaler.update_scale(overflow)
